"""Pallas TPU flash-attention kernels (forward + backward).

Parity target: the reference's fused attention-softmax CUDA kernel PAIRS
(``smp_torch_cuda_lib``: ``scaled_masked_softmax_{forward,backward}``,
``scaled_upper_triang_softmax_{forward,backward}`` — SURVEY §2.1 N8,
dispatched from ``torch/nn/softmax.py:7-93``). The TPU design goes further
than the reference's fused softmax: a blockwise online-softmax (flash)
forward and a blockwise recompute backward, neither of which materializes
the [T, S] score matrix in HBM — scores live in VMEM one
[block_q, block_k] tile at a time.

Supported feature surface (all combinations):
  - causal and non-causal attention, T != S (cross-attention offsets);
  - windowed (local/banded) attention, causal band or symmetric band
    (reference ``torch/nn/transformer.py:1331-1352``);
  - additive key-padding bias [B, S] (the broadcastable form of HF-style
    attention masks; arbitrary [.., T, S] biases fall back to jnp);
  - dropout on the attention probabilities, replayed exactly in the
    backward via a counter-based hash RNG (no [T, S] mask materialized);
  - fp32 score math always (subsumes ``attention_in_fp32``): MXU dots run
    on the input dtype with fp32 accumulation (exact for bf16 inputs) and
    masking/softmax/rescaling stay fp32; fp32 probability/gradient tiles
    are rounded to the operand dtype for the second-stage dots (standard
    flash practice — keeps every matmul at native MXU throughput).

Backward: two passes — dq (grid over q blocks, kv streamed) and dk/dv
(grid over kv blocks, q streamed) — using the forward's saved per-row
logsumexp and the precomputed ``delta = rowsum(dO * O)``, the standard
flash-attention backward decomposition.

Layout: inputs [B, T, H, hd]; kernels run on [B*H, T, hd].
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LSE_MASKED = 1e30  # lse sentinel for fully-masked rows -> p == 0 in bwd

# Testing hook: run kernels in interpret mode even when dispatched through
# attention_core (which does not thread an interpret flag). Lets CPU tests
# exercise the real dispatch path.
FORCE_INTERPRET = False


def _dropout_keep(seed, bh, rows, cols, s_total, rate):
    """Counter-based keep mask for a [bq, bk] tile.

    lowbias32-style integer hash of the global (bh, row, col) position —
    identical bits in forward and backward, works compiled and in
    interpret mode (no pltpu PRNG state).
    """
    idx = (bh.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
           + rows.astype(jnp.uint32) * jnp.uint32(s_total)
           + cols.astype(jnp.uint32))
    x = idx + seed.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    thr = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return x >= thr


def _tile_mask(rows, cols, *, q_len, kv_len, causal, window):
    """Static structural mask for a tile given absolute row/col indices."""
    offset = kv_len - q_len
    keep = cols < kv_len
    keep &= rows < q_len
    if causal:
        keep &= cols <= rows + offset
        if window is not None:
            keep &= rows + offset - cols < window
    elif window is not None:
        keep &= jnp.abs(rows + offset - cols) < window
    return keep


def _kv_bounds(q_lo, q_hi, *, q_len, kv_len, causal, window, block_k, num_kv):
    """Traced [lo, hi) kv-block range relevant to q rows [q_lo, q_hi)."""
    offset = kv_len - q_len
    if causal:
        hi = jnp.minimum(num_kv, (q_hi - 1 + offset) // block_k + 1)
    elif window is not None:
        # Symmetric band: cols < rows + offset + window.
        hi = jnp.minimum(num_kv, (q_hi - 1 + offset + window - 1) // block_k + 1)
    else:
        hi = num_kv
    if window is not None:
        lo = jnp.maximum(0, (q_lo + offset - window + 1) // block_k)
    else:
        lo = 0
    return lo, hi


def _q_bounds(k_lo, k_hi, *, q_len, kv_len, causal, window, block_q, num_q):
    """Traced [lo, hi) q-block range relevant to kv cols [k_lo, k_hi)."""
    offset = kv_len - q_len
    lo = 0
    hi = num_q
    if causal:
        lo = jnp.maximum(0, (k_lo - offset) // block_q)
        if window is not None:
            hi = jnp.minimum(num_q, (k_hi - 1 - offset + window - 1) // block_q + 1)
    elif window is not None:
        lo = jnp.maximum(0, (k_lo - offset - window + 1) // block_q)
        hi = jnp.minimum(num_q, (k_hi - 1 - offset + window - 1) // block_q + 1)
    return lo, hi


# ----------------------------------------------------------------------
# Forward
# ----------------------------------------------------------------------

def _ids_mask(rows_loc, cols_loc, rid, cid, *, q_len, kv_len, causal, window):
    """Mask for index-vector ("ids") mode: padding by LOCAL indices,
    causal/window by the GLOBAL ids carried in the q_ids/kv_ids inputs —
    this is what lets a kernel call over one ring-attention block pair
    apply the global causal relation (including zigzag-reordered rows)."""
    keep = (rows_loc < q_len) & (cols_loc < kv_len)
    if causal:
        keep &= cid <= rid
        if window is not None:
            keep &= rid - cid < window
    elif window is not None:
        keep &= jnp.abs(rid - cid) < window
    return keep


def _ids_rmax(qid_ref, q_offset, block_q, q_len):
    """Max global row id among this program's valid q rows (for causal
    block skipping)."""
    ids = qid_ref[0, pl.ds(q_offset, block_q)][None, :]
    loc = q_offset + jax.lax.broadcasted_iota(jnp.int32, (1, block_q), 1)
    return jnp.max(jnp.where(loc < q_len, ids, -1))


def _ids_cmin(kid_ref, k_offset, block_k, kv_len):
    """Min global col id among valid kv cols of a block (for skipping)."""
    ids = kid_ref[0, pl.ds(k_offset, block_k)][None, :]
    loc = k_offset + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    return jnp.min(jnp.where(loc < kv_len, ids, jnp.int32(2**30)))


def _bh_remap(b, h_local, head_total, head0_ref):
    """Flat (batch*local_head) program index -> GLOBAL batch*head id for
    the dropout hash. Identity when heads are unsharded; under Ulysses the
    local heads are a window [head0, head0+h_local) of the global heads."""
    if head0_ref is None:
        return b
    return (
        (b // h_local) * head_total + head0_ref[0, 0] + (b % h_local)
    )


def _fwd_kernel(*refs, scale, block_q, block_k, q_len, kv_len, causal,
                window, rate, has_kpm, has_seed, s_total, has_ids=False,
                h_local=None, head_total=None, has_head0=False):
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    kpm_ref = next(it) if has_kpm else None
    seed_ref = next(it) if has_seed else None
    head0_ref = next(it) if has_head0 else None
    qid_ref = next(it) if has_ids else None
    kid_ref = next(it) if has_ids else None
    o_ref, lse_ref = next(it), next(it)

    b = pl.program_id(0)
    i = pl.program_id(1)
    # MXU operands stay in their input dtype (bf16 on the training path):
    # the v5e MXU does bf16 x bf16 -> fp32-accumulate natively, while fp32
    # matmuls decompose into multiple passes. bf16 products accumulated in
    # fp32 are exact, so post-scaling the fp32 scores keeps score math fp32
    # (N8 parity) at native throughput.
    q = q_ref[0]                                      # [bq, hd]
    hd = q.shape[-1]
    q_offset = i * block_q
    if has_ids:
        q_ids = qid_ref[0, pl.ds(q_offset, block_q)]
        r_max = _ids_rmax(qid_ref, q_offset, block_q, q_len)

    def compute(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [bq, bk]
        if scale != 1.0:
            s = s * scale
        rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if kpm_ref is not None:
            s = s + kpm_ref[0, pl.ds(j * block_k, block_k)][None, :]
        if has_ids:
            kv_ids = kid_ref[0, pl.ds(j * block_k, block_k)]
            hrows, hcols = q_ids[:, None], kv_ids[None, :]
            keep = _ids_mask(rows, cols, hrows, hcols,
                             q_len=q_len, kv_len=kv_len, causal=causal,
                             window=window)
        else:
            hrows, hcols = rows, cols
            keep = _tile_mask(rows, cols, q_len=q_len, kv_len=kv_len,
                              causal=causal, window=window)
        s = jnp.where(keep, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        if rate > 0.0:
            bh = _bh_remap(b, h_local, head_total, head0_ref)
            dkeep = _dropout_keep(seed_ref[0, 0], bh, hrows, hcols,
                                  s_total, rate)
            p = jnp.where(dkeep, p, 0.0)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    if has_ids and causal:
        # Data-dependent block skip: the static _kv_bounds cannot see the
        # global ids, so each kv block is skipped at runtime when its
        # minimum col id exceeds every row id in this q block.
        def body(j, carry):
            visible = _ids_cmin(kid_ref, j * block_k, block_k, kv_len) <= r_max
            return jax.lax.cond(
                visible, lambda c: compute(j, c), lambda c: c, carry
            )
    else:
        body = compute

    num_kv = k_ref.shape[1] // block_k
    if has_ids:
        lo, hi = 0, num_kv
    else:
        lo, hi = _kv_bounds(
            q_offset, q_offset + block_q, q_len=q_len, kv_len=kv_len,
            causal=causal, window=window, block_k=block_k, num_kv=num_kv,
        )
    acc0 = jnp.zeros((block_q, hd), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(lo, hi, body, (acc0, m0, l0))
    inv_keep = 1.0 / (1.0 - rate) if rate > 0.0 else 1.0
    o_ref[0] = (acc * inv_keep / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse = jnp.where(
        l[:, 0] > 0.0, m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-30)),
        _LSE_MASKED,
    )
    lse_ref[0] = lse[None, :]


# ----------------------------------------------------------------------
# Backward
# ----------------------------------------------------------------------

def _bwd_dq_kernel(*refs, scale, block_q, block_k, q_len, kv_len, causal,
                   window, rate, has_kpm, has_seed, s_total, has_ids=False,
                   h_local=None, head_total=None, has_head0=False):
    it = iter(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = (next(it) for _ in range(6))
    kpm_ref = next(it) if has_kpm else None
    seed_ref = next(it) if has_seed else None
    head0_ref = next(it) if has_head0 else None
    qid_ref = next(it) if has_ids else None
    kid_ref = next(it) if has_ids else None
    dq_ref = next(it)

    b = pl.program_id(0)
    i = pl.program_id(1)
    q = q_ref[0]                                      # [bq, hd] input dtype
    do = do_ref[0]
    lse = lse_ref[0, 0, :][:, None]                   # [bq, 1]
    delta = delta_ref[0, 0, :][:, None]
    q_offset = i * block_q
    inv_keep = 1.0 / (1.0 - rate) if rate > 0.0 else 1.0
    if has_ids:
        q_ids = qid_ref[0, pl.ds(q_offset, block_q)]
        r_max = _ids_rmax(qid_ref, q_offset, block_q, q_len)

    def compute(j, dq_acc):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if scale != 1.0:
            s = s * scale
        rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if kpm_ref is not None:
            s = s + kpm_ref[0, pl.ds(j * block_k, block_k)][None, :]
        if has_ids:
            kv_ids = kid_ref[0, pl.ds(j * block_k, block_k)]
            hrows, hcols = q_ids[:, None], kv_ids[None, :]
            keep = _ids_mask(rows, cols, hrows, hcols,
                             q_len=q_len, kv_len=kv_len, causal=causal,
                             window=window)
        else:
            hrows, hcols = rows, cols
            keep = _tile_mask(rows, cols, q_len=q_len, kv_len=kv_len,
                              causal=causal, window=window)
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)    # [bq, bk]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if rate > 0.0:
            bh = _bh_remap(b, h_local, head_total, head0_ref)
            dkeep = _dropout_keep(seed_ref[0, 0], bh, hrows, hcols,
                                  s_total, rate)
            dp = jnp.where(dkeep, dp * inv_keep, 0.0)
        ds = p * (dp - delta) * scale                 # d(q.k^T)
        return dq_acc + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if has_ids and causal:
        def body(j, dq_acc):
            visible = _ids_cmin(kid_ref, j * block_k, block_k, kv_len) <= r_max
            return jax.lax.cond(
                visible, lambda c: compute(j, c), lambda c: c, dq_acc
            )
    else:
        body = compute

    num_kv = k_ref.shape[1] // block_k
    if has_ids:
        lo, hi = 0, num_kv
    else:
        lo, hi = _kv_bounds(
            q_offset, q_offset + block_q, q_len=q_len, kv_len=kv_len,
            causal=causal, window=window, block_k=block_k, num_kv=num_kv,
        )
    dq0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(lo, hi, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, block_q, block_k, q_len, kv_len, causal,
                    window, rate, has_kpm, has_seed, s_total, has_ids=False,
                    h_local=None, head_total=None, has_head0=False):
    it = iter(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = (next(it) for _ in range(6))
    kpm_ref = next(it) if has_kpm else None
    seed_ref = next(it) if has_seed else None
    head0_ref = next(it) if has_head0 else None
    qid_ref = next(it) if has_ids else None
    kid_ref = next(it) if has_ids else None
    dk_ref, dv_ref = next(it), next(it)

    b = pl.program_id(0)
    j = pl.program_id(1)
    k_blk = k_ref[0]                                  # [bk, hd] input dtype
    v_blk = v_ref[0]
    k_offset = j * block_k
    inv_keep = 1.0 / (1.0 - rate) if rate > 0.0 else 1.0
    # kpm is indexed per kv block here (the block is this program's slice).
    kpm_blk = None
    if kpm_ref is not None:
        kpm_blk = kpm_ref[0, pl.ds(k_offset, block_k)][None, :]
    if has_ids:
        kv_ids = kid_ref[0, pl.ds(k_offset, block_k)]
        c_min = _ids_cmin(kid_ref, k_offset, block_k, kv_len)

    def compute(i, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [bq, bk]
        if scale != 1.0:
            s = s * scale
        rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = k_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if kpm_blk is not None:
            s = s + kpm_blk
        if has_ids:
            q_ids = qid_ref[0, pl.ds(i * block_q, block_q)]
            hrows, hcols = q_ids[:, None], kv_ids[None, :]
            keep = _ids_mask(rows, cols, hrows, hcols,
                             q_len=q_len, kv_len=kv_len, causal=causal,
                             window=window)
        else:
            hrows, hcols = rows, cols
            keep = _tile_mask(rows, cols, q_len=q_len, kv_len=kv_len,
                              causal=causal, window=window)
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if rate > 0.0:
            bh = _bh_remap(b, h_local, head_total, head0_ref)
            dkeep = _dropout_keep(seed_ref[0, 0], bh, hrows, hcols,
                                  s_total, rate)
            p_drop = jnp.where(dkeep, p * inv_keep, 0.0)
            dp = jnp.where(dkeep, dp * inv_keep, 0.0)
        else:
            p_drop = p
        dv_acc = dv_acc + jax.lax.dot_general(
            p_drop.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [bk, hd]
        ds = p * (dp - delta) * scale
        dk_acc = dk_acc + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_acc, dv_acc

    if has_ids and causal:
        def body(i, carry):
            visible = c_min <= _ids_rmax(qid_ref, i * block_q, block_q, q_len)
            return jax.lax.cond(
                visible, lambda c: compute(i, c), lambda c: c, carry
            )
    else:
        body = compute

    num_q = q_ref.shape[1] // block_q
    if has_ids:
        lo, hi = 0, num_q
    else:
        lo, hi = _q_bounds(
            k_offset, k_offset + block_k, q_len=q_len, kv_len=kv_len,
            causal=causal, window=window, block_q=block_q, num_q=num_q,
        )
    hd = k_blk.shape[-1]
    z = jnp.zeros((block_k, hd), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, hi, body, (z, z))
    # ds carries exactly one *scale factor and q_blk is raw (unscaled), so
    # dk = ds^T.q is already correct.
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ----------------------------------------------------------------------
# Host-side wrappers
# ----------------------------------------------------------------------

def resolve_blocks(block_q, block_k, default_q=256, default_k=512):
    """Block-size resolution, the ONE source of truth for every entry
    point: an explicit argument wins, else the smp config override
    (``pallas_attn_block_{q,k}``), else the per-path default."""
    from smdistributed_modelparallel_tpu.backend.state import state

    cfg = state.cfg
    if block_q is None:
        block_q = (
            getattr(cfg, "pallas_attn_block_q", None) if cfg is not None
            else None
        ) or default_q
    if block_k is None:
        block_k = (
            getattr(cfg, "pallas_attn_block_k", None) if cfg is not None
            else None
        ) or default_k
    return block_q, block_k


def _clamp_block(block, dim):
    """Clamp a block size to a sequence dim, keeping lane alignment: the
    result is min(block, dim rounded up to 128), so a short/ragged dim
    yields ONE aligned block (padded by ``_prep``) instead of a raw
    ``min`` that would hand Mosaic an unaligned (non-multiple-of-128)
    block shape for dims like 300."""
    return min(block, ((dim + 127) // 128) * 128)


def _prep(q, k, v, block_q, block_k):
    B, T, H, hd = q.shape
    S = k.shape[1]

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(B * x.shape[2], x.shape[1], hd)

    qt, kt, vt = to_bht(q), to_bht(k), to_bht(v)
    hd_pad = max(128, int(2 ** np.ceil(np.log2(hd)))) if hd % 128 else hd
    t_pad = ((T + block_q - 1) // block_q) * block_q
    s_pad = ((S + block_k - 1) // block_k) * block_k
    if hd_pad != hd or t_pad != T:
        qt = jnp.pad(qt, ((0, 0), (0, t_pad - T), (0, hd_pad - hd)))
    if hd_pad != hd or s_pad != S:
        pad = ((0, 0), (0, s_pad - S), (0, hd_pad - hd))
        kt = jnp.pad(kt, pad)
        vt = jnp.pad(vt, pad)
    return qt, kt, vt, (B, T, S, H, hd, hd_pad, t_pad, s_pad)


def _common_inputs(kpad_bias, seed, s_pad, B, H, interpret, head0=None):
    """(extra_inputs, extra_specs, has_kpm, has_seed, has_head0) shared by
    all kernels."""
    inputs, specs = [], []
    has_kpm = kpad_bias is not None
    if has_kpm:
        S = kpad_bias.shape[1]
        kpm = kpad_bias.astype(jnp.float32)
        if s_pad != S:
            kpm = jnp.pad(kpm, ((0, 0), (0, s_pad - S)), constant_values=NEG_INF)
        if kpm.shape[0] != B:
            # Broadcast batch dim: the index_map below computes b // H and
            # must never address past the array's blocks.
            kpm = jnp.broadcast_to(kpm, (B, s_pad))
        inputs.append(kpm)
        specs.append(pl.BlockSpec((1, s_pad), lambda b, i: (b // H, 0)))

    def scalar_spec():
        return pl.BlockSpec(
            (1, 1), lambda b, i: (0, 0),
            memory_space=pltpu.SMEM if not interpret else None,
        )

    has_seed = seed is not None
    if has_seed:
        inputs.append(seed.reshape(1, 1).astype(jnp.int32))
        specs.append(scalar_spec())
    has_head0 = head0 is not None
    if has_head0:
        inputs.append(jnp.asarray(head0).reshape(1, 1).astype(jnp.int32))
        specs.append(scalar_spec())
    return inputs, specs, has_kpm, has_seed, has_head0


def _ids_extra(q_ids, kv_ids, t_pad, s_pad):
    """(inputs, specs) for index-vector mode: [1, t_pad]/[1, s_pad] int32
    global row/col id arrays, broadcast to every program."""
    qi = jnp.pad(q_ids.astype(jnp.int32), (0, t_pad - q_ids.shape[0]))
    ki = jnp.pad(kv_ids.astype(jnp.int32), (0, s_pad - kv_ids.shape[0]))
    return (
        [qi[None, :], ki[None, :]],
        [
            pl.BlockSpec((1, t_pad), lambda b, i: (0, 0)),
            pl.BlockSpec((1, s_pad), lambda b, i: (0, 0)),
        ],
    )


def _flash_fwd_impl(q, k, v, kpad_bias, seed, scale, causal, window,
                    dropout_rate, block_q, block_k, interpret,
                    q_ids=None, kv_ids=None, head0=None, head_total=None,
                    counter_len=None):
    qt, kt, vt, (B, T, S, H, hd, hd_pad, t_pad, s_pad) = _prep(
        q, k, v, block_q, block_k
    )
    extra, extra_specs, has_kpm, has_seed, has_head0 = _common_inputs(
        kpad_bias, seed, s_pad, B, H, interpret, head0
    )
    has_ids = q_ids is not None
    if has_ids:
        id_in, id_specs = _ids_extra(q_ids, kv_ids, t_pad, s_pad)
        extra, extra_specs = extra + id_in, extra_specs + id_specs
    grid = (B * H, t_pad // block_q)
    kern = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        q_len=T, kv_len=S, causal=causal, window=window,
        rate=dropout_rate if has_seed else 0.0,
        has_kpm=has_kpm, has_seed=has_seed,
        s_total=counter_len if counter_len is not None else s_pad,
        has_ids=has_ids, h_local=H, head_total=head_total or H,
        has_head0=has_head0,
    )
    out, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, hd_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_pad, hd_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_pad, hd_pad), lambda b, i: (b, 0, 0)),
            *extra_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, hd_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            # ids mode feeds the ring's fp32 online-softmax merge: per-step
            # partials must not round-trip through bf16 before accumulating.
            jax.ShapeDtypeStruct(
                (B * H, t_pad, hd_pad),
                jnp.float32 if has_ids else q.dtype,
            ),
            jax.ShapeDtypeStruct((B * H, 1, t_pad), jnp.float32),
        ],
        interpret=interpret or FORCE_INTERPRET,
    )(qt, kt, vt, *extra)
    o = out[:, :T, :hd].reshape(B, H, T, hd).transpose(0, 2, 1, 3)
    return o, lse


def _flash_bwd_impl(q, k, v, o, g, lse, kpad_bias, seed, scale, causal,
                    window, dropout_rate, block_q, block_k, interpret,
                    q_ids=None, kv_ids=None, head0=None, head_total=None,
                    counter_len=None):
    qt, kt, vt, (B, T, S, H, hd, hd_pad, t_pad, s_pad) = _prep(
        q, k, v, block_q, block_k
    )
    gt = g.transpose(0, 2, 1, 3).reshape(B * H, T, hd)
    if hd_pad != hd or t_pad != T:
        gt = jnp.pad(gt, ((0, 0), (0, t_pad - T), (0, hd_pad - hd)))
    # delta = rowsum(dO * O): one fused elementwise+reduce pass in XLA.
    delta = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1).reshape(B * H, 1, T)
    if t_pad != T:
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, t_pad - T)))

    extra, extra_specs, has_kpm, has_seed, has_head0 = _common_inputs(
        kpad_bias, seed, s_pad, B, H, interpret, head0
    )
    has_ids = q_ids is not None
    if has_ids:
        id_in, id_specs = _ids_extra(q_ids, kv_ids, t_pad, s_pad)
        extra, extra_specs = extra + id_in, extra_specs + id_specs
    common = dict(
        scale=scale, block_q=block_q, block_k=block_k, q_len=T, kv_len=S,
        causal=causal, window=window,
        rate=dropout_rate if has_seed else 0.0,
        has_kpm=has_kpm, has_seed=has_seed,
        s_total=counter_len if counter_len is not None else s_pad,
        has_ids=has_ids, h_local=H, head_total=head_total or H,
        has_head0=has_head0,
    )
    res_spec_q = pl.BlockSpec((1, t_pad, hd_pad), lambda b, i: (b, 0, 0))
    row_spec = pl.BlockSpec((1, 1, t_pad), lambda b, i: (b, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(B * H, t_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, hd_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_pad, hd_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_pad, hd_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, hd_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            *extra_specs,
        ],
        out_specs=pl.BlockSpec((1, block_q, hd_pad), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (B * H, t_pad, hd_pad), jnp.float32 if has_ids else q.dtype
        ),
        interpret=interpret or FORCE_INTERPRET,
    )(qt, kt, vt, gt, lse, delta, *extra)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(B * H, s_pad // block_k),
        in_specs=[
            res_spec_q,
            pl.BlockSpec((1, block_k, hd_pad), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, hd_pad), lambda b, j: (b, j, 0)),
            res_spec_q,
            row_spec,
            row_spec,
            *extra_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, hd_pad), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, hd_pad), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            # ids mode: fp32 per-step gradients for the ring's rotating
            # accumulators (see fwd out_shape note).
            jax.ShapeDtypeStruct(
                (B * H, s_pad, hd_pad), jnp.float32 if has_ids else k.dtype
            ),
            jax.ShapeDtypeStruct(
                (B * H, s_pad, hd_pad), jnp.float32 if has_ids else v.dtype
            ),
        ],
        interpret=interpret or FORCE_INTERPRET,
    )(qt, kt, vt, gt, lse, delta, *extra)

    def from_bht(x, L):
        return x[:, :L, :hd].reshape(B, H, L, hd).transpose(0, 2, 1, 3)

    return from_bht(dq, T), from_bht(dk, S), from_bht(dv, S)


# ----------------------------------------------------------------------
# custom_vjp surface
# ----------------------------------------------------------------------

@functools.partial(
    jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12, 13, 14)
)
def flash_attention(q, k, v, kpad_bias=None, seed=None, head0=None,
                    scale=None, causal=True, window=None, dropout_rate=0.0,
                    block_q=None, block_k=None, interpret=False,
                    head_total=None, counter_len=None):
    """Flash attention over [B, T, H, hd] q and [B, S, H, hd] k/v.

    ``kpad_bias``: additive float [B, S] bias (0 keep / -1e30 drop for
    boolean masks). ``seed``: int32 scalar array enabling dropout at
    ``dropout_rate``. ``head0``/``head_total``/``counter_len``: GLOBAL
    dropout-hash coordinates for head-sharded callers (Ulysses) — the
    local heads hash as window [head0, head0+H) of ``head_total`` global
    heads, with ``counter_len`` as the row-stride (defaults reproduce the
    local hash, bh = flat program index, stride = padded S). Fully-masked
    rows produce an undefined (zero-ish) output, matching
    softmax-of-all-masked degeneracy in the jnp path.
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    block_q, block_k = resolve_blocks(block_q, block_k)
    block_q = _clamp_block(block_q, q.shape[1])
    block_k = _clamp_block(block_k, k.shape[1])
    o, _ = _flash_fwd_impl(q, k, v, kpad_bias, seed, scale, causal, window,
                           dropout_rate, block_q, block_k, interpret,
                           head0=head0, head_total=head_total,
                           counter_len=counter_len)
    return o


def _fa_fwd(q, k, v, kpad_bias, seed, head0, scale, causal, window,
            dropout_rate, block_q, block_k, interpret, head_total,
            counter_len):
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    block_q, block_k = resolve_blocks(block_q, block_k)
    block_q = _clamp_block(block_q, q.shape[1])
    block_k = _clamp_block(block_k, k.shape[1])
    o, lse = _flash_fwd_impl(q, k, v, kpad_bias, seed, scale, causal, window,
                             dropout_rate, block_q, block_k, interpret,
                             head0=head0, head_total=head_total,
                             counter_len=counter_len)
    return o, (q, k, v, o, lse, kpad_bias, seed, head0)


def _fa_bwd(scale, causal, window, dropout_rate, block_q, block_k, interpret,
            head_total, counter_len, res, g):
    q, k, v, o, lse, kpad_bias, seed, head0 = res
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    block_q, block_k = resolve_blocks(block_q, block_k)
    block_q = _clamp_block(block_q, q.shape[1])
    block_k = _clamp_block(block_k, k.shape[1])
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, o, g, lse, kpad_bias, seed, scale, causal, window,
        dropout_rate, block_q, block_k, interpret,
        head0=head0, head_total=head_total, counter_len=counter_len,
    )
    return dq, dk, dv, None, None, None


flash_attention.defvjp(_fa_fwd, _fa_bwd)


# ----------------------------------------------------------------------
# Index-vector ("ids") entry points — building blocks for ring attention
# ----------------------------------------------------------------------
#
# These are NOT custom_vjp surfaces: the ring-attention executor
# (ops/context_parallel.py) owns the differentiation, calling the forward
# per KV ring step (merging partials with the online-softmax rule) and the
# backward per step with the GLOBAL logsumexp — the standard blockwise
# flash decomposition distributed over the cp ring. q_ids / kv_ids carry
# the global sequence positions of the local blocks, which is what makes
# causal masking correct under the zigzag re-layout (non-contiguous rows).
# Dropout is not supported in ids mode (the ring falls back to the jnp
# path when attention dropout is active).


def _lse_to_rows(lse_raw, B, H, T):
    """Kernel-layout lse [B*H, 1, t_pad] -> [B, H, T]."""
    return lse_raw[:, 0, :T].reshape(B, H, T)


def _rows_to_lse(lse, t_pad):
    """[B, H, T] -> kernel layout [B*H, 1, t_pad] (padded with the masked
    sentinel so padded rows contribute p == 0 in the backward)."""
    B, H, T = lse.shape
    out = lse.reshape(B * H, 1, T)
    if t_pad != T:
        out = jnp.pad(out, ((0, 0), (0, 0), (0, t_pad - T)),
                      constant_values=_LSE_MASKED)
    return out


def flash_fwd_with_ids(q, k, v, kpad_bias, q_ids, kv_ids, *, scale, causal,
                       seed=None, dropout_rate=0.0, counter_len=None,
                       block_q=None, block_k=None, interpret=False,
                       head0=None, head_total=None):
    """One blockwise forward over a (q block, kv block) pair.

    Dropout hashes on the GLOBAL ids (rows/cols from q_ids/kv_ids, stride
    ``counter_len``; ``head0``/``head_total`` remap head-sharded callers'
    local heads to global ids, as in ``flash_attention``) so the pattern
    matches the jnp ring/Ulysses bodies bit for bit. Returns (o
    [B, T, H, hd] fp32-normalized per-block output, lse [B, H, T] with
    +_LSE_MASKED sentinel on fully-masked rows).
    """
    block_q, block_k = resolve_blocks(block_q, block_k, default_k=256)
    block_q = _clamp_block(block_q, q.shape[1])
    block_k = _clamp_block(block_k, k.shape[1])
    o, lse = _flash_fwd_impl(
        q, k, v, kpad_bias, seed, scale, causal, None, dropout_rate,
        block_q, block_k, interpret, q_ids=q_ids, kv_ids=kv_ids,
        counter_len=counter_len, head0=head0, head_total=head_total,
    )
    B, T, H = q.shape[0], q.shape[1], q.shape[2]
    return o, _lse_to_rows(lse, B, H, T)


def flash_bwd_with_ids(q, k, v, o, g, lse, kpad_bias, q_ids, kv_ids, *,
                       scale, causal, seed=None, dropout_rate=0.0,
                       counter_len=None, block_q=None, block_k=None,
                       interpret=False, head0=None, head_total=None):
    """Blockwise backward for one (q block, kv block) pair given the GLOBAL
    per-row logsumexp ``lse`` [B, H, T] (+_LSE_MASKED sentinel rows) and
    the GLOBAL output ``o`` / cotangent ``g``. Returns (dq, dk, dv)."""
    block_q, block_k = resolve_blocks(block_q, block_k, default_k=256)
    block_q = _clamp_block(block_q, q.shape[1])
    block_k = _clamp_block(block_k, k.shape[1])
    t_pad = ((q.shape[1] + block_q - 1) // block_q) * block_q
    lse_raw = _rows_to_lse(lse, t_pad)
    return _flash_bwd_impl(
        q, k, v, o, g, lse_raw, kpad_bias, seed, scale, causal, None,
        dropout_rate, block_q, block_k, interpret, q_ids=q_ids,
        kv_ids=kv_ids, counter_len=counter_len, head0=head0,
        head_total=head_total,
    )
