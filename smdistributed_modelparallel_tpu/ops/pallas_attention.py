"""Pallas TPU flash-attention kernel.

Parity target: the reference's fused attention-softmax CUDA kernels
(``smp_torch_cuda_lib``: ``scaled_upper_triang_softmax_{forward,backward}``,
SURVEY §2.1 N8, dispatched from ``torch/nn/softmax.py:15-93``). The TPU
design goes further than the reference's fused softmax: a blockwise
online-softmax (flash) forward that never materializes the [T, T] score
matrix in HBM — scores live in VMEM one [block_q, block_k] tile at a time,
and causally-masked-out tiles are skipped entirely.

Backward is recompute-based (``jax.custom_vjp``): the standard softmax
transpose in plain jnp, which XLA fuses; the forward's memory saving is the
flash win, matching how the reference pairs its fused forward with an
explicit backward kernel.

Layout: inputs [B, T, H, hd]; the kernel runs on [B*H, T, hd] with grid
(B*H, T/block_q), k/v resident in VMEM per (batch, head) — the dispatch gate
(``ops/attention.py::_pallas_ok``) bounds T so k/v fit VMEM.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, block_q, block_k, seq_len):
    """One q block vs all (causally relevant) kv blocks, online softmax."""
    i = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, hd]
    hd = q.shape[-1]
    q_offset = i * block_q

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [bq, bk]
        rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = (cols <= rows) & (cols < seq_len)
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    # Causal: kv blocks beyond this q block's diagonal are all-masked; skip.
    num_kv = (q_offset + block_q + block_k - 1) // block_k
    acc0 = jnp.zeros((block_q, hd), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, block_q, block_k, interpret):
    B, T, H, hd = q.shape
    # [B, T, H, hd] -> [B*H, T, hd]
    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, hd)

    qt, kt, vt = to_bht(q), to_bht(k), to_bht(v)
    hd_pad = max(128, int(2 ** np.ceil(np.log2(hd)))) if hd % 128 else hd
    t_pad = ((T + block_q - 1) // block_q) * block_q
    if hd_pad != hd or t_pad != T:
        pad = ((0, 0), (0, t_pad - T), (0, hd_pad - hd))
        qt = jnp.pad(qt, pad)
        kt = jnp.pad(kt, pad)
        vt = jnp.pad(vt, pad)

    grid = (B * H, t_pad // block_q)
    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
            seq_len=T,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, hd_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t_pad, hd_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t_pad, hd_pad), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, hd_pad), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, t_pad, hd_pad), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    out = out[:, :T, :hd].reshape(B, H, T, hd).transpose(0, 2, 1, 3)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, scale=None, block_q=256, block_k=256,
                    interpret=False):
    """Causal flash attention over [B, T, H, hd] (self-attention, T == S)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    return _flash_fwd(q, k, v, scale, block_q, block_k, interpret)


def _fa_fwd(q, k, v, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _fa_bwd(scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    # Recompute-based backward: standard softmax transpose, fused by XLA.
    from smdistributed_modelparallel_tpu.ops.attention import causal_window_mask

    qf, kf, vf, gf = (x.astype(jnp.float32) for x in (q, k, v, g))
    s = jnp.einsum("bthd,bshd->bhts", qf, kf) * scale
    T = q.shape[1]
    mask = causal_window_mask(T, T)
    s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bhts,bthd->bshd", p, gf)
    dp = jnp.einsum("bthd,bshd->bhts", gf, vf)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    ds = jnp.where(mask[None, None], ds, 0.0) * scale
    dq = jnp.einsum("bhts,bshd->bthd", ds, kf)
    dk = jnp.einsum("bhts,bthd->bshd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
