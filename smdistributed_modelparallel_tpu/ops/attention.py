"""Attention compute core.

Parity target: reference attention math in ``DistributedAttentionLayer``
(``torch/nn/transformer.py:1352-1444``) and the fused softmax kernels it
dispatches to (``torch/nn/softmax.py``, ``can_use_fused_kernel``
``torch/nn/transformer.py:83-112``, SURVEY §2.1 N8).

TPU-native design: one functional entry point ``attention_core`` over
[B, T, H, hd] tensors. Dispatch order:
  1. Pallas flash-attention kernel (TPU, shapes tile, no bias/dropout) —
     never materializes the [T, S] score matrix;
  2. jnp path — XLA fuses scale+mask+softmax into one HBM pass.
Ring-attention context parallelism (M6) wraps this core with a ppermute
loop over KV blocks.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def causal_window_mask(T, S, window=None, dtype=jnp.bool_):
    """[T, S] lower-triangular mask, optionally banded to ``window``.

    Parity: causal-mask buffer + windowed attention
    (``torch/nn/transformer.py:1331-1352``).
    """
    rows = jnp.arange(T)[:, None]
    cols = jnp.arange(S)[None, :]
    offset = S - T
    mask = cols <= rows + offset
    if window is not None:
        mask = mask & (rows + offset - cols < window)
    return mask.astype(dtype)


def _fold_scale_and_seed(q, scale, dropout_rate, dropout_rng):
    """Shared prologue of the Pallas and CP fast paths: fold a traced scale
    into q (their scale arguments are static; keep q's dtype so a traced
    f32 scalar cannot promote bf16 q), and derive the int32 dropout seed
    from the rng — one definition, so the ring/Ulysses/Pallas dropout
    patterns cannot silently diverge."""
    if isinstance(scale, (int, float, np.floating)):
        qq, static_scale = q, float(scale)
    else:
        qq, static_scale = (q * scale).astype(q.dtype), 1.0
    seed = None
    rate = 0.0
    if dropout_rate > 0.0 and dropout_rng is not None:
        rate = float(dropout_rate)
        seed = jax.lax.bitcast_convert_type(
            jax.random.bits(dropout_rng, (), jnp.uint32), jnp.int32
        )
    return qq, static_scale, seed, rate


def attention_core(
    q,
    k,
    v,
    *,
    causal: bool = True,
    window: Optional[int] = None,
    local_select=None,
    scale: Optional[float] = None,
    extra_scale=None,
    qk_compensation=None,
    bias=None,
    mask=None,
    mask_value: float = -1e4,
    attention_in_fp32: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng=None,
    use_pallas: bool = True,
):
    """Multi-head attention over [B, T, H, hd] q and [B, S, H, hd] k/v.

    Args:
      causal/window: static masking (window = local attention band).
      local_select: optional traced bool scalar — when given, the window
        band applies only if True (per-layer local/global selection under
        ``lax.scan``, GPT-Neo ``attention_layers_type``).
      scale: score scale; default 1/sqrt(hd). Applied to q BEFORE the
        matmul so half-precision scores cannot overflow.
      extra_scale: optional traced scalar multiplier on the scale
        (scale_attn_by_layer_idx).
      qk_compensation: optional traced scalar c — q is pre-scaled by 1/c
        before the matmul and the fp32 scores multiplied back by c
        (parity: reference query_key_layer_scaling, a numerics-only
        protection for half-precision score matmuls,
        ``torch/nn/transformer.py:1804-1836``).
      bias: additive [B|1, H|1, T, S] bias (e.g. relative position).
      mask: additive or boolean attention mask broadcastable to
        [B, 1, T, S] (True/0 = keep).
      mask_value: additive value for masked positions (parity: reference
        ``mask_value`` key, default -1e4).
    Returns: [B, T, H, hd].
    """
    hd = q.shape[-1]
    if scale is None:
        scale = 1.0 / np.sqrt(hd)
    if extra_scale is not None:
        scale = scale * extra_scale

    # Context parallelism (M6): sequence sharded over the cp mesh axis ->
    # ring / Ulysses manual regions. Real-model features (key-padding
    # masks, attention dropout) are supported in-region; unsupported
    # combinations (windows, rich biases, per-layer local selection) fall
    # through to the GSPMD path (allgather-KV semantics).
    from smdistributed_modelparallel_tpu.ops.context_parallel import cp_size

    cp_kpad = _as_key_padding_bias(mask, mask_value) if cp_size() > 1 else None
    if (
        cp_size() > 1
        and bias is None
        and (mask is None or cp_kpad is not None)
        and local_select is None
        and window is None
        and q.shape[1] == k.shape[1]
        and q.shape[1] % cp_size() == 0
        # The in-region flash kernels share _pallas_ok's mixed-dtype
        # restriction (MXU dots run on the operand dtype).
        and q.dtype == k.dtype == v.dtype
    ):
        from smdistributed_modelparallel_tpu.backend.state import state
        from smdistributed_modelparallel_tpu.ops.context_parallel import (
            cp_attention,
        )

        impl = state.cfg.context_parallel_impl
        if impl in ("ring", "ulysses"):
            qq, static_scale, seed, rate = _fold_scale_and_seed(
                q, scale, dropout_rate, dropout_rng
            )
            return cp_attention(
                qq, k, v, scale=static_scale, causal=causal, impl=impl,
                kpad=cp_kpad, dropout_rate=rate, seed=seed,
            )

    kpad = (
        cp_kpad if cp_kpad is not None else _as_key_padding_bias(mask, mask_value)
    )
    if (
        use_pallas
        and _pallas_ok(q, k, v)
        and bias is None
        and (mask is None or kpad is not None)
        and local_select is None
        # attention_in_fp32 / qk_compensation need no special handling: the
        # kernel's score math is always fp32 (N8 parity, and then some).
    ):
        from smdistributed_modelparallel_tpu.ops.pallas_attention import (
            flash_attention,
        )

        qq, kernel_scale, seed, rate = _fold_scale_and_seed(
            q, scale, dropout_rate, dropout_rng
        )
        # Block sizes resolve inside the kernel entry (explicit arg ->
        # pallas_attn_block_{q,k} config -> default).
        return flash_attention(
            qq, k, v, kpad, seed, None, kernel_scale, causal, window, rate
        )

    T, S = q.shape[1], k.shape[1]
    compute_dtype = jnp.float32 if attention_in_fp32 else q.dtype
    # Pre-scale q so the half-precision score matmul cannot overflow
    # (reference applies the norm factor inside the baddbmm alpha).
    pre = jnp.asarray(scale, jnp.float32)
    if qk_compensation is not None:
        pre = pre / qk_compensation
    qc = (q.astype(jnp.float32) * pre).astype(compute_dtype)
    kc = k.astype(compute_dtype)
    scores = jnp.einsum("bthd,bshd->bhts", qc, kc).astype(jnp.float32)
    if qk_compensation is not None:
        scores = scores * qk_compensation

    if causal:
        cmask = causal_window_mask(T, S)
        if window is not None:
            if local_select is not None:
                wmask = causal_window_mask(T, S, window)
                cmask = jnp.where(local_select, wmask, cmask)
            else:
                cmask = causal_window_mask(T, S, window)
        scores = jnp.where(cmask[None, None], scores, mask_value)
    elif window is not None:
        # Non-causal local attention: symmetric band of width `window`.
        rows = jnp.arange(T)[:, None]
        cols = jnp.arange(S)[None, :]
        band = jnp.abs(rows + (S - T) - cols) < window
        scores = jnp.where(band[None, None], scores, mask_value)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, mask_value)
        else:
            scores = scores + mask.astype(scores.dtype)
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)

    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def _as_key_padding_bias(mask, mask_value):
    """Reduce a broadcastable attention mask to additive [B, S] form, or
    None if it genuinely varies along T (falls back to the jnp path).

    Accepts [B|1, 1, 1, S] boolean or additive-float masks — the shape of
    HF-style padding masks (reference ``attention_mask`` handling)."""
    if mask is None:
        return None
    if mask.ndim == 2:  # already [B, S]
        reduced = mask
    elif mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
        reduced = mask[:, 0, 0, :]
    else:
        return None
    if reduced.dtype == jnp.bool_:
        return jnp.where(reduced, 0.0, mask_value).astype(jnp.float32)
    return reduced.astype(jnp.float32)


def _pallas_ok(q, k, v):
    """Pallas flash kernel preconditions: TPU backend and q/kv sequences
    short enough that K/V (dq pass) or Q/dO (dkv pass) fit VMEM per
    (batch, head) — the kernels pad hd/T/S to tile boundaries themselves
    (``pallas_attention._prep``)."""
    import os

    if os.environ.get("SMP_DISABLE_PALLAS_ATTN", "0") == "1":
        return False
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        return False
    if not (q.dtype == k.dtype == v.dtype):
        # Kernel MXU dots run on the operand dtype (no fp32 upcast), so
        # mixed q/k/v dtypes would fail at trace time — jnp path handles
        # them via its own promotion.
        return False
    T, S, hd = q.shape[1], k.shape[1], q.shape[-1]
    return T >= 128 and S >= 128 and T <= 8192 and S <= 8192 and hd <= 256
