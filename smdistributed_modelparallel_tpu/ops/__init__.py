"""Compute ops: Pallas kernels and collective wrappers."""
