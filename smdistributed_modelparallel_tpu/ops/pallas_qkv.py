"""Fused QKV projection Pallas kernel: matmul with the bias folded into
the epilogue.

The attention layers already express QKV as ONE einsum against a
concatenated [in, 3, heads, head_dim] kernel, but XLA still emits the
bias add as a separate HBM pass over the [*, 3*H*hd] result on shapes it
declines to fuse. This kernel computes ``y = x @ w + b`` tile-by-tile on
the MXU with the bias added while the tile is VMEM-resident — one pass
over the output. Under ``tp_overlap: ring`` the same kernel runs INSIDE
the ring's partial matmuls (``ops/collective_matmul._chunk_mm``), so the
"ring + fusions" rung stacks both wins; on the GSPMD tp path the sharded
weight cannot enter a plain ``pallas_call`` without a gather, so
dispatch there keeps the einsum (``fused_qkv_ok``).

Backward is the standard dense triple (dx = dy @ w^T, dw = x^T @ dy,
db = sum(dy)) as plain XLA matmuls — exact, no recompute trade — behind
a ``custom_vjp`` so the forward kernel never gets differentiated
through. Interpret-mode fallback on CPU mirrors ``pallas_ce.py``
(``FORCE_INTERPRET`` test hook); dispatch off-TPU without it falls back
to the jnp path with a counted decision
(``smp_fused_kernel_dispatch_total``).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Testing hook, mirroring pallas_ce.FORCE_INTERPRET.
FORCE_INTERPRET = False

_VMEM_BUDGET = 12 * 2**20

# (rows, cols) tile candidates, large-first; shrink cols before rows so
# wide contractions (large D) keep a fitting configuration.
_BLOCK_CANDIDATES = (
    (256, 512), (256, 256), (128, 256), (128, 128), (64, 128), (32, 128),
)


def _step_bytes(D, bn, bf):
    # fp32 in-kernel copies: x tile + w tile + y tile (+ bias row).
    return 4 * (bn * D + bf * D + bn * bf + bf)


def _auto_blocks(D):
    for bn, bf in _BLOCK_CANDIDATES:
        if _step_bytes(D, bn, bf) <= _VMEM_BUDGET:
            return bn, bf
    return None


def _mm_bias_kernel(*refs, has_bias):
    it = iter(refs)
    x_ref, w_ref = next(it), next(it)
    b_ref = next(it) if has_bias else None
    y_ref = next(it)
    x = x_ref[...].astype(jnp.float32)                   # [bn, D]
    w = w_ref[...].astype(jnp.float32)                   # [D, bf]
    y = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if has_bias:
        y = y + b_ref[...].astype(jnp.float32)           # [1, bf]
    y_ref[...] = y.astype(y_ref.dtype)


def _pad_to(x, n, axis):
    if x.shape[axis] == n:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, n - x.shape[axis])
    return jnp.pad(x, pads)


def _matmul_bias_impl(x, w, b, interpret):
    N, D = x.shape
    F = w.shape[1]
    blocks = _auto_blocks(D)
    if blocks is None:
        # No tile fits VMEM at this contraction width (fused_qkv_ok
        # steers dispatch away; direct callers get the same math unfused
        # rather than an unpack crash).
        y = x.astype(jnp.float32) @ w.astype(jnp.float32)
        if b is not None:
            y = y + b.astype(jnp.float32)
        return y.astype(x.dtype)
    bn, bf = blocks
    # Few-row calls (decode steps: N = batch) must not pad to the full
    # row tile — cap bn at N rounded to the 32-sublane granule (valid
    # for every dtype's TPU tiling) so a batch-8 decode QKV runs 32
    # rows, not 256.
    bn = min(bn, max(32, -(-N // 32) * 32))
    n_pad = -(-N // bn) * bn
    f_pad = -(-F // bf) * bf
    xp = _pad_to(x, n_pad, 0)
    wp = _pad_to(w, f_pad, 1)
    has_bias = b is not None
    args = [xp, wp]
    in_specs = [
        pl.BlockSpec((bn, D), lambda i, j: (i, 0)),
        pl.BlockSpec((D, bf), lambda i, j: (0, j)),
    ]
    if has_bias:
        args.append(_pad_to(b.reshape(1, F), f_pad, 1))
        in_specs.append(pl.BlockSpec((1, bf), lambda i, j: (0, j)))
    y = pl.pallas_call(
        functools.partial(_mm_bias_kernel, has_bias=has_bias),
        grid=(n_pad // bn, f_pad // bf),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bn, bf), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), x.dtype),
        interpret=interpret or FORCE_INTERPRET,
    )(*args)
    return y[:N, :F]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _matmul_bias(x, w, b, interpret):
    return _matmul_bias_impl(x, w, b, interpret)


def _mb_fwd(x, w, b, interpret):
    return _matmul_bias_impl(x, w, b, interpret), (x, w, b is not None)


def _mb_bwd(interpret, res, dy):
    x, w, had_bias = res
    dyf = dy.astype(jnp.float32)
    dx = (dyf @ w.astype(jnp.float32).T).astype(x.dtype)
    dw = (x.astype(jnp.float32).T @ dyf).astype(w.dtype)
    db = jnp.sum(dyf, axis=0).astype(dy.dtype) if had_bias else None
    return dx, dw, db


_matmul_bias.defvjp(_mb_fwd, _mb_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _matmul_nobias(x, w, interpret):
    return _matmul_bias_impl(x, w, None, interpret)


_matmul_nobias.defvjp(
    lambda x, w, interpret: (_matmul_bias_impl(x, w, None, interpret),
                             (x, w)),
    lambda interpret, res, dy: _mb_bwd(interpret, res + (False,), dy)[:2],
)


def matmul_bias(x, w, b=None, *, interpret=False):
    """``x [N, D] @ w [D, F] (+ b [F])`` through the fused Pallas kernel
    (bias in the matmul epilogue, one output pass). Differentiable in
    x/w/b; the backward is exact plain-XLA matmuls."""
    if b is not None:
        return _matmul_bias(x, w, b.reshape(-1), interpret)
    return _matmul_nobias(x, w, interpret)


def _mm_fp8_kernel(x_ref, w_ref, y_ref):
    # Operands stay f8 INTO the dot — the MXU consumes them natively on
    # f8-capable TPUs; preferred_element_type pins the f32 accumulator.
    y_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def matmul_bias_fp8(x8, w8, *, interpret=False):
    """The fp8 rung of this kernel ladder (matmul_precision: fp8):
    ``x8 [N, D] @ w8 [D, F] -> f32`` with e4m3 operand refs — the
    delayed-scaling dequant multiply and the bias add stay in the XLA
    epilogue (``quant._fp8_mm2d``), keeping the kernel a pure f8 MXU
    pass. Not differentiable on its own: the caller's custom_vjp owns
    the e5m2 backward. The (32, 128) floor of ``_BLOCK_CANDIDATES``
    satisfies the f8 minimum tile; an unfittable contraction width
    falls back to the plain f8 dot (same operands, XLA-tiled)."""
    N, D = x8.shape
    F = w8.shape[1]
    blocks = _auto_blocks(D)
    if blocks is None:
        return jax.lax.dot_general(
            x8, w8, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    bn, bf = blocks
    bn = min(bn, max(32, -(-N // 32) * 32))
    n_pad = -(-N // bn) * bn
    f_pad = -(-F // bf) * bf
    xp = _pad_to(x8, n_pad, 0)
    wp = _pad_to(w8, f_pad, 1)
    y = pl.pallas_call(
        _mm_fp8_kernel,
        grid=(n_pad // bn, f_pad // bf),
        in_specs=[
            pl.BlockSpec((bn, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, bf), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bn, bf), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        interpret=interpret or FORCE_INTERPRET,
    )(xp, wp)
    return y[:N, :F]


def fused_qkv_ok(D, ring=False, tp=1):
    """Dispatch precondition for the fused QKV projection: the knob's
    target backend (TPU, or interpret-mode testing), a fitting tile
    configuration, and — at tp > 1 — the ring path (a tp-sharded weight
    cannot enter a plain ``pallas_call``; the ring's manual region hands
    the kernel its local shard)."""
    if jax.default_backend() != "tpu" and not FORCE_INTERPRET:
        return False
    if _auto_blocks(D) is None:
        return False
    if tp > 1 and not ring:
        return False
    return True


def reference_matmul_bias(x, w, b=None):
    """jnp reference: same math, materialized — the parity oracle."""
    y = x.astype(jnp.float32) @ w.astype(jnp.float32)
    if b is not None:
        y = y + b.reshape(-1).astype(jnp.float32)
    return y.astype(x.dtype)
