"""Context parallelism: ring attention and Ulysses sequence parallelism.

New capability relative to the reference (SURVEY §5.7: absent there; its
building blocks exist as the ``scatter_and_merge`` all-to-all —
``torch/collectives.py:218-245``, exactly the Ulysses exchange — and the
``shard_sequence`` helpers, ``torch/nn/utils.py:45-70``).

TPU-native design: the sequence axis lives on the ``cp`` mesh axis.
- **Ring attention**: inside a ``shard_map`` manual region over cp, each
  device holds Q for its sequence block and rotates K/V blocks around the
  ring with ``lax.ppermute`` (ICI neighbor traffic), merging per-block
  partial attention with the online-softmax rule — full attention over the
  global sequence without ever materializing it on one chip.
- **Ulysses**: two ``lax.all_to_all``s re-shard [B, T/cp, H, hd] ->
  [B, T, H/cp, hd] (heads scattered, sequence gathered), run plain local
  attention, and shard back.
- **allgather** (``context_parallel_impl: allgather``): no manual region;
  GSPMD gathers K/V from the sharding constraints (the baseline).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.backend.topology import CP_AXIS
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

NEG_INF = -1e30


def cp_size():
    if not state.initialized:
        return 1
    return state.mesh.shape.get(CP_AXIS, 1)


def _block_scores(q, k, scale):
    return jnp.einsum(
        "bthd,bshd->bhts",
        (q.astype(jnp.float32) * scale),
        k.astype(jnp.float32),
    )


def ring_attention_local(q, k, v, *, scale, causal, n_blocks, axis_name=CP_AXIS):
    """Per-shard ring attention body (runs inside shard_map).

    q, k, v: [B, Tl, H, hd] — this device's sequence block.
    Rotates K/V around the cp ring; merges blocks with online softmax.
    """
    B, Tl, H, hd = q.shape
    me = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    rows_local = jnp.arange(Tl)
    cols_local = jnp.arange(Tl)

    def body(i, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (me - i) % n_blocks  # whose block we currently hold
        s = _block_scores(q, k_cur, scale)  # [B, H, Tl, Tl]
        if causal:
            rows_g = me * Tl + rows_local[:, None]
            cols_g = src * Tl + cols_local[None, :]
            mask = cols_g <= rows_g
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # Guard fully-masked rows/blocks: keep m finite for the exp.
        m_safe = jnp.maximum(m_new, -1e29)
        p = jnp.exp(s - m_safe)
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        alpha = jnp.exp(jnp.maximum(m, -1e29) - m_safe) * (m > NEG_INF / 2)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhts,bshd->bthd", p, v_cur.astype(jnp.float32)
        ).transpose(0, 2, 1, 3)
        # Rotate K/V to the next device (ICI neighbor exchange).
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc_new, m_new, l_new, k_nxt, v_nxt

    acc0 = jnp.zeros((B, H, Tl, hd), jnp.float32)
    m0 = jnp.full((B, H, Tl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    acc, m, l, _, _ = jax.lax.fori_loop(
        0, n_blocks, body, (acc0, m0, l0, k, v)
    )
    out = acc / jnp.maximum(l, 1e-30)  # [B, H, Tl, hd]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention_local(q, k, v, *, scale, causal, n_blocks,
                            axis_name=CP_AXIS):
    """Per-shard Ulysses body: all_to_all heads<->sequence, local attention.

    Parity note: the head/sequence exchange is the reference's
    ``scatter_and_merge`` collective (``torch/collectives.py:218-245``).
    """
    H = q.shape[2]
    if H % n_blocks != 0:
        raise SMPValidationError(
            f"Ulysses context parallelism needs heads ({H}) divisible by "
            f"cp degree ({n_blocks})."
        )

    def exchange_fwd(x):  # [B, Tl, H, hd] -> [B, T, H/cp, hd]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qg, kg, vg = exchange_fwd(q), exchange_fwd(k), exchange_fwd(v)
    T = qg.shape[1]
    s = _block_scores(qg, kg, scale)  # [B, H/cp, T, T]
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", p, vg.astype(jnp.float32))
    out = out.astype(q.dtype)
    # [B, T, H/cp, hd] -> [B, Tl, H, hd]
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def cp_attention(q, k, v, *, scale, causal, impl=None):
    """Context-parallel attention over logically-full [B, T, H, hd] inputs
    whose sequence axis is sharded over the cp mesh axis."""
    n = cp_size()
    mesh = state.mesh
    impl = impl or state.cfg.context_parallel_impl
    T = q.shape[1]
    if T % n != 0:
        raise SMPValidationError(
            f"Sequence length {T} must be divisible by context_parallel_degree {n}."
        )
    body = {
        "ring": ring_attention_local,
        "ulysses": ulysses_attention_local,
    }[impl]
    fn = functools.partial(body, scale=scale, causal=causal, n_blocks=n)
    spec = P(None, CP_AXIS, None, None)
    shard_fn = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={CP_AXIS},
        check_vma=False,
    )
    return shard_fn(q, k, v)
