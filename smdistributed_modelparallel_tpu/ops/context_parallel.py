"""Context parallelism: ring attention and Ulysses sequence parallelism.

New capability relative to the reference (SURVEY §5.7: absent there; its
building blocks exist as the ``scatter_and_merge`` all-to-all —
``torch/collectives.py:218-245``, exactly the Ulysses exchange — and the
``shard_sequence`` helpers, ``torch/nn/utils.py:45-70``).

TPU-native design: the sequence axis lives on the ``cp`` mesh axis.
- **Ring attention**: inside a ``shard_map`` manual region over cp, each
  device holds Q for its sequence block and rotates K/V blocks around the
  ring with ``lax.ppermute`` (ICI neighbor traffic), merging per-block
  partial attention with the online-softmax rule — full attention over the
  global sequence without ever materializing it on one chip. For CAUSAL
  attention the sequence is laid out in ZIGZAG order (device i holds
  chunks i and 2n-1-i of 2n half-chunks), so every device carries an equal
  share of the causal triangle — without it, early ring ranks idle on
  mostly-masked blocks while late ranks do ~2x the unmasked work.
- **Ulysses**: two ``lax.all_to_all``s re-shard [B, T/cp, H, hd] ->
  [B, T, H/cp, hd] (heads scattered, sequence gathered), run plain local
  attention, and shard back.
- **allgather** (``context_parallel_impl: allgather``): no manual region;
  GSPMD gathers K/V from the sharding constraints (the baseline).

Real-model support: additive key-padding biases [B, S] travel around the
ring with K/V (or allgather under Ulysses), and attention dropout uses the
counter-based hash RNG shared with the Pallas kernels — keyed on GLOBAL
(batch*head, row, col) indices, so ring and Ulysses produce identical
dropout patterns and JAX AD replays them exactly in the backward.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.backend.topology import CP_AXIS
from smdistributed_modelparallel_tpu.ops.pallas_attention import _dropout_keep
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError
from smdistributed_modelparallel_tpu.utils.jax_compat import shard_map
from smdistributed_modelparallel_tpu.utils.logger import get_logger

NEG_INF = -1e30

logger = get_logger()

# Largest per-kernel-call sequence extent: the flash kernels hold full K/V
# (forward, dq pass) and full Q (dk/dv pass) blocks in VMEM, so one call's
# q/kv lengths must stay inside the proven <=8k envelope. Longer per-shard
# blocks are CHUNKED at this size and merged with the same online-softmax
# rule the ring already uses (fwd) / additive accumulation (bwd).
_RING_CHUNK = 8192

# Chunk-length floor per dispatch mode: real-kernel calls need tileable
# blocks; the interpret-mode CPU tier has no such constraint (kept as a
# module constant so tests can exercise the padded ring path).
_RING_MIN_LEN = 128
_RING_MIN_LEN_INTERPRET = 1

# One warning per distinct shape when the Pallas path is unavailable and
# dispatch falls back to the score-materializing jnp body.
_FALLBACK_WARNED = set()


def _tr(a):
    """[B, H, T] per-row weight -> broadcastable over [B, T, H, hd]."""
    return a.transpose(0, 2, 1)[..., None]


def _merge_partial(u, m_run, z, o_i, lse_i):
    """One online-softmax merge step for blockwise flash partials.

    Shared by the ring steps, the ring kv chunks, and the Ulysses full-
    sequence chunks — the merge rule must stay bit-identical across
    impls, so it lives in exactly one place. ``lse_i`` uses the kernels'
    +_LSE_MASKED sentinel (> 1e29) for fully-masked rows."""
    lse_i = jnp.where(lse_i > 1e29, NEG_INF, lse_i)
    m_new = jnp.maximum(m_run, lse_i)
    m_safe = jnp.maximum(m_new, -1e29)
    alpha = jnp.where(m_run > NEG_INF / 2, jnp.exp(m_run - m_safe), 0.0)
    w_i = jnp.where(lse_i > NEG_INF / 2, jnp.exp(lse_i - m_safe), 0.0)
    u = u * _tr(alpha) + o_i.astype(jnp.float32) * _tr(w_i)
    z = z * alpha + w_i
    return u, m_new, z


def _finalize_merge(u, m_run, z, dtype):
    """(normalized output, global lse with NEG_INF on all-masked rows)."""
    out = (u / _tr(jnp.maximum(z, 1e-30))).astype(dtype)
    lse = jnp.where(
        z > 0.0,
        jnp.maximum(m_run, -1e29) + jnp.log(jnp.maximum(z, 1e-30)),
        NEG_INF,
    )
    return out, lse


def _ring_chunks(Tl, chunk, min_len=128):
    """Smallest split count s with Tl % s == 0 and min_len <= Tl//s <=
    chunk, or None if no such split exists (then dispatch pads or falls
    back)."""
    if Tl <= chunk:
        return 1 if Tl >= min_len else None
    for s in range(-(-Tl // chunk), Tl + 1):
        if Tl % s == 0 and Tl // s <= chunk:
            return s if Tl // s >= min_len else None
    return None


def _pad_plan(Tl, chunk, min_len):
    """Smallest padded per-shard length with a valid chunk split.

    For per-shard lengths with no exact divisor in [min_len, chunk] (odd /
    prime ``Tl``, ADVICE item), abandoning the flash path costs an O(T^2)
    score-materializing fallback; a few rows of padding keeps it. Returns
    ``(Tl_padded, n_sub)`` minimizing the padding, or None when even
    padding cannot produce a valid split.
    """
    best = None
    s_lo = max(1, -(-Tl // chunk))
    s_hi = max(s_lo, -(-Tl // max(min_len, 1)))
    for s in range(s_lo, s_hi + 1):
        need = -(-Tl // s)
        if need > chunk:
            continue
        block = max(min_len, need)
        if block > chunk:
            continue
        cand = s * block
        if cand < Tl:
            continue
        if best is None or cand < best[0]:
            best = (cand, s)
    return best


def cp_size():
    if not state.initialized:
        return 1
    return state.mesh.shape.get(CP_AXIS, 1)


def _block_scores(q, k, scale):
    return jnp.einsum(
        "bthd,bshd->bhts",
        (q.astype(jnp.float32) * scale),
        k.astype(jnp.float32),
    )


def _keep4d(seed, B, n_heads, h0, h_total, rows_g, cols_g, s_total, rate):
    """[B, n_heads, len(rows), len(cols)] dropout keep mask from GLOBAL
    indices; ``h0`` is the global index of the first local head and
    ``h_total`` the global head count (Ulysses shards heads, ring does
    not). Same hash AND same key as the Pallas kernels: bh = b*H + h
    (the kernel's flat program_id over a [B*H] grid) — ring, Ulysses, and
    the Pallas path produce identical dropout patterns for one model.
    """
    b = jnp.arange(B)[:, None, None, None]
    h = (h0 + jnp.arange(n_heads))[None, :, None, None]
    bh = b * jnp.int32(h_total) + h
    rows = rows_g[None, None, :, None]
    cols = cols_g[None, None, None, :]
    return _dropout_keep(seed, bh, rows, cols, s_total, rate)


def _zig_rows(dev, half, n):
    """Global row indices of the zigzag-local block held by ``dev``."""
    a = dev * half + jnp.arange(half)
    b = (2 * n - 1 - dev) * half + jnp.arange(half)
    return jnp.concatenate([a, b])


def _zig_owner(h, n):
    """Zigzag owner device of half-chunk h (of 2n): device h for the first
    n half-chunks, mirrored back for the rest."""
    return h if h < n else 2 * n - 1 - h


def _zig_perms(n):
    """Device permutations realizing the natural->zigzag re-layout.

    Natural layout: device d holds half-chunks (2d, 2d+1). Zigzag: device
    d holds (d, 2n-1-d). Each device's first half goes to one distinct
    device and its second half to another — TWO ppermutes move the whole
    re-layout as point-to-point ICI neighbor traffic (vs. the generic
    gather GSPMD emits for a global take on the sharded axis).
    """
    perm1 = [(d, _zig_owner(2 * d, n)) for d in range(n)]
    perm2 = [(d, _zig_owner(2 * d + 1, n)) for d in range(n)]
    return perm1, perm2


def _zig_enter(x, me, n, axis_name):
    """Natural-layout local block [B, Tl, ...] -> zigzag-layout block."""
    half = x.shape[1] // 2
    perm1, perm2 = _zig_perms(n)
    a = jax.lax.ppermute(x[:, :half], axis_name, perm1)
    b = jax.lax.ppermute(x[:, half:], axis_name, perm2)
    # Zigzag slot 0 holds h=me (a first half iff me is even), slot 1 holds
    # h=2n-1-me (first half iff me is odd).
    even = (me % 2) == 0
    slot0 = jnp.where(even, a, b)
    slot1 = jnp.where(even, b, a)
    return jnp.concatenate([slot0, slot1], axis=1)


def _zig_exit(x, me, n, axis_name):
    """Zigzag-layout local block -> natural layout (inverse of enter)."""
    half = x.shape[1] // 2
    perm1, perm2 = _zig_perms(n)
    inv1 = [(dst, src) for src, dst in perm1]
    inv2 = [(dst, src) for src, dst in perm2]
    even = (me % 2) == 0
    even_chunk = jnp.where(even, x[:, :half], x[:, half:])  # h even
    odd_chunk = jnp.where(even, x[:, half:], x[:, :half])   # h odd
    first = jax.lax.ppermute(even_chunk, axis_name, inv1)
    second = jax.lax.ppermute(odd_chunk, axis_name, inv2)
    return jnp.concatenate([first, second], axis=1)


def ring_attention_local(q, k, v, kpad, seed, *, scale, causal, n_blocks,
                         zigzag, dropout_rate, axis_name=CP_AXIS):
    """Per-shard ring attention body (runs inside shard_map).

    q, k, v: [B, Tl, H, hd] — this device's sequence block (zigzag order
    for causal); kpad: [B, Tl] additive bias or None; seed: int32 or None.
    Rotates K/V (and kpad) around the cp ring; merges blocks with online
    softmax.
    """
    B, Tl, H, hd = q.shape
    me = jax.lax.axis_index(axis_name)
    if zigzag:
        # Re-layout to zigzag IN-REGION (two ppermutes each way) so every
        # device carries an equal share of the causal triangle; undone on
        # the way out. The block-index math below addresses the zigzag
        # layout through global_rows().
        q = _zig_enter(q, me, n_blocks, axis_name)
        k = _zig_enter(k, me, n_blocks, axis_name)
        v = _zig_enter(v, me, n_blocks, axis_name)
        if kpad is not None:
            kpad = _zig_enter(kpad, me, n_blocks, axis_name)
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]
    T_total = Tl * n_blocks
    half = Tl // 2

    def global_rows(dev):
        if zigzag:
            return _zig_rows(dev, half, n_blocks)
        return dev * Tl + jnp.arange(Tl)

    rows_g = global_rows(me)
    inv_keep = 1.0 / (1.0 - dropout_rate) if dropout_rate > 0.0 else 1.0

    def body(i, carry):
        acc, m, l, k_cur, v_cur, kp_cur = carry
        src = (me - i) % n_blocks  # whose block we currently hold
        s = _block_scores(q, k_cur, scale)  # [B, H, Tl, Tl]
        cols_g = global_rows(src)
        if kp_cur is not None:
            s = s + kp_cur[:, None, None, :]
        if causal:
            mask = cols_g[None, :] <= rows_g[:, None]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # Guard fully-masked rows/blocks: keep m finite for the exp.
        m_safe = jnp.maximum(m_new, -1e29)
        p = jnp.exp(s - m_safe)
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        alpha = jnp.exp(jnp.maximum(m, -1e29) - m_safe) * (m > NEG_INF / 2)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _keep4d(seed, B, H, 0, H, rows_g, cols_g, T_total,
                           dropout_rate)
            p = jnp.where(keep, p, 0.0)
        acc_new = acc * alpha + jnp.einsum(
            "bhts,bshd->bthd", p, v_cur.astype(jnp.float32)
        ).transpose(0, 2, 1, 3)
        # Rotate K/V (and the key-padding bias) to the next device.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        kp_nxt = (
            jax.lax.ppermute(kp_cur, axis_name, perm)
            if kp_cur is not None else None
        )
        return acc_new, m_new, l_new, k_nxt, v_nxt, kp_nxt

    acc0 = jnp.zeros((B, H, Tl, hd), jnp.float32)
    m0 = jnp.full((B, H, Tl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    acc, m, l, _, _, _ = jax.lax.fori_loop(
        0, n_blocks, body, (acc0, m0, l0, k, v, kpad)
    )
    out = acc * inv_keep / jnp.maximum(l, 1e-30)  # [B, H, Tl, hd]
    out = out.transpose(0, 2, 1, 3).astype(q.dtype)
    if zigzag:
        out = _zig_exit(out, me, n_blocks, axis_name)
    return out


@functools.lru_cache(maxsize=32)
def _ring_flash_fn(scale, causal, n_blocks, zigzag, axis_name, interpret,
                   has_kp, dropout_rate=0.0, n_sub=1):
    """custom_vjp ring attention built on the blockwise Pallas kernels.

    Forward: per ring step, one flash forward over the (local q block,
    rotating kv block) pair with GLOBAL ids driving the causal mask (so
    the zigzag row re-ordering is exact); partials merge with the online
    log-space softmax rule. The per-step wrappers re-derive the kernel
    layouts of the loop-invariant operands (q; and o/g/delta/lse in the
    backward) — XLA's while-loop invariant code motion hoists those out
    of the compiled fori_loop, so they cost one pass, not n_blocks. Backward: the flash backward decomposition
    distributed over the ring — dq accumulates locally from the global
    logsumexp/delta, while dk/dv accumulators ROTATE WITH k/v so each
    block's gradient arrives home after the full cycle. Residuals are the
    LOCAL q/k/v/out/lse only: unlike reverse-AD through the jnp ring's
    fori_loop, no rotating KV carries (i.e. no full global KV) are saved,
    and no [Tl, Tl] score block is ever materialized in HBM.
    """
    from smdistributed_modelparallel_tpu.ops.pallas_attention import (
        _LSE_MASKED,
        flash_bwd_with_ids,
        flash_fwd_with_ids,
    )

    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def rows_for(dev, Tl):
        if zigzag:
            return _zig_rows(dev, Tl // 2, n_blocks)
        return dev * Tl + jnp.arange(Tl)

    def fwd_impl(q, k, v, kp, seed):
        me = jax.lax.axis_index(axis_name)
        if zigzag:
            q = _zig_enter(q, me, n_blocks, axis_name)
            k = _zig_enter(k, me, n_blocks, axis_name)
            v = _zig_enter(v, me, n_blocks, axis_name)
            if kp is not None:
                kp = _zig_enter(kp, me, n_blocks, axis_name)
        B, Tl, H, hd = q.shape
        rows_g = rows_for(me, Tl)

        C = Tl // n_sub

        def step(i, carry):
            u, m_run, z, k_cur, v_cur, kp_cur = carry
            src = (me - i) % n_blocks
            cols_full = rows_for(src, Tl)
            # KV-chunked flash: each sub-call fits the kernels' VMEM
            # envelope; partials merge with the same online-softmax rule
            # used across ring steps (n_sub == 1 is the unchunked case).
            for sub in range(n_sub):
                sl = slice(sub * C, (sub + 1) * C)
                o_i, lse_i = flash_fwd_with_ids(
                    q, k_cur[:, sl], v_cur[:, sl],
                    kp_cur[:, sl] if kp_cur is not None else None,
                    rows_g, cols_full[sl],
                    scale=scale, causal=causal, interpret=interpret,
                    seed=seed if dropout_rate > 0.0 else None,
                    dropout_rate=dropout_rate,
                    counter_len=Tl * n_blocks,
                )
                u, m_run, z = _merge_partial(u, m_run, z, o_i, lse_i)
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            kp_nxt = (
                jax.lax.ppermute(kp_cur, axis_name, perm)
                if kp_cur is not None else None
            )
            return u, m_run, z, k_nxt, v_nxt, kp_nxt

        u0 = jnp.zeros((B, Tl, H, hd), jnp.float32)
        m0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
        z0 = jnp.zeros((B, H, Tl), jnp.float32)
        u, m_run, z, _, _, _ = jax.lax.fori_loop(
            0, n_blocks, step, (u0, m0, z0, k, v, kp)
        )
        out, lse = _finalize_merge(u, m_run, z, q.dtype)
        out_nat = (
            _zig_exit(out, me, n_blocks, axis_name) if zigzag else out
        )
        return out_nat, (q, k, v, kp, seed, out, lse)

    def bwd_impl(res, g):
        q, k, v, kp, seed, o, lse = res     # zigzag layout (as entered)
        me = jax.lax.axis_index(axis_name)
        if zigzag:
            g = _zig_enter(g, me, n_blocks, axis_name)
        B, Tl, H, hd = q.shape
        rows_g = rows_for(me, Tl)
        lse_b = jnp.where(lse <= NEG_INF / 2, _LSE_MASKED, lse)

        C = Tl // n_sub

        def step(i, carry):
            dq, k_cur, v_cur, kp_cur, dk, dv = carry
            src = (me - i) % n_blocks
            cols_full = rows_for(src, Tl)
            # (q-chunk x kv-chunk) flash calls: with the GLOBAL lse/delta
            # fixed, each pair's dq/dk/dv contribution is additive, so
            # chunking both sides keeps every call inside the kernels'
            # full-Q (dk/dv pass) and full-KV (dq pass) VMEM envelopes.
            for qs in range(n_sub):
                qsl = slice(qs * C, (qs + 1) * C)
                for ks in range(n_sub):
                    ksl = slice(ks * C, (ks + 1) * C)
                    dq_i, dk_i, dv_i = flash_bwd_with_ids(
                        q[:, qsl], k_cur[:, ksl], v_cur[:, ksl],
                        o[:, qsl], g[:, qsl], lse_b[:, :, qsl],
                        kp_cur[:, ksl] if kp_cur is not None else None,
                        rows_g[qsl], cols_full[ksl],
                        scale=scale, causal=causal, interpret=interpret,
                        seed=seed if dropout_rate > 0.0 else None,
                        dropout_rate=dropout_rate,
                        counter_len=Tl * n_blocks,
                    )
                    dq = dq.at[:, qsl].add(dq_i.astype(jnp.float32))
                    dk = dk.at[:, ksl].add(dk_i.astype(jnp.float32))
                    dv = dv.at[:, ksl].add(dv_i.astype(jnp.float32))
            # dk/dv ride the ring with k/v: after the full cycle each
            # block's accumulated gradient sits on its owning device.
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            kp_nxt = (
                jax.lax.ppermute(kp_cur, axis_name, perm)
                if kp_cur is not None else None
            )
            dk = jax.lax.ppermute(dk, axis_name, perm)
            dv = jax.lax.ppermute(dv, axis_name, perm)
            return dq, k_nxt, v_nxt, kp_nxt, dk, dv

        z = jnp.zeros((B, Tl, H, hd), jnp.float32)
        dq, _, _, _, dk, dv = jax.lax.fori_loop(
            0, n_blocks, step, (z, k, v, kp, z, z)
        )
        if zigzag:
            dq = _zig_exit(dq, me, n_blocks, axis_name)
            dk = _zig_exit(dk, me, n_blocks, axis_name)
            dv = _zig_exit(dv, me, n_blocks, axis_name)
        grads = (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))
        if has_kp:
            grads = grads + (jnp.zeros_like(kp),)
        return grads + (None,)      # seed (int) carries no cotangent

    # seed is ALWAYS an argument (a dummy 0 when dropout is off — the
    # static dropout_rate==0.0 keeps the kernels from ever hashing it),
    # so only kpad's presence forks the arity.
    if has_kp:
        @jax.custom_vjp
        def ring(q, k, v, kp, seed):
            return fwd_impl(q, k, v, kp, seed)[0]

        ring.defvjp(lambda q, k, v, kp, s: fwd_impl(q, k, v, kp, s),
                    bwd_impl)
    else:
        @jax.custom_vjp
        def ring(q, k, v, seed):
            return fwd_impl(q, k, v, None, seed)[0]

        ring.defvjp(lambda q, k, v, s: fwd_impl(q, k, v, None, s),
                    bwd_impl)
    return ring


def ring_attention_local_flash(q, k, v, kpad, seed, *, scale, causal,
                               n_blocks, zigzag, interpret,
                               dropout_rate=0.0, n_sub=1,
                               axis_name=CP_AXIS):
    """Pallas-kernel ring attention body. Dropout hashes on GLOBAL
    (bh, row, col) ids with the T_total stride — bit-identical to the jnp
    ring/Ulysses bodies, so impls stay interchangeable mid-training.
    ``n_sub`` > 1 chunks each ring step's local block so per-shard lengths
    beyond the kernels' VMEM envelope stay in-kernel."""
    has_seed = seed is not None and dropout_rate > 0.0
    fn = _ring_flash_fn(
        scale, causal, n_blocks, zigzag, axis_name, interpret,
        kpad is not None, dropout_rate if has_seed else 0.0, n_sub,
    )
    seed_arg = seed if has_seed else jnp.int32(0)
    if kpad is not None:
        return fn(q, k, v, kpad, seed_arg)
    return fn(q, k, v, seed_arg)


@functools.lru_cache(maxsize=32)
def _chunked_full_flash_fn(scale, causal, n_sub, interpret, has_kp,
                           dropout_rate, head_total, counter_len):
    """custom_vjp full attention over [B, T, H_local, hd] with T beyond
    the kernels' single-call VMEM envelope: the same chunk-and-merge
    composition as the chunked ring (kv chunks online-softmax merged in
    the forward; (q-chunk x kv-chunk) additive accumulation against the
    global logsumexp in the backward), minus the ring permutes. Used by
    the Ulysses body after its all_to_all, so per-device global sequences
    up to n_sub * _RING_CHUNK stay on the no-materialization path.
    Dropout hashes with global head ids (head0 runtime arg) and the
    ``counter_len`` stride — bit-identical to the jnp Ulysses body."""
    from smdistributed_modelparallel_tpu.ops.pallas_attention import (
        _LSE_MASKED,
        flash_bwd_with_ids,
        flash_fwd_with_ids,
    )

    def fwd_impl(q, k, v, kp, seed, head0):
        B, T, H, hd = q.shape
        C = T // n_sub
        rows = jnp.arange(T)
        u = jnp.zeros((B, T, H, hd), jnp.float32)
        m_run = jnp.full((B, H, T), NEG_INF, jnp.float32)
        z = jnp.zeros((B, H, T), jnp.float32)
        for sub in range(n_sub):
            sl = slice(sub * C, (sub + 1) * C)
            o_i, lse_i = flash_fwd_with_ids(
                q, k[:, sl], v[:, sl],
                kp[:, sl] if kp is not None else None,
                rows, rows[sl],
                scale=scale, causal=causal, interpret=interpret,
                seed=seed if dropout_rate > 0.0 else None,
                dropout_rate=dropout_rate, counter_len=counter_len,
                head0=head0 if dropout_rate > 0.0 else None,
                head_total=head_total,
            )
            u, m_run, z = _merge_partial(u, m_run, z, o_i, lse_i)
        out, lse = _finalize_merge(u, m_run, z, q.dtype)
        return out, (q, k, v, kp, seed, head0, out, lse)

    def bwd_impl(res, g):
        q, k, v, kp, seed, head0, o, lse = res
        B, T, H, hd = q.shape
        C = T // n_sub
        rows = jnp.arange(T)
        lse_b = jnp.where(lse <= NEG_INF / 2, _LSE_MASKED, lse)
        zq = jnp.zeros((B, T, H, hd), jnp.float32)
        dq, dk, dv = zq, zq, zq
        for qs in range(n_sub):
            qsl = slice(qs * C, (qs + 1) * C)
            for ks in range(n_sub):
                if causal and ks > qs:
                    # Static ids (unlike the ring's rotating blocks):
                    # every block strictly above the diagonal is fully
                    # masked — skip the kernel call outright.
                    continue
                ksl = slice(ks * C, (ks + 1) * C)
                dq_i, dk_i, dv_i = flash_bwd_with_ids(
                    q[:, qsl], k[:, ksl], v[:, ksl],
                    o[:, qsl], g[:, qsl], lse_b[:, :, qsl],
                    kp[:, ksl] if kp is not None else None,
                    rows[qsl], rows[ksl],
                    scale=scale, causal=causal, interpret=interpret,
                    seed=seed if dropout_rate > 0.0 else None,
                    dropout_rate=dropout_rate, counter_len=counter_len,
                    head0=head0 if dropout_rate > 0.0 else None,
                    head_total=head_total,
                )
                dq = dq.at[:, qsl].add(dq_i.astype(jnp.float32))
                dk = dk.at[:, ksl].add(dk_i.astype(jnp.float32))
                dv = dv.at[:, ksl].add(dv_i.astype(jnp.float32))
        grads = (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))
        if has_kp:
            grads = grads + (jnp.zeros_like(kp),)
        return grads + (None, None)    # seed, head0: no cotangent

    if has_kp:
        @jax.custom_vjp
        def attn(q, k, v, kp, seed, head0):
            return fwd_impl(q, k, v, kp, seed, head0)[0]

        attn.defvjp(lambda q, k, v, kp, s, h0: fwd_impl(q, k, v, kp, s, h0),
                    bwd_impl)
    else:
        @jax.custom_vjp
        def attn(q, k, v, seed, head0):
            return fwd_impl(q, k, v, None, seed, head0)[0]

        attn.defvjp(
            lambda q, k, v, s, h0: fwd_impl(q, k, v, None, s, h0),
            bwd_impl,
        )
    return attn


def ulysses_attention_local(q, k, v, kpad, seed, *, scale, causal, n_blocks,
                            dropout_rate, use_flash=False, interpret=False,
                            n_sub=1, axis_name=CP_AXIS):
    """Per-shard Ulysses body: all_to_all heads<->sequence, local attention.

    ``n_sub`` > 1 chunks the post-exchange global sequence through the
    flash kernels (forward kv chunks online-merged, backward additive),
    lifting the per-call VMEM ceiling exactly like the chunked ring.

    Parity note: the head/sequence exchange is the reference's
    ``scatter_and_merge`` collective (``torch/collectives.py:218-245``).
    """
    B = q.shape[0]
    H = q.shape[2]
    if H % n_blocks != 0:
        raise SMPValidationError(
            f"Ulysses context parallelism needs heads ({H}) divisible by "
            f"cp degree ({n_blocks})."
        )
    me = jax.lax.axis_index(axis_name)

    def exchange_fwd(x):  # [B, Tl, H, hd] -> [B, T, H/cp, hd]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qg, kg, vg = exchange_fwd(q), exchange_fwd(k), exchange_fwd(v)
    T = qg.shape[1]
    kp_full = (
        jax.lax.all_gather(kpad, axis_name, axis=1, tiled=True)
        if kpad is not None else None
    )
    if use_flash:
        # Pallas flash kernel (fwd + custom_vjp bwd) over the head-sharded
        # global sequence — no [T, T] score matrix. Dropout hashes with
        # GLOBAL head ids (head0 window of H) and the T stride, matching
        # the jnp bodies bit for bit.
        from smdistributed_modelparallel_tpu.ops.pallas_attention import (
            flash_attention,
        )

        h_local = qg.shape[2]
        use_drop = dropout_rate > 0.0 and seed is not None
        head0 = (me * h_local) if use_drop else None
        if n_sub > 1:
            fn = _chunked_full_flash_fn(
                scale, causal, n_sub, interpret, kp_full is not None,
                dropout_rate if use_drop else 0.0, H, T,
            )
            head0_arg = (
                (me * h_local).astype(jnp.int32) if use_drop
                else jnp.int32(0)
            )
            seed_arg = seed if use_drop else jnp.int32(0)
            if kp_full is not None:
                out = fn(qg, kg, vg, kp_full, seed_arg, head0_arg)
            else:
                out = fn(qg, kg, vg, seed_arg, head0_arg)
        else:
            out = flash_attention(
                qg, kg, vg, kp_full,
                seed if use_drop else None, head0,
                scale, causal, None, dropout_rate if use_drop else 0.0,
                256, 256, interpret, H, T,
            ).astype(q.dtype)
        return jax.lax.all_to_all(
            out, axis_name, split_axis=1, concat_axis=2, tiled=True
        )
    s = _block_scores(qg, kg, scale)  # [B, H/cp, T, T]
    if kp_full is not None:
        s = s + kp_full[:, None, None, :]
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        h_local = H // n_blocks
        rows_g = jnp.arange(T)
        keep = _keep4d(seed, B, h_local, me * h_local, H, rows_g, rows_g, T,
                       dropout_rate)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum("bhts,bshd->bthd", p, vg.astype(jnp.float32))
    out = out.astype(q.dtype)
    # [B, T, H/cp, hd] -> [B, Tl, H, hd]
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def cp_attention(q, k, v, *, scale, causal, impl=None, kpad=None,
                 dropout_rate=0.0, seed=None):
    """Context-parallel attention over logically-full [B, T, H, hd] inputs
    whose sequence axis is sharded over the cp mesh axis.

    ``kpad``: additive key-padding bias [B, T] (or None). ``seed``: int32
    scalar enabling dropout at ``dropout_rate``.
    """
    n = cp_size()
    mesh = state.mesh
    impl = impl or state.cfg.context_parallel_impl
    T = q.shape[1]
    if T % n != 0:
        raise SMPValidationError(
            f"Sequence length {T} must be divisible by context_parallel_degree {n}."
        )
    if dropout_rate > 0.0 and seed is None:
        dropout_rate = 0.0

    # Zigzag causal load balance: the natural->zigzag re-layout (and its
    # inverse) happens INSIDE the manual region as two ppermutes each way
    # (ring_attention_local), so each call costs point-to-point ICI
    # transfers instead of a generic global gather on the sharded axis.
    zigzag = bool(causal) and impl == "ring" and (T // n) % 2 == 0 and n > 1

    # Pallas flash kernels inside the manual regions (VERDICT r3 weak #3):
    # engaged whenever the shapes fit the kernels' VMEM envelope. Dropout
    # included: the kernels hash on GLOBAL (bh, row, col) ids with the
    # T_total stride, so the counter-replay pattern is bit-identical to
    # the jnp bodies (and across ring/Ulysses). FORCE_INTERPRET lets the
    # CPU test tier exercise the exact dispatch.
    from smdistributed_modelparallel_tpu.ops import pallas_attention as _pk

    hd = q.shape[-1]
    flash_cfg = (
        state.cfg is not None
        and getattr(state.cfg, "use_pallas_kernels", True)
    )
    on_tpu = jax.default_backend() == "tpu"
    interpret = not on_tpu
    n_sub = n_sub_uly = None
    if on_tpu:
        # Blocks longer than the kernel envelope are CHUNKED (n_sub > 1),
        # not abandoned: a cp8 x 128k-token run (16k/shard ring, full-T
        # Ulysses) stays on the no-materialization flash path.
        n_sub = _ring_chunks(T // n, _RING_CHUNK)
        flash_ring = flash_cfg and n_sub is not None and hd <= 256
        n_sub_uly = _ring_chunks(T, _RING_CHUNK)
        flash_uly = flash_cfg and n_sub_uly is not None and hd <= 256
    else:
        flash_ring = flash_uly = flash_cfg and _pk.FORCE_INTERPRET
        if flash_ring:
            n_sub = _ring_chunks(
                T // n, _RING_CHUNK, min_len=_RING_MIN_LEN_INTERPRET
            )
            flash_ring = n_sub is not None
        if flash_uly:
            n_sub_uly = _ring_chunks(T, _RING_CHUNK, min_len=1)
            flash_uly = n_sub_uly is not None

    # No exact chunk divisor (odd/prime per-shard lengths): PAD the
    # sequence to the next chunkable multiple instead of dropping to the
    # O(T^2) score-materializing body. Padded key columns are masked —
    # by causality (their global ids exceed every real row) or by a
    # NEG_INF key-padding bias — and padded query rows are sliced off the
    # output. Dropout is the one exception: its counter hash strides by
    # the total length, so padding would silently change the pattern —
    # those shapes keep the warned fallback.
    pad_rows = 0
    if (impl == "ring" and flash_cfg and not flash_ring
            and dropout_rate == 0.0 and hd <= 256
            and (on_tpu or _pk.FORCE_INTERPRET)):
        min_len = _RING_MIN_LEN if on_tpu else _RING_MIN_LEN_INTERPRET
        # Only shards at least a kernel floor long: those pad by at most
        # one chunk-granule (~1%). Sub-floor shards (Tl < min_len) would
        # blow up many-fold — they keep the warned jnp fallback.
        plan = (
            _pad_plan(T // n, _RING_CHUNK, min_len)
            if T // n >= min_len else None
        )
        if plan is not None and plan[0] > T // n:
            Tl_pad, n_sub = plan
            pad_rows = Tl_pad * n - T
            flash_ring = True
            if kpad is None and not causal:
                kpad = jnp.zeros((q.shape[0], T), jnp.float32)
            if kpad is not None:
                kpad = jnp.pad(
                    kpad, ((0, 0), (0, pad_rows)), constant_values=NEG_INF
                )
            q, k, v = (
                jnp.pad(a, ((0, 0), (0, pad_rows), (0, 0), (0, 0)))
                for a in (q, k, v)
            )
            T = T + pad_rows
            zigzag = bool(causal) and (T // n) % 2 == 0 and n > 1

    if flash_cfg and on_tpu and (
        (impl == "ring" and not flash_ring)
        or (impl == "ulysses" and not flash_uly)
    ):
        key = (impl, T, n, hd)
        if key not in _FALLBACK_WARNED:
            _FALLBACK_WARNED.add(key)
            # Ring's jnp body materializes [T/n, T/n] score blocks; the
            # Ulysses body attends over the full all-to-all'd sequence,
            # so its fallback cost is the FULL [T, T].
            ext = T // n if impl == "ring" else T
            logger.warning(
                "cp_attention: Pallas flash path unavailable for "
                "impl=%s T=%d cp=%d hd=%d — falling back to the "
                "score-materializing jnp body (expect O(%d^2) fp32 "
                "score temps).", impl, T, n, hd, ext,
            )

    if impl == "ring":
        if flash_ring:
            body_fn = ring_attention_local_flash
            body_kw = dict(scale=scale, causal=causal, n_blocks=n,
                           zigzag=zigzag, interpret=interpret,
                           dropout_rate=dropout_rate, n_sub=n_sub)
        else:
            body_fn = ring_attention_local
            body_kw = dict(scale=scale, causal=causal, n_blocks=n,
                           zigzag=zigzag, dropout_rate=dropout_rate)
    elif impl == "ulysses":
        body_fn = ulysses_attention_local
        body_kw = dict(scale=scale, causal=causal, n_blocks=n,
                       dropout_rate=dropout_rate, use_flash=flash_uly,
                       interpret=interpret,
                       n_sub=n_sub_uly if flash_uly else 1)
    else:
        raise SMPValidationError(f"Unknown context_parallel_impl {impl!r}")

    spec = P(None, CP_AXIS, None, None)
    call_args = [q, k, v]
    if kpad is not None:
        call_args.append(kpad.astype(jnp.float32))
    if seed is not None:
        call_args.append(jnp.asarray(seed, jnp.int32))
    jitted = _build_cp_call(
        body_fn, tuple(sorted(body_kw.items())), mesh, spec,
        kpad is not None, seed is not None,
    )
    out = jitted(*call_args)
    if pad_rows:
        out = out[:, :T - pad_rows]
    return out


@functools.lru_cache(maxsize=64)
def _build_cp_call(body_fn, body_kw_items, mesh, spec, has_kp, has_seed):
    """Cached jit-of-shard_map builder with optional operands (kpad/seed
    dropped from the arg list when absent; the body receives None).

    Cached by (body fn, static kwargs, mesh, presence flags): eager callers
    (the init/trace pass calls cp_attention per layer) reuse one compiled
    executable instead of paying a fresh shard_map trace + XLA compile per
    call.
    """
    body = functools.partial(body_fn, **dict(body_kw_items))
    in_specs = [spec, spec, spec]
    if has_kp:
        in_specs.append(P(None, CP_AXIS))
    if has_seed:
        in_specs.append(P())

    def fn(*args):
        it = iter(args)
        q, k, v = next(it), next(it), next(it)
        kp = next(it) if has_kp else None
        sd = next(it) if has_seed else None
        return body(q, k, v, kp, sd)

    shard_fn = shard_map(
        fn,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=spec,
        axis_names={CP_AXIS},
        check_vma=False,
    )
    # Partial-manual shard_map must be staged under a jit trace (eager
    # dispatch rejects partial-manual specs). A nested jit wrapper covers
    # every caller: inlined when already tracing (the compiled step),
    # compiled when called eagerly (the init/trace pass).
    return jax.jit(lambda *a: shard_fn(*a))
