"""Fused LM-head cross-entropy Pallas kernels.

TPU extension targeting the known single-chip MFU gap: with a tied LM
head, ``loss = CE(hidden @ emb^T, targets)`` materializes an [N, V]
logits tensor in HBM (GPT-2-124M at B*T=8k tokens: ~800 MB bf16, plus
fp32 casts) that is written once and read twice per step — XLA cannot
eliminate an explicit intermediate. These kernels tile BOTH the row and
the vocab dimension into the Pallas grid (vocab is the inner, sequential
grid axis, so per-row online-softmax state accumulates in revisited
output blocks that stay VMEM-resident) and never materialize logits:

- forward: per (row-block, vocab-block) grid step, one
  ``x_blk @ W_blk^T`` MXU matmul feeding an online max/sum-exp and a
  one-hot-free target-logit pick; outputs per-row (running max, sum-exp,
  target logit), finalized to lse on the host side.
- backward: the standard softmax-minus-one-hot cotangent, recomputed
  blockwise from the saved per-row lse and contracted immediately into
  dx (rows outer, vocab inner) and dW (vocab outer, rows inner) — +1
  recompute matmul pass in exchange for eliminating all [N, V] HBM
  traffic, the same trade the flash attention kernels make.

VMEM per grid step is O(block_n*D + block_v*D + block_n*block_v), NOT
O(V*D) — the full embedding table is never staged (GPT-2's table alone
is ~5x VMEM).

No reference counterpart (SURVEY §2.1 N8 covers fused softmax only);
this is a new-capability op. Layout: x [N, D], W [V, D] (embedding-table
layout; the tied head computes x @ W^T), targets int32 [N].
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from smdistributed_modelparallel_tpu.utils.jax_compat import shard_map

NEG_INF = -1e30

# Testing hook, mirroring pallas_attention.FORCE_INTERPRET.
FORCE_INTERPRET = False


def _fwd_kernel(*refs, block_v, v_total, smoothing):
    it = iter(refs)
    x_ref, w_ref, t_ref = next(it), next(it), next(it)
    m_ref, l_ref, tgt_ref = next(it), next(it), next(it)
    sum_ref = next(it) if smoothing else None
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        tgt_ref[...] = jnp.zeros(tgt_ref.shape, jnp.float32)
        if smoothing:
            sum_ref[...] = jnp.zeros(sum_ref.shape, jnp.float32)

    x = x_ref[...].astype(jnp.float32)                  # [bn, D]
    w = w_ref[...].astype(jnp.float32)                  # [bv, D]
    tids = t_ref[...].reshape(-1, 1)                    # [bn, 1]
    logits = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                   # [bn, bv]
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(cols < v_total, logits, NEG_INF)

    m_prev = m_ref[...].reshape(-1, 1)
    l_prev = l_ref[...].reshape(-1, 1)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(
        jnp.exp(logits - m_new), axis=-1, keepdims=True
    )
    # Target pick: at most one column of this block matches each row's
    # target id; a masked row-sum extracts it without a gather.
    hit = cols == tids
    tgt_add = jnp.sum(jnp.where(hit, logits, 0.0), axis=-1)
    m_ref[...] = m_new.reshape(m_ref.shape)
    l_ref[...] = l_new.reshape(l_ref.shape)
    tgt_ref[...] = tgt_ref[...] + tgt_add.reshape(tgt_ref.shape)
    if smoothing:
        # Valid-column logit row-sums feed the label-smoothing term
        # (loss += eps * (lse - mean(logits))); padded columns hold
        # NEG_INF and are excluded.
        valid = cols < v_total
        sum_ref[...] = sum_ref[...] + jnp.sum(
            jnp.where(valid, logits, 0.0), axis=-1
        ).reshape(sum_ref.shape)


def _bwd_dx_kernel(x_ref, w_ref, t_ref, lse_ref, g_ref, dx_ref, *, block_v,
                   v_total, smoothing, smooth_denom=None):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dx_ref[...] = jnp.zeros(dx_ref.shape, dx_ref.dtype)

    x = x_ref[...].astype(jnp.float32)                  # [bn, D]
    w = w_ref[...].astype(jnp.float32)                  # [bv, D]
    tids = t_ref[...].reshape(-1, 1)
    lse = lse_ref[...].reshape(-1, 1)
    g = g_ref[...].reshape(-1, 1)
    logits = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    valid = cols < v_total
    p = jnp.where(valid, jnp.exp(logits - lse), 0.0)
    target_mass = (cols == tids).astype(jnp.float32)
    if smoothing:
        # dloss/dlogit = p - (1-eps)*onehot - eps/V on valid columns.
        # Under vocab sharding (tp) the denominator is the GLOBAL vocab
        # while the valid mask covers only the local shard.
        target_mass = (1.0 - smoothing) * target_mass + jnp.where(
            valid, smoothing / (smooth_denom or v_total), 0.0
        )
    dlog = (p - target_mass) * g
    dx_ref[...] = dx_ref[...] + jax.lax.dot_general(
        dlog, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dx_ref.dtype)


def _bwd_dw_kernel(x_ref, w_ref, t_ref, lse_ref, g_ref, dw_ref, *, block_n,
                   block_v, n_total, v_total, smoothing, smooth_denom=None):
    j = pl.program_id(0)                                # vocab block (outer)
    i = pl.program_id(1)                                # row block (inner)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros(dw_ref.shape, dw_ref.dtype)

    x = x_ref[...].astype(jnp.float32)                  # [bn, D]
    w = w_ref[...].astype(jnp.float32)                  # [bv, D]
    tids = t_ref[...].reshape(-1, 1)
    lse = lse_ref[...].reshape(-1, 1)
    g = g_ref[...].reshape(-1, 1)
    rows = i * block_n + jax.lax.broadcasted_iota(
        jnp.int32, (x.shape[0], 1), 0
    )
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_v), 1
    )
    logits = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                   # [bn, bv]
    p = jnp.exp(logits - lse)
    target_mass = (cols == tids).astype(jnp.float32)
    if smoothing:
        # All columns of a dW program's block are valid (v_pad slicing
        # happens host-side), but guard like the dx kernel for symmetry.
        target_mass = (1.0 - smoothing) * target_mass + jnp.where(
            cols < v_total, smoothing / (smooth_denom or v_total), 0.0
        )
    dlog = (p - target_mass) * g
    # Padded rows carry g=0 already (their loss cotangent is zero), but
    # guard anyway: their lse is a filler value.
    dlog = jnp.where(rows < n_total, dlog, 0.0)
    dw_ref[...] = dw_ref[...] + jax.lax.dot_general(
        dlog, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dw_ref.dtype)


def _pad_to(x, n, axis, value=0):
    if x.shape[axis] == n:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, n - x.shape[axis])
    return jnp.pad(x, pads, constant_values=value)


def _blocks(N, V, block_n, block_v):
    block_n = min(block_n, max(8, N))
    block_v = min(block_v, V)
    n_pad = -(-N // block_n) * block_n
    v_pad = -(-V // block_v) * block_v
    return block_n, block_v, n_pad, v_pad


def _fused_ce_fwd_impl(x, w, targets, block_n, block_v, interpret,
                       smoothing=0.0):
    N, D = x.shape
    V = w.shape[0]
    block_n, block_v, n_pad, v_pad = _blocks(N, V, block_n, block_v)
    xp = _pad_to(x, n_pad, 0)
    wp = _pad_to(w, v_pad, 0)
    tp = _pad_to(targets.astype(jnp.int32), n_pad, 0)[None, :]
    kern = functools.partial(_fwd_kernel, block_v=block_v, v_total=V,
                             smoothing=smoothing)
    row = pl.BlockSpec((1, block_n), lambda i, j: (0, i))
    n_out = 4 if smoothing else 3
    outs = pl.pallas_call(
        kern,
        grid=(n_pad // block_n, v_pad // block_v),
        in_specs=[
            pl.BlockSpec((block_n, D), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, D), lambda i, j: (j, 0)),
            row,
        ],
        out_specs=[row] * n_out,
        out_shape=[
            jax.ShapeDtypeStruct((1, n_pad), jnp.float32)
            for _ in range(n_out)
        ],
        interpret=interpret or FORCE_INTERPRET,
    )(xp, wp, tp)
    m, l, tgt = outs[0], outs[1], outs[2]
    lse = m[0, :N] + jnp.log(jnp.maximum(l[0, :N], 1e-30))
    logit_sum = outs[3][0, :N] if smoothing else None
    return lse, tgt[0, :N], logit_sum


def _fused_ce_bwd_impl(x, w, targets, lse, g, block_n, block_v, interpret,
                       smoothing=0.0, smooth_denom=None):
    N, D = x.shape
    V = w.shape[0]
    block_n, block_v, n_pad, v_pad = _blocks(N, V, block_n, block_v)
    xp = _pad_to(x, n_pad, 0)
    wp = _pad_to(w, v_pad, 0)
    tp = _pad_to(targets.astype(jnp.int32), n_pad, 0)[None, :]
    # Padded rows: lse filler keeps exp() finite; g = 0 kills their grads.
    lsep = _pad_to(lse.astype(jnp.float32), n_pad, 0, value=1.0)[None, :]
    gp = _pad_to(g.astype(jnp.float32), n_pad, 0)[None, :]
    interp = interpret or FORCE_INTERPRET
    row_i = pl.BlockSpec((1, block_n), lambda i, j: (0, i))

    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, block_v=block_v, v_total=V,
                          smoothing=smoothing, smooth_denom=smooth_denom),
        grid=(n_pad // block_n, v_pad // block_v),
        in_specs=[
            pl.BlockSpec((block_n, D), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, D), lambda i, j: (j, 0)),
            row_i, row_i, row_i,
        ],
        out_specs=pl.BlockSpec((block_n, D), lambda i, j: (i, 0)),
        # fp32 accumulator: the block is revisited across the vocab sweep;
        # accumulating ~V/block_v partial sums in bf16 would round.
        out_shape=jax.ShapeDtypeStruct((n_pad, D), jnp.float32),
        interpret=interp,
    )(xp, wp, tp, lsep, gp)

    # dW grid: vocab outer, rows inner — the dW block is revisited across
    # the inner row sweep.
    row_j = pl.BlockSpec((1, block_n), lambda j, i: (0, i))
    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, block_n=block_n, block_v=block_v,
                          n_total=N, v_total=V, smoothing=smoothing,
                          smooth_denom=smooth_denom),
        grid=(v_pad // block_v, n_pad // block_n),
        in_specs=[
            pl.BlockSpec((block_n, D), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, D), lambda j, i: (j, 0)),
            row_j, row_j, row_j,
        ],
        out_specs=pl.BlockSpec((block_v, D), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((v_pad, D), jnp.float32),
        interpret=interp,
    )(xp, wp, tp, lsep, gp)
    return dx[:N].astype(x.dtype), dw[:V].astype(w.dtype)


def _assemble_loss(lse, tgt, logit_sum, V, smoothing):
    if not smoothing:
        return lse - tgt
    # loss = (1-eps)*(lse - tgt) + eps*(lse - mean(logits))
    #      = lse - (1-eps)*tgt - (eps/V)*sum(logits)
    return lse - (1.0 - smoothing) * tgt - (smoothing / V) * logit_sum


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_lm_head_ce(x, w, targets, block_n=256, block_v=1024,
                     interpret=False, label_smoothing=0.0):
    """Per-token CE of ``x @ w^T`` against ``targets`` without
    materializing logits. x: [N, D]; w: [V, D]; targets: [N] int.
    ``label_smoothing``: HF/T5-convention uniform smoothing
    (eps * mean-over-vocab NLL mixed in). Returns fp32 [N] losses.
    Differentiable in x and w.
    """
    lse, tgt, ls = _fused_ce_fwd_impl(
        x, w, targets, block_n, block_v, interpret, label_smoothing
    )
    return _assemble_loss(lse, tgt, ls, w.shape[0], label_smoothing)


def _fce_fwd(x, w, targets, block_n, block_v, interpret, label_smoothing):
    lse, tgt, ls = _fused_ce_fwd_impl(
        x, w, targets, block_n, block_v, interpret, label_smoothing
    )
    loss = _assemble_loss(lse, tgt, ls, w.shape[0], label_smoothing)
    return loss, (x, w, targets, lse)


def _fce_bwd(block_n, block_v, interpret, label_smoothing, res, g):
    x, w, targets, lse = res
    dx, dw = _fused_ce_bwd_impl(
        x, w, targets, lse, g, block_n, block_v, interpret, label_smoothing
    )
    return dx, dw, None


fused_lm_head_ce.defvjp(_fce_fwd, _fce_bwd)


@functools.lru_cache(maxsize=32)
def make_vocab_parallel_fused_ce(mesh, v_global, block_n, block_v,
                                 interpret, smoothing, axis_name="tp"):
    """Vocab-parallel fused CE (the Megatron composition of
    ``nn/cross_entropy.py``, fused): returns ``ce(x, w, targets)`` for a
    [V, D] table sharded over ``axis_name`` on the given mesh.

    Each shard runs the blockwise kernels on its LOCAL [V/tp, D] table
    slice with targets shifted into local coordinates (out-of-range
    targets simply never hit). The custom_vjp lives at GSPMD level;
    shard_map appears only INSIDE its fwd/bwd implementations (the
    manual regions are never differentiated through, so no dependence on
    shard_map's replicated-cotangent transpose rules):

    - fwd: a tp manual region emits per-shard (lse, target-logit,
      smoothing-sum) stacked on a leading shard axis; the stable
      log-sum-exp merge and loss assembly happen outside (small GSPMD
      collectives) — exactly the allreduce(max)/allreduce(sum) pair the
      materialized path codes (reference ``torch/nn/cross_entropy.py:
      28-112``).
    - bwd: a second manual region recomputes logit blocks per shard from
      the GLOBAL lse, contracting immediately into a psum'd dx
      (replicated out) and a vocab-sharded dW. Smoothing's eps/V term
      uses the GLOBAL vocab; the valid-column mask is local.
    """
    from jax.sharding import PartitionSpec as P

    def _shift(t, v_local):
        me = jax.lax.axis_index(axis_name)
        return t.astype(jnp.int32) - me * v_local

    def stats_body(x, w_local, t):
        lse_l, tgt_l, sum_l = _fused_ce_fwd_impl(
            x, w_local, _shift(t, w_local.shape[0]),
            block_n, block_v, interpret, smoothing,
        )
        if sum_l is None:
            sum_l = jnp.zeros_like(lse_l)
        return lse_l[None], tgt_l[None], sum_l[None]   # [1, N] per shard

    stats_fn = shard_map(
        stats_body, mesh=mesh,
        in_specs=(P(), P(axis_name, None), P()),
        out_specs=(P(axis_name, None),) * 3,
        axis_names={axis_name},
        check_vma=False,
    )

    def bwd_body(x, w_local, t, lse_g, g):
        dx_l, dw_l = _fused_ce_bwd_impl(
            x, w_local, _shift(t, w_local.shape[0]), lse_g, g,
            block_n, block_v, interpret, smoothing,
            smooth_denom=v_global,
        )
        # dx sums vocab-shard contributions -> identical across the axis,
        # so the unmapped out_spec is sound; dW stays vocab-sharded.
        dx = jax.lax.psum(dx_l.astype(jnp.float32), axis_name)
        return dx, dw_l

    bwd_fn = shard_map(
        bwd_body, mesh=mesh,
        in_specs=(P(), P(axis_name, None), P(), P(), P()),
        out_specs=(P(), P(axis_name, None)),
        axis_names={axis_name},
        check_vma=False,
    )

    def fwd_impl(x, w, t):
        lse_s, tgt_s, sum_s = stats_fn(x, w, t)        # [tp, N]
        m_g = jnp.max(lse_s, axis=0)
        z = jnp.sum(jnp.exp(lse_s - m_g[None]), axis=0)
        lse_g = m_g + jnp.log(jnp.maximum(z, 1e-30))
        tgt_g = jnp.sum(tgt_s, axis=0)
        sum_g = jnp.sum(sum_s, axis=0) if smoothing else None
        loss = _assemble_loss(lse_g, tgt_g, sum_g, v_global, smoothing)
        return loss, (x, w, t, lse_g)

    @jax.custom_vjp
    def ce(x, w, t):
        return fwd_impl(x, w, t)[0]

    def bwd(res, g):
        x, w, t, lse_g = res
        dx, dw = bwd_fn(x, w, t, lse_g, g.astype(jnp.float32))
        return dx.astype(x.dtype), dw.astype(w.dtype), None

    ce.defvjp(fwd_impl, bwd)
    return jax.jit(ce)


def _step_bytes(D, block_n, block_v):
    # fp32 in-kernel copies: x_blk + w_blk + logits + dx/dw accumulator.
    return 4 * (block_n * D + block_v * D + block_n * block_v
                + max(block_n, block_v) * D)


_VMEM_BUDGET = 12 * 2**20

# Preference order: large vocab blocks amortize the row re-reads; shrink
# block_v first (it multiplies D in three of the four VMEM terms), then
# block_n, so wide models (large D) still get a fitting configuration
# instead of losing the kernel entirely.
_BLOCK_CANDIDATES = (
    (256, 1024), (256, 512), (128, 512), (128, 256), (64, 256), (32, 128),
)


def auto_blocks(D, block_n=None, block_v=None):
    """Pick (block_n, block_v) whose working set fits the VMEM budget.

    Explicit ``block_n``/``block_v`` are honored when they fit; a
    partially-specified call pins the given dimension and picks the other
    from the candidate list. Returns None when nothing fits
    (pathologically wide D) — callers treat that as "kernel
    unavailable"."""
    if block_n is not None and block_v is not None:
        return (
            (block_n, block_v)
            if _step_bytes(D, block_n, block_v) <= _VMEM_BUDGET else None
        )
    for bn, bv in _BLOCK_CANDIDATES:
        bn = block_n if block_n is not None else bn
        bv = block_v if block_v is not None else bv
        if _step_bytes(D, bn, bv) <= _VMEM_BUDGET:
            return bn, bv
    return None


def fused_ce_ok(x, w, block_n=None, block_v=None):
    """Dispatch precondition: TPU backend (or interpret-mode testing) and
    a block configuration whose working set fits VMEM (``auto_blocks``
    shrinks blocks for wide D); the caller guards vocab sharding.
    SMP_DISABLE_FUSED_CE=1 is the operator escape hatch."""
    import os

    if os.environ.get("SMP_DISABLE_FUSED_CE", "0") == "1":
        return False
    if jax.default_backend() != "tpu" and not FORCE_INTERPRET:
        return False
    return auto_blocks(x.shape[-1], block_n, block_v) is not None


def reference_lm_head_ce(x, w, targets):
    """jnp reference: same math through materialized logits (the fallback
    path and the parity oracle)."""
    logits = (x.astype(jnp.float32) @ w.astype(jnp.float32).T)
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1)) + m[:, 0]
    tgt = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return lse - tgt
