"""Fused bias+GELU Pallas kernel.

The MLP's ``fc`` epilogue is ``h + bias`` followed by tanh-GELU — two
elementwise HBM passes over the [*, intermediate] activation when XLA
declines to fuse them into the matmul. This kernel computes
``gelu(x + b)`` in one VMEM-resident pass; the backward kernel
recomputes the pre-activation from the saved (x, b) and emits
``dpre = g * gelu'(x + b)`` in one pass (db is the row-sum of dpre,
done host-side) — the same recompute-over-materialize trade as the
flash/CE kernels, at elementwise cost.

Parity: the reference's ``fused_bias_gelu`` knob (``torch/nn/gelu.py``,
a hand-written CUDA bias-gelu pair) — the ``DistributedTransformerOutput
Layer`` field now actually dispatches here. The tanh approximation IS
the reference's bias_gelu polynomial (HF "gelu_new"); the exact-erf
variant stays on the jnp path. Interpret-mode fallback on CPU mirrors
``pallas_ce.py`` (``FORCE_INTERPRET`` test hook). Under tensor
parallelism the activation arrives sharded on its feature dim — callers
wrap the call in a tp manual region (``nn/transformer.py``) so the
kernel always sees a local block.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

# Testing hook, mirroring pallas_ce.FORCE_INTERPRET.
FORCE_INTERPRET = False

_SQRT_2_OVER_PI = float(np.sqrt(2.0 / np.pi))
_COEFF = 0.044715

# Rows per grid step; the feature dim stays whole (bias broadcasts over
# rows, and intermediate dims are at most a few k * 4 bytes per row).
_BLOCK_ROWS = 256


def _gelu_tanh(u):
    inner = _SQRT_2_OVER_PI * (u + _COEFF * u * u * u)
    return 0.5 * u * (1.0 + jnp.tanh(inner))


def _dgelu_tanh(u):
    inner = _SQRT_2_OVER_PI * (u + _COEFF * u * u * u)
    t = jnp.tanh(inner)
    sech2 = 1.0 - t * t
    dinner = _SQRT_2_OVER_PI * (1.0 + 3.0 * _COEFF * u * u)
    return 0.5 * (1.0 + t) + 0.5 * u * sech2 * dinner


def _fwd_kernel(x_ref, b_ref, y_ref):
    u = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = _gelu_tanh(u).astype(y_ref.dtype)


def _bwd_kernel(x_ref, b_ref, g_ref, dpre_ref):
    u = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    dpre_ref[...] = (
        g_ref[...].astype(jnp.float32) * _dgelu_tanh(u)
    ).astype(dpre_ref.dtype)


def _pad_rows(x, n):
    if x.shape[0] == n:
        return x
    return jnp.pad(x, ((0, n - x.shape[0]), (0, 0)))


def _call_rowwise(kernel, outs_dtype, interpret, x2d, b, *extra):
    N, F = x2d.shape
    bn = min(_BLOCK_ROWS, max(8, N))
    n_pad = -(-N // bn) * bn
    row = pl.BlockSpec((bn, F), lambda i: (i, 0))
    args = [_pad_rows(x2d, n_pad), b.reshape(1, F)]
    in_specs = [row, pl.BlockSpec((1, F), lambda i: (0, 0))]
    for e in extra:
        args.append(_pad_rows(e, n_pad))
        in_specs.append(row)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // bn,),
        in_specs=in_specs,
        out_specs=row,
        out_shape=jax.ShapeDtypeStruct((n_pad, F), outs_dtype),
        interpret=interpret or FORCE_INTERPRET,
    )(*args)
    return out[:N]


def _bias_gelu_impl(x, b, interpret):
    lead = x.shape[:-1]
    x2d = x.reshape(-1, x.shape[-1])
    return _call_rowwise(
        _fwd_kernel, x.dtype, interpret, x2d, b
    ).reshape(lead + (x.shape[-1],))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def bias_gelu(x, b, interpret=False):
    """``gelu(x + b)`` (tanh approximation) over ``x [..., F]`` and
    ``b [F]`` in one fused pass. Differentiable in x and b."""
    return _bias_gelu_impl(x, b, interpret)


def _bg_fwd(x, b, interpret):
    return _bias_gelu_impl(x, b, interpret), (x, b)


def _bg_bwd(interpret, res, g):
    x, b = res
    lead = x.shape[:-1]
    F = x.shape[-1]
    dpre = _call_rowwise(
        _bwd_kernel, jnp.float32, interpret,
        x.reshape(-1, F), b, g.reshape(-1, F),
    )
    dx = dpre.astype(x.dtype).reshape(lead + (F,))
    db = jnp.sum(dpre, axis=0).astype(b.dtype)
    return dx, db


bias_gelu.defvjp(_bg_fwd, _bg_bwd)


def bias_gelu_ok(activation):
    """Dispatch precondition: the tanh-GELU family (the reference's
    fused bias_gelu polynomial) on the kernel's target backend (TPU, or
    interpret-mode testing)."""
    if activation not in ("gelu", "gelu_new"):
        return False
    return jax.default_backend() == "tpu" or FORCE_INTERPRET


def reference_bias_gelu(x, b):
    """jnp reference: same math, unfused — the parity oracle (matches
    ``nn.gelu(x + b, approximate=True)`` bit-for-tolerance)."""
    u = x.astype(jnp.float32) + b.astype(jnp.float32)
    return _gelu_tanh(u).astype(x.dtype)
