"""@smp.step — the compiled training-step engine.

Parity target: reference ``torch/step.py:118-357`` (``StepFunction``): split
args into microbatches, execute forward/backward per microbatch under the
pipeline, reassemble ``StepOutput``. The reference dispatches each microbatch
through the module-server event loop (``torch/server.py``); here the whole
step — microbatch loop, forward, backward, gradient accumulation, data-
parallel reduction — is ONE jit-compiled SPMD program:

- the user step function runs under JAX tracing; ``model(...)`` applies the
  flax module with the trace's parameters and ``model.backward(loss)``
  records the loss to differentiate;
- microbatches are a ``lax.scan`` over a stacked leading axis (gradient
  accumulation with mean semantics, parity with
  ``torch/allreduce/ddp.py:92-98``);
- data parallelism comes from batch sharding over the mesh's data axes —
  XLA inserts the gradient psum (the reference's bucketed NCCL allreduce,
  SURVEY §2.1 N7, disappears);
- pipeline parallelism (pp > 1) lowers the scan to a 1F1B schedule (M2,
  ``parallel/pipeline.py``).

First call = the reference's trace-and-partition moment
(``torch/server.py:345-352``): parameters are materialized eagerly from the
first microbatch, the partitioner runs, then the step compiles.
"""

import functools
import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.split import (
    DeferredSplit,
    NonSplit,
    StepOutput,
    TensorSplitter,
    microbatch_slice,
    stack_leaf,
)
from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.model import DistributedModel
from smdistributed_modelparallel_tpu.parallel import zero as zero_mod
from smdistributed_modelparallel_tpu.parallel.sharding import batch_spec
from smdistributed_modelparallel_tpu.resilience.chaos import chaos
from smdistributed_modelparallel_tpu.resilience.preemption import preemption
from smdistributed_modelparallel_tpu.resilience.supervisor import supervisor
from smdistributed_modelparallel_tpu.utils import exec_cache
from smdistributed_modelparallel_tpu.utils import health
from smdistributed_modelparallel_tpu.utils import hlo_audit
from smdistributed_modelparallel_tpu.utils import profiling
from smdistributed_modelparallel_tpu.utils.exceptions import StepUsageError
from smdistributed_modelparallel_tpu.utils.flight_recorder import flight_recorder
from smdistributed_modelparallel_tpu.utils.goodput import goodput
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    record_step_time,
    telemetry,
)
from smdistributed_modelparallel_tpu.nn.utils import half_cast as half_cast_util

logger = get_logger()


class _ModelRef:
    """Static placeholder for a DistributedModel inside traced args.

    Value-hashable: instances are created fresh on every step call and feed
    the compiled-function cache key, so identity hashing would defeat the
    cache and silently retrace every step.
    """

    def __init__(self, index):
        self.index = index

    def __hash__(self):
        return hash((_ModelRef, self.index))

    def __eq__(self, other):
        return isinstance(other, _ModelRef) and other.index == self.index

    def __repr__(self):
        # Stable across processes: the repr feeds the persistent
        # executable cache's disk key (the default object repr embeds a
        # heap address).
        return f"_ModelRef({self.index})"


class StepFunction:
    def __init__(self, fn, non_split_inputs=None, input_split_axes=None):
        self.fn = fn
        self.non_split_inputs = non_split_inputs
        self.input_split_axes = input_split_axes
        self._cache = {}
        self._last_runner = None
        functools.update_wrapper(self, fn)

    # ------------------------------------------------------------------

    def __call__(self, *args, **kwargs):
        if state.cfg is None:
            raise StepUsageError("Call smp.init(config) before invoking an @smp.step function.")
        cfg = state.cfg
        model, clean_args, clean_kwargs = self._extract_model(args, kwargs)
        splitter = TensorSplitter(
            cfg.microbatches, self.non_split_inputs, self.input_split_axes
        )
        arg_names = _positional_names(self.fn, len(clean_args))
        # Shape bucketing (SMP_SHAPE_BUCKETS): pad the batch/sequence dims
        # up to the next configured bucket so variable-shaped batches map
        # onto a small set of compiled (and disk-cached) executables.
        # Batch padding is masked at microbatch granularity inside the
        # compiled program (exact, not approximate); unset policy is one
        # env lookup and leaves everything byte-identical.
        bucket_state = None
        policy = exec_cache.bucket_policy()
        if policy is not None:
            clean_args, clean_kwargs, bucket_state = _apply_shape_buckets(
                clean_args, clean_kwargs, arg_names, splitter, policy, cfg
            )
        stacked_args, stacked_kwargs = splitter.stack_microbatches(
            clean_args, clean_kwargs, arg_names
        )

        if model is not None and not model.initialized:
            self._init_run(model, stacked_args, stacked_kwargs)
        elif model is not None:
            # Model may have been initialized by another step fn or an eager
            # call: this StepFunction still needs to learn whether it calls
            # backward, and the partitioner must have run.
            self._discover_backward(model, stacked_args, stacked_kwargs)
            if model._partition_result is None:
                from smdistributed_modelparallel_tpu.parallel.partition import (
                    maybe_auto_partition,
                )

                maybe_auto_partition(model)

        tl = state.timeline
        telemetry.set_phase(f"step_{state.step_count}")
        flight_recorder.record_step("begin", state.step_count)
        # On-demand profiler capture (SMP_PROFILE=steps=N:M / SIGUSR2):
        # starts exactly at this step's begin edge when armed; a single
        # attribute test otherwise.
        profiling.capture.on_step_begin(state.step_count)
        t_step = time.perf_counter()
        exact_time = False
        if tl is not None and tl.enabled:
            tl.start_step(state.step_count)
            with tl.span(f"step_{state.step_count}"):
                grads, outputs = self._run_compiled(
                    model, stacked_args, stacked_kwargs, bucket_state
                )
                with profiling.region("step/fetch"):
                    jax.block_until_ready(outputs)
            tl.end_step(state.step_count)
            tl.flush()
            exact_time = True
        else:
            grads, outputs = self._run_compiled(
                model, stacked_args, stacked_kwargs, bucket_state
            )
            if profiling.should_sample_step(state.step_count):
                # Roofline sample: block on this step's outputs so the
                # measured time covers device execution. Without it the
                # async-dispatch time is a lower bound and smp_mfu would
                # overreport (possibly >1). ~1/16 steps; cost is one
                # drained dispatch queue.
                with profiling.region("step/fetch"):
                    jax.block_until_ready(outputs)
                exact_time = True
        # Dispatch wall time: exact when a block happened above, otherwise
        # a lower bound (async dispatch returns before the device
        # finishes) — still enough for compile-vs-steady-state attribution.
        t_step = time.perf_counter() - t_step
        telemetry.histogram(
            "smp_step_dispatch_seconds", "host wall time per step dispatch"
        ).observe(t_step)
        # Log-bucketed distribution + p50/p90/p99 gauges: the coarse
        # dispatch histogram above keeps its legacy buckets; this one
        # resolves tail steps (a p99 blowup is invisible in the mean).
        record_step_time(t_step)
        # Goodput ledger tick (publish + sentinel window at most once per
        # tick interval): one attribute test while disarmed.
        goodput.on_step_edge(state.step_count)
        profiling.capture.on_step_end(state.step_count, outputs=outputs)
        if exact_time:
            # smp_mfu / smp_roofline_* gauges for this program, from its
            # cached cost analysis + this step's exact wall time.
            profiling.record_step_roofline(self._last_runner, t_step)
        flight_recorder.record_step("end", state.step_count)
        telemetry.counter("smp_step_total", "step invocations").inc()
        if state.memory_metrics is not None:
            state.memory_metrics.record_step(state.step_count)
        from smdistributed_modelparallel_tpu.utils.metrics import (
            record_device_memory_telemetry,
        )

        record_device_memory_telemetry()
        state.step_count += 1
        # Step edge: the only point where every rank is at a known,
        # identical position in the program — chaos faults land here
        # deterministically, and a pending preemption (SIGTERM, sentinel
        # file, peer notice) turns into the coordinated emergency
        # checkpoint before the next step's work begins. Both are
        # single-flag no-ops when disarmed, and the failure-recovery
        # supervisor's edge hook (close a pending recovery's MTTR, raise
        # typed on a detected peer failure before the next dispatch can
        # hang on it) is ONE attribute test when SMP_SUPERVISOR=off.
        chaos.on_step_edge(state.step_count)
        preemption.maybe_emergency_save()
        if supervisor.active:
            supervisor.on_step_edge()
        return StepOutput(outputs)

    # ------------------------------------------------------------------

    def _extract_model(self, args, kwargs):
        model = None

        def swap(v):
            nonlocal model
            if isinstance(v, DistributedModel):
                model = v
                return _ModelRef(0)
            return v

        args = tuple(swap(a) for a in args)
        kwargs = {k: swap(v) for k, v in kwargs.items()}
        if model is None:
            model = state.model
        return model, args, kwargs

    def _init_run(self, model, stacked_args, stacked_kwargs):
        """Eager run of microbatch 0: materializes params (lazy flax init),
        discovers whether backward is used, and gives the partitioner
        concrete shapes. Parity: the reference's first-step trace
        (``torch/worker.py:248-278``)."""
        logger.info("First @smp.step call: running init/trace pass on microbatch 0.")
        mb_args = microbatch_slice(stacked_args, 0)
        mb_kwargs = microbatch_slice(stacked_kwargs, 0)
        mb_args, mb_kwargs = _resolve_model_refs(mb_args, mb_kwargs, model)
        model._tls.in_step = True
        model._tls.rngs = {s: state.rng_manager.next_key("init_" + s) for s in model.rng_streams}
        state._tracing = True
        try:
            self.fn(*mb_args, **mb_kwargs)
        finally:
            state._tracing = False
            self._has_backward = model._end_step_trace() is not None
        from smdistributed_modelparallel_tpu.parallel.partition import maybe_auto_partition

        maybe_auto_partition(model)

    def _discover_backward(self, model, stacked_args, stacked_kwargs):
        """Abstractly trace microbatch 0 to learn whether this step function
        calls model.backward (cheap: jax.eval_shape, no compute)."""
        if hasattr(self, "_has_backward"):
            return
        mb_args = microbatch_slice(stacked_args, 0)
        mb_kwargs = microbatch_slice(stacked_kwargs, 0)
        step_fn = self

        def probe(params):
            rngs = {s: jax.random.key(0) for s in model.rng_streams}
            model._begin_step_trace(params, rngs)
            try:
                args, kwargs = _resolve_model_refs(mb_args, mb_kwargs, model)
                step_fn.fn(*args, **kwargs)
            finally:
                loss = model._end_step_trace()
            step_fn._has_backward = loss is not None
            return jnp.zeros(())

        with jax.set_mesh(state.mesh):
            jax.eval_shape(probe, model.params)

    # ------------------------------------------------------------------

    def _run_compiled(self, model, stacked_args, stacked_kwargs,
                      bucket_state=None):
        # Chaos seam: `wedge@step=N:ms=M` hangs HERE — inside dispatch,
        # after the step-begin edge, before the compiled program runs —
        # so the rank keeps heartbeating (detector thread) while its
        # reported step edge stalls: the peers' supervisors must classify
        # it wedged, not dead. One env lookup when disarmed.
        chaos.on_step_dispatch(state.step_count)
        cfg = state.cfg
        mesh = state.mesh
        num_mb = cfg.microbatches

        # Partition the arg tree into scan leaves (DeferredSplit: restacked
        # to [num_mb, ...] inside the compiled program), broadcast array
        # leaves, and static leaves.
        tree = (stacked_args, stacked_kwargs)
        leaves, treedef = jax.tree_util.tree_flatten(
            tree, is_leaf=lambda x: isinstance(x, (NonSplit, _ModelRef, DeferredSplit))
        )
        scan_idx, bcast_idx, static = [], [], {}
        scan_vals, bcast_vals, scan_meta = [], [], []
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, _ModelRef):
                static[i] = leaf
            elif isinstance(leaf, DeferredSplit):
                scan_idx.append(i)
                scan_vals.append(leaf.value)
                scan_meta.append((leaf.axis, leaf.num_mb, leaf.stacked))
            elif isinstance(leaf, NonSplit):
                if _is_jax_type(leaf.value):
                    bcast_idx.append(i)
                    bcast_vals.append(leaf.value)
                else:
                    static[i] = leaf.value
            else:  # untracked array leaf: broadcast
                bcast_idx.append(i)
                bcast_vals.append(leaf)

        # Fused optimizer update (TPU extension, cfg.fused_optimizer_step):
        # compile the optax update into the step program so a full training
        # iteration is ONE device launch. Disabled under fp16 loss scaling
        # (the overflow-skip decision lives in the scaler on the host).
        opt = state.optimizer
        fused = (
            getattr(cfg, "fused_optimizer_step", False)
            and opt is not None
            and opt.model is model
            and state.loss_scaler is None
            and getattr(self, "_has_backward", True)
        )
        if fused:
            opt._ensure_state()

        # state.generation pins the entry to the topology it was compiled
        # under: smp.reset()/re-init with a different cfg or mesh must not
        # serve a stale program whose shapes/flags happen to collide. The
        # health mode is part of the key: the sentinel reduces live inside
        # the program, so flipping SMP_HEALTH_CHECK recompiles. The
        # pipeline shape tuple (pp, schedule, virtual degree, microbatch
        # math) is keyed explicitly as well: the baked 1F1B schedule and
        # chunk layout depend on all four, and the key must not rely on
        # every config change also bumping the generation.
        hmode = health.mode()
        # Shape bucketing: a masked (microbatch-weighted) program differs
        # from the exact-shape program even at identical input shapes, so
        # the mask flag is part of the key. The weight VECTOR is a device
        # input — every occupancy of one bucket shares one executable.
        masked = bucket_state is not None
        pipe_key = (cfg.pipeline_parallel_degree, cfg.pipeline,
                    getattr(cfg, "virtual_pipeline_degree", 1),
                    num_mb, cfg.active_microbatches)
        # ZeRO knobs change the built program (param sharding layout,
        # slice-grad restructuring, bucket boundaries) without moving any
        # shape component — key them explicitly so a knob flip can never
        # warm-hit a stale executable. Mirrored in the exec-cache's
        # verified knob facts (utils/exec_cache.py) for the disk entries.
        # Sub-knobs that cannot affect the program under the current mode
        # (bucket/prefetch without zero3, the persistence threshold
        # without any ZeRO param sharding) are canonicalized out so an
        # idle env var never spuriously invalidates caches.
        zero3 = cfg.zero3_enabled
        zero_key = (getattr(cfg, "sharded_params", "none"),
                    getattr(cfg, "zero3_bucket_mb", 0) if zero3 else 0,
                    cfg.sdp_param_persistence_threshold
                    if (zero3 or cfg.zero2d_enabled) else 0,
                    cfg.sharded_data_parallel_degree,
                    # Prefetch flips between the transfer-register scan
                    # and the lifted scan at identical shapes.
                    zero_mod.prefetch_knob() if zero3 else "-")
        # Recompute-planner knob: a stash mode rebuilds the pipeline
        # executors (and the checkpoint policy) at identical shapes, so
        # the knob must be keyed. Canonicalized so idle values never
        # move the key: the default ("full") contributes NOTHING — the
        # key (and the disk key every stored entry and golden hashes)
        # stays byte-identical to pre-knob builds regardless of stray
        # budget env vars — and the budget is keyed only under "auto"
        # (the only mode that reads it).
        from smdistributed_modelparallel_tpu.parallel import remat_plan
        rmode = remat_plan.resolve(cfg)
        # Under "auto", an UNSET budget (-1: planner falls back to the
        # last audit's temp bytes or its own ring bound) is a different
        # program than an explicit 0 (degrade everything) — keep them
        # distinct. The audit-derived default itself is deliberately not
        # keyed (it is a volatile registry value); a plan drift under the
        # same key is caught by the disk cache's lowered-module content
        # hash, costing a verified miss, never a wrong program.
        _rbudget = getattr(cfg, "recompute_budget_mb", None)
        recompute_key = (
            () if rmode == "full"
            else ((rmode,
                   (-1 if _rbudget is None else int(_rbudget))
                   if rmode == "auto" else 0),)
        )
        # Overlapped-tp knobs: the ring decomposition and the fused QKV
        # kernel rebuild the program at identical shapes. Canonicalized
        # the recompute way: the defaults (mode "off" via
        # collective_matmul.tp_overlap_mode — which also folds in the
        # tp<=1 / cp>1 inertness — and fused_qkv False) contribute
        # NOTHING, so default keys stay byte-identical to pre-knob
        # builds. Mirrored in the exec-cache knob facts.
        from smdistributed_modelparallel_tpu.ops.collective_matmul import (
            fused_qkv_effective,
            tp_overlap_mode,
        )
        tmode = tp_overlap_mode(cfg)
        _fused_qkv = fused_qkv_effective(cfg)
        tp_overlap_key = (
            () if tmode == "off" and not _fused_qkv
            else ((tmode, _fused_qkv),)
        )
        # Low-precision knob, canonicalized the same way: the default
        # ("bf16", also the pp>1/zero3 fallback via
        # quant.matmul_precision_mode) contributes NOTHING — default
        # keys and the committed goldens stay byte-identical — while
        # fp8 rebuilds the program (quantized seams, the QuantState
        # input/output) at identical shapes. Mirrored in the exec-cache
        # knob facts.
        from smdistributed_modelparallel_tpu import quant as quant_mod
        qmode = quant_mod.matmul_precision_mode(cfg)
        quant_key = () if qmode == "bf16" else ((qmode,),)
        key_pre = (pipe_key, zero_key) + recompute_key + tp_overlap_key + quant_key + (
                   treedef, tuple(scan_idx), tuple(bcast_idx),
                   tuple((i, _static_key(v)) for i, v in sorted(static.items())),
                   tuple((v.shape, str(v.dtype)) for v in scan_vals),
                   tuple(scan_meta),
                   tuple((v.shape, str(v.dtype)) for v in bcast_vals),
                   getattr(self, "_has_backward", True), fused)
        key_post = (model.training if model is not None else None,
                    hmode, masked)
        key = ((state.generation,) + key_pre
               + (opt._serial if fused else None,) + key_post)
        # Disk-cache key: generation and optimizer serial are per-process
        # instance counters that can never match across a restart — the
        # disk entry drops both and relies on the lowered-module hash
        # (verified at load) to catch any content difference they guarded.
        disk_key_src = key_pre + (None,) + key_post
        compiled = self._cache.get(key)
        cache_events = telemetry.counter(
            "smp_step_compile_cache_total",
            "compiled-step cache lookups by outcome",
        )
        if compiled is None:
            cache_events.labels(event="miss").inc()
            # Prior-generation entries are unreachable (their key[0] can
            # never match again) — evict them so re-init cycles don't
            # accumulate dead compiled executables.
            stale = [k for k in self._cache if k[0] != state.generation]
            for k in stale:
                del self._cache[k]
            telemetry.set_phase(f"step_{state.step_count}/trace")
            t_build = time.perf_counter()
            with profiling.region("step/trace"):
                compiled = self._build(
                    model, treedef, scan_idx, bcast_idx, static, num_mb,
                    scan_meta, opt.build_update_fn() if fused else None,
                    masked=masked,
                )
            t_build = time.perf_counter() - t_build
            telemetry.histogram(
                "smp_step_trace_seconds", "step program build/trace wall time"
            ).observe(t_build)
            flight_recorder.record_compile("trace", "step", t_build)
            # The X-ray fingerprint is keyed by this cache key: one audit
            # per distinct compiled program, re-identifiable across runs.
            compiled.audit_key = hlo_audit.cache_key_hash(key)
            compiled.disk_key = exec_cache.stable_key_hash(disk_key_src)
            self._cache[key] = compiled
        else:
            cache_events.labels(event="hit").inc()
        self._last_runner = compiled
        tokens = _count_tokens(scan_vals, scan_meta)
        if tokens:
            telemetry.counter(
                "smp_step_tokens_total",
                "input tokens consumed by step invocations",
            ).inc(tokens)

        # Device placement: params already sharded; shard batch over data axes
        # (replicate arrays whose dims don't divide the mesh axes, e.g. tiny
        # test batches). Skip the dispatch when the leaf already sits on the
        # target sharding (the steady-state case).
        scan_vals = [
            _place(v, _input_sharding(mesh, cfg, v, meta))
            for v, meta in zip(scan_vals, scan_meta)
        ]
        rng = state.step_rng
        if rng is None:
            rng = state.rng_manager.next_key("step")
        loss_scale = _cached_scalar(
            state.loss_scaler.loss_scale if state.loss_scaler else 1.0
        )
        opt_state = opt._opt_state if fused else ()
        has_backward = getattr(self, "_has_backward", True)
        if model is not None:
            # Forgot-optimizer.step() detector (both paths): a pending
            # fused update OR unconsumed grads with params untouched since
            # the previous step means the last step's work is being
            # discarded. Once is normal (an eval step in between);
            # repeatedly means the model silently never learns. Counter is
            # per-model (multi-model loops warn for the forgotten one) and
            # reset by that model's optimizer.step(). Eval-only steps (no
            # backward) neither produce nor consume updates — a train step
            # followed by N eval steps before optimizer.step() is a normal
            # loop shape, so they don't count.
            stale = model._pending_update is not None or (
                model._grads_store is not None
                and model._params is getattr(model, "_params_at_step", None)
            )
            if (stale and has_backward
                    and not getattr(cfg, "fused_step_donation", False)):
                n = getattr(model, "_dropped_updates", 0) + 1
                model._dropped_updates = n
                if n == 3:
                    logger.warning(
                        "3 training steps ran without optimizer.step(): "
                        "parameter updates are computed and then "
                        "discarded, so the model is NOT learning. Call "
                        "optimizer.step() after each step (or enable "
                        "fused_step_donation to auto-install updates)."
                    )
            # An eval-only step must not clobber the pending train-step
            # state either: the fused update tuple and the fp16
            # grads-finite flag belong to the preceding train step and
            # are consumed by the upcoming optimizer.step().
            if has_backward:
                model._params_at_step = model._params
                model._pending_update = None
        in_params = model.params
        extra = ()
        if masked:
            extra = (_cached_mb_weights(
                num_mb, bucket_state["active_mb"], mesh
            ),)
        if qmode == "fp8":
            # The delayed-scaling state rides the step like the fp16
            # loss scale: last step's scales enter as a program input,
            # the rolled history + refreshed scales come back as the
            # program's quant output, absorbed below.
            extra = extra + (quant_mod.ensure_state().arrays(),)
        (grads, outputs, grads_finite, next_rng, fused_out, health_word,
         quant_out) = (
            compiled(in_params, opt_state, scan_vals, bcast_vals, rng,
                     loss_scale, *extra)
        )
        if qmode == "fp8" and quant_out:
            quant_mod.ensure_state().absorb(quant_out)
        state.step_rng = next_rng
        schema = list(getattr(compiled, "health_schema", ()) or ())
        if schema:
            # Submit the still-on-device health word: the PREVIOUS step's
            # word is decoded now (its step has finished — no sync on the
            # step just dispatched). The bisector retains references to the
            # exact dispatched inputs so a trip can re-run this step
            # eagerly with per-module checkpoints.
            bisect_fn = None
            if model is not None and model._output_aval is not None:
                reconstruct = self._make_reconstruct(
                    model, treedef, scan_idx, bcast_idx, static
                )

                def mb_args(mb, _sv=tuple(scan_vals), _sm=tuple(scan_meta),
                            _bv=tuple(bcast_vals), _rc=reconstruct):
                    leaves = [
                        stack_leaf(v, *m)[mb] for v, m in zip(_sv, _sm)
                    ]
                    return _rc(leaves, list(_bv))

                # in_params: the exact tree this step consumed. Retaining
                # it for one step keeps bisection honest when an optimizer
                # update lands before the word is decoded (it is dropped
                # with the pending entry; donated trees are detected and
                # fall back to the live params).
                bisect_fn = health.make_bisector(
                    model, self.fn, mb_args, num_mb, rng, has_backward,
                    step_params=in_params,
                )
            health.monitor.submit(
                state.step_count, health_word, schema, hmode, bisect_fn
            )
        if model is not None and has_backward:
            model._grads_finite = grads_finite
            if grads is not None:
                raw_div = getattr(compiled, "raw_divisor", None)
                if raw_div:
                    if masked:
                        # The raw accumulator holds only the active
                        # microbatches (padding carries zero weight); the
                        # lazy mean divides by the live active count.
                        raw_div = bucket_state["active_mb"]
                    model._set_raw_grads(grads, raw_div)
                else:
                    model._grads = grads
            if fused:
                if getattr(cfg, "fused_step_donation", False):
                    # Donated inputs are gone: install the update NOW and
                    # leave a self-consistent pending tuple so a following
                    # optimizer.step() no-ops instead of re-applying.
                    model.params = fused_out[0]
                    opt._opt_state = fused_out[1]
                    model._pending_update = (
                        grads, fused_out[0], fused_out[1],
                        fused_out[0], fused_out[1],
                    )
                else:
                    # Tokens of the exact inputs the fused update consumed:
                    # optimizer.step() installs the precomputed result only
                    # if neither grads, params, nor opt_state were replaced
                    # since.
                    model._pending_update = (
                        grads, fused_out[0], fused_out[1], in_params,
                        opt_state,
                    )
        if masked and bucket_state["active_mb"] < num_mb:
            # Padded microbatches computed garbage under a zero weight;
            # the user-visible StepOutput carries only the real ones
            # (padding is whole trailing microbatches by construction).
            act = bucket_state["active_mb"]
            outputs = jax.tree_util.tree_map(lambda x: x[:act], outputs)
        return grads, outputs

    @staticmethod
    def _make_reconstruct(model, treedef, scan_idx, bcast_idx, static):
        def reconstruct(mb_scan_leaves, bcast_leaves):
            leaves = [None] * treedef.num_leaves
            for i, v in zip(scan_idx, mb_scan_leaves):
                leaves[i] = v
            for i, v in zip(bcast_idx, bcast_leaves):
                leaves[i] = v
            for i, v in static.items():
                leaves[i] = v
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            return _resolve_model_refs(args, kwargs, model)

        return reconstruct

    def _build(self, model, treedef, scan_idx, bcast_idx, static, num_mb,
               scan_meta, fused_update, masked=False):
        cfg = state.cfg
        if (
            cfg.pipeline_parallel_degree > 1
            and model is not None
            and model._pipeline_spec is not None
            and model._output_aval is not None
        ):
            return self._build_pipeline(
                model, treedef, scan_idx, bcast_idx, static, num_mb,
                scan_meta, fused_update,
            )
        has_backward = getattr(self, "_has_backward", True)
        half = cfg.half_dtype
        fn = self.fn

        reconstruct = self._make_reconstruct(model, treedef, scan_idx, bcast_idx, static)

        def mb_forward(run_params, mb_scan_leaves, bcast_leaves, key):
            rngs = {
                s: jax.random.fold_in(key, h)
                for h, s in enumerate(model.rng_streams)
            }
            model._begin_step_trace(run_params, rngs)
            try:
                args, kwargs = reconstruct(mb_scan_leaves, bcast_leaves)
                out = fn(*args, **kwargs)
            finally:
                loss = model._end_step_trace()
            if has_backward and loss is None:
                raise StepUsageError(
                    "model.backward(loss) was not called in the step function."
                )
            return (loss if has_backward else jnp.zeros(())), out

        use_scaler = cfg.fp16
        # ZeRO-3 explicit gradient path: the microbatch forward runs
        # vmapped over an rdp-reshaped batch axis, so the per-slice weight
        # grads are genuine per-device partial sums and the cross-replica
        # reduction is OUR bucketed reduce-scatter (zero3_grad_reduce),
        # not a GSPMD-chosen all-reduce. Requires rdp to be the only
        # nontrivial mesh axis; other compositions keep sharded params +
        # just-in-time gathers with GSPMD-reduced grads.
        z3_manual = (
            zero_mod.zero3_manual_grads_supported(cfg) and has_backward
        )
        z3_rdp = zero_mod.rdp_size() if z3_manual else 1
        # Per-microbatch batch axis of each scan leaf (stacked inputs
        # carry their batch at 0 by the splitter's contract).
        mb_axes = [0 if stacked else axis for axis, _n, stacked in scan_meta]

        def step_impl(params, scan_leaves, bcast_leaves, rng, loss_scale,
                      mb_weights=None):
            hc = health.active()
            keys = jax.random.split(rng, num_mb)
            # Half-cast hoisted out of the microbatch scan: the cast is
            # loop-invariant, and differentiating w.r.t. the half params is
            # numerically identical (the astype VJP is an exact bf16->fp32
            # upcast of the cotangent, applied below at accumulation).
            run_params = half_cast_util(params, half)
            if has_backward:
                def scaled_fwd(run_params, mb_leaves, bcast_leaves, key):
                    loss, out = mb_forward(run_params, mb_leaves, bcast_leaves, key)
                    # fp8 delayed scaling: amax recorded during this
                    # forward are JVP-trace values — they must exit
                    # value_and_grad as aux OUTPUTS (a Python-side stash
                    # would hold dead tracers once the grad closes).
                    qd = _quant().scan_drain()
                    if qd:
                        out = (out, qd)
                    # fp16: differentiate scale*loss so half grads stay
                    # representable (reference LossScaler.backward).
                    return loss * loss_scale, out

                grad_fn = jax.value_and_grad(scaled_fwd, has_aux=True)

                use_z3 = z3_manual and zero_mod.zero3_sliceable(
                    scan_leaves, mb_axes, z3_rdp
                )
                if z3_manual and not use_z3:
                    logger.warning(
                        "zero3: a microbatch batch dim is not divisible by "
                        "rdp=%d; falling back to the GSPMD gradient "
                        "reduction for this program.", z3_rdp,
                    )
                if use_z3:
                    # Output-shape probe (abstract, no compute): the user
                    # fn's outputs must survive the slice-vmap round trip
                    # exactly — leading batch dims scale by rdp, scalars
                    # stay scalar. Outputs that don't (batch on a later
                    # axis, shapes that happen not to scale) cannot be
                    # reassembled without guessing; keep them untouched on
                    # the GSPMD gradient path instead.
                    def _out_avals(leaves):
                        def probe(rp, ls, key):
                            _, out = mb_forward(rp, ls, bcast_leaves, key)
                            return out

                        return jax.eval_shape(
                            probe, run_params, leaves, keys[0]
                        )

                    try:
                        plain_avals = _out_avals([
                            jax.ShapeDtypeStruct(l.shape[1:], l.dtype)
                            for l in scan_leaves
                        ])
                        sliced_avals = _out_avals([
                            jax.ShapeDtypeStruct(
                                l.shape[1:1 + a]
                                + (l.shape[1 + a] // z3_rdp,)
                                + l.shape[2 + a:],
                                l.dtype,
                            )
                            for l, a in zip(scan_leaves, mb_axes)
                        ])
                        use_z3 = zero_mod.zero3_outputs_mergeable(
                            plain_avals, sliced_avals, z3_rdp
                        )
                    except Exception as e:
                        use_z3 = False
                        logger.warning(
                            "zero3: output-shape probe failed (%s); "
                            "falling back to the GSPMD gradient "
                            "reduction for this program.", e,
                        )
                    if not use_z3:
                        logger.warning(
                            "zero3: step outputs are not slice-mergeable "
                            "(need leading-batch arrays or scalars); "
                            "using the GSPMD gradient reduction so "
                            "outputs stay exact."
                        )

                def z3_body(acc, xs):
                    if mb_weights is None:
                        mb_leaves, key = xs
                        wmb = None
                    else:
                        mb_leaves, key, wmb = xs
                    sliced = [
                        zero_mod.zero3_slice_batch(l, a, z3_rdp)
                        for l, a in zip(mb_leaves, mb_axes)
                    ]
                    slice_keys = jax.random.split(key, z3_rdp)

                    def slice_fwd(run_params, sl_leaves, k):
                        loss, out = mb_forward(
                            run_params, sl_leaves, bcast_leaves, k
                        )
                        return loss * loss_scale, out

                    (loss_v, out), pgrads = jax.vmap(
                        jax.value_and_grad(slice_fwd, has_aux=True),
                        in_axes=(None, 0, 0),
                    )(run_params, sliced, slice_keys)
                    grads = zero_mod.zero3_grad_reduce(
                        pgrads, params, model, name="step"
                    )
                    out = zero_mod.zero3_merge_outputs(out)
                    loss_v = jnp.mean(loss_v)
                    if wmb is not None:
                        grads = jax.tree_util.tree_map(
                            lambda g: wmb.astype(g.dtype) * g, grads
                        )
                        loss_v = loss_v * wmb
                    acc = jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(a.dtype), acc, grads
                    )
                    ys = (out, loss_v) if hc is not None else out
                    return acc, ys

                def body(acc, xs):
                    # Shape bucketing (mb_weights): padded microbatches
                    # carry a zero weight — their grads and losses are
                    # masked out exactly, and the mean below divides by
                    # the ACTIVE count, so a bucketed run's numbers equal
                    # the exact-shape run's.
                    if mb_weights is None:
                        mb_leaves, key = xs
                        wmb = None
                    else:
                        mb_leaves, key, wmb = xs
                    (loss_v, out), grads = grad_fn(
                        run_params, mb_leaves, bcast_leaves, key
                    )
                    if _quant().scan_was_drained():
                        # Unwrap the aux-threaded amax and re-record them
                        # at THIS trace level so the body-end drain ships
                        # them out of the microbatch scan.
                        out, qaux = out
                        _quant().absorb_stacked(qaux)
                    if wmb is not None:
                        grads = jax.tree_util.tree_map(
                            lambda g: wmb.astype(g.dtype) * g, grads
                        )
                        loss_v = loss_v * wmb
                    acc = jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(a.dtype), acc, grads
                    )
                    # Health sentinel: the per-microbatch loss rides out of
                    # the scan so the word records the FIRST bad microbatch.
                    ys = (out, loss_v) if hc is not None else out
                    # fp8 delayed scaling: the amax observations absorbed
                    # from the grad aux above exit the scan as stacked
                    # outputs; () outside a quant trace — the ys pytree
                    # (and the program) is unchanged at the default.
                    qd = _quant().scan_drain()
                    if qd:
                        ys = (ys, qd)
                    return acc, ys

                acc0 = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, _acc_dtype(p.dtype, cfg)), params
                )
                if zero_mod.zero3_enabled(cfg):
                    # Sharded gradient accumulator: the carry keeps the
                    # params' rdp-sharded placements across microbatches,
                    # so per-mb grads reduce INTO shards rather than
                    # materializing replicated between iterations.
                    acc0 = zero_mod.zero3_pin_grads(acc0, model)
                xs = (
                    (scan_leaves, keys) if mb_weights is None
                    else (scan_leaves, keys, mb_weights)
                )
                grads, ys = jax.lax.scan(
                    z3_body if use_z3 else body, acc0, xs
                )
                if _quant().scan_was_drained():
                    ys, qstk = ys
                    # Max over the microbatch axis: one amax per slot for
                    # the whole step, folded into the rolled history at
                    # the runner's finalize.
                    _quant().absorb_stacked(qstk)
                if hc is not None:
                    outs, losses = ys
                    hc.add_stacked("loss", losses / loss_scale)
                    hc.add_stacked("outputs", outs)
                else:
                    outs = ys
                if fused_update is not None:
                    # Fused mode: return the RAW accumulator (aliases the
                    # scan carry, no extra materialization); the averaging
                    # folds into the optimizer-update kernels in the runner,
                    # and into a lazy divide if the user reads model.grads.
                    # (Loss scaling is off in fused mode.)
                    if zero_mod.zero3_enabled(cfg):
                        grads = zero_mod.zero3_pin_grads(grads, model)
                    return grads, outs, None
                # Microbatch averaging: parity with reference
                # torch/allreduce/ddp.py:92-98 (grads divided by num_mb);
                # loss-scale undone in the same pass. Bucketed programs
                # average over the active-microbatch count instead.
                divisor = (
                    num_mb if mb_weights is None
                    else jnp.maximum(jnp.sum(mb_weights), 1.0)
                )
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g / (divisor * loss_scale)).astype(p.dtype),
                    grads, params,
                )
                if zero_mod.zero3_enabled(cfg):
                    grads = zero_mod.zero3_pin_grads(grads, model)
                finite = _grads_finite(grads) if use_scaler else None
                return grads, outs, finite

            def body(carry, xs):
                mb_leaves, key = xs
                _, out = mb_forward(run_params, mb_leaves, bcast_leaves, key)
                qd = _quant().scan_drain()
                return carry, ((out, qd) if qd else out)

            _, outs = jax.lax.scan(body, 0, (scan_leaves, keys))
            if _quant().scan_was_drained():
                outs, qstk = outs
                _quant().absorb_stacked(qstk)
            if hc is not None:
                hc.add_stacked("outputs", outs)
            return None, outs, None

        return _make_runner(step_impl, "step", scan_meta, fused_update, model,
                            raw_divisor=num_mb if fused_update is not None else None)

    def _build_pipeline(self, model, treedef, scan_idx, bcast_idx, static,
                        num_mb, scan_meta, fused_update):
        """pp > 1: one pipelined forward over all microbatches.

        The user fn is traced twice per microbatch: once with the model call
        intercepted to *capture* its inputs (loss math on the dummy output is
        dead code XLA eliminates), and once with the call *forced* to the
        pipeline's output for that microbatch to compute loss/outputs.
        Requires exactly one model(...) call per step function.

        Schedule dispatch: ``pipeline: interleaved`` (the default) lowers to
        the 1F1B executor with bounded in-flight microbatches
        (``parallel/pipeline_1f1b.py``; ``virtual_pipeline_degree > 1``
        selects its interleaved virtual-stage generalization inside the
        same entry point); ``zero_bubble`` takes the same entry point and
        selects the ZB-H1 split-backward executor (input-grad/weight-grad
        passes scheduled separately); ``simple`` / forward-only steps use
        the fill-drain executor (``parallel/pipeline.py``, which runs
        chunked layouts as sequential logical stages).
        """
        from smdistributed_modelparallel_tpu.parallel.pipeline import pipeline_forward

        has_backward = getattr(self, "_has_backward", True)
        cfg = state.cfg
        half = cfg.half_dtype
        fn = self.fn
        out_aval = model._output_aval
        reconstruct = self._make_reconstruct(model, treedef, scan_idx, bcast_idx, static)

        use_scaler = cfg.fp16
        use_1f1b = has_backward and cfg.pipeline in ("interleaved",
                                                     "zero_bubble")

        def capture_inputs(scan_leaves, bcast_leaves, keys):
            def cap_body(_, xs):
                mb_leaves, key = xs
                model._begin_capture(out_aval)
                try:
                    args, kwargs = reconstruct(mb_leaves, bcast_leaves)
                    fn(*args, **kwargs)
                finally:
                    model._end_step_trace()
                captured = model._last_captured
                if len(captured) != 1:
                    raise StepUsageError(
                        "pipeline_parallel_degree > 1 requires exactly one "
                        f"model(...) call per step function (got {len(captured)})."
                    )
                return 0, captured[0]

            _, stacked_inputs = jax.lax.scan(cap_body, 0, (scan_leaves, keys))
            return stacked_inputs

        if use_1f1b:
            from smdistributed_modelparallel_tpu.parallel.pipeline_1f1b import (
                pipeline_1f1b,
            )

            def step_impl(params, scan_leaves, bcast_leaves, rng, loss_scale):
                keys = jax.random.split(rng, num_mb)
                stacked_inputs = capture_inputs(scan_leaves, bcast_leaves, keys)
                run_p = half_cast_util(params, half)

                def mb_loss_fn(out, mb_index, key):
                    mb_leaves = [
                        jax.lax.dynamic_index_in_dim(l, mb_index, 0, keepdims=False)
                        for l in scan_leaves
                    ]
                    rngs = {
                        s: jax.random.fold_in(key, h)
                        for h, s in enumerate(model.rng_streams)
                    }
                    model._begin_force(run_p, rngs, out)
                    try:
                        args, kwargs = reconstruct(mb_leaves, bcast_leaves)
                        user_out = fn(*args, **kwargs)
                    finally:
                        loss = model._end_step_trace()
                    if loss is None:
                        raise StepUsageError(
                            "model.backward(loss) was not called in the step function."
                        )
                    return loss, user_out

                grads, losses, outs = pipeline_1f1b(
                    model, params, stacked_inputs, rng, mb_loss_fn,
                    loss_scale / num_mb,
                )
                hc = health.active()
                if hc is not None:
                    # Stage-boundary entries were contributed inside
                    # pipeline_1f1b (its tick scan is in THIS trace); the
                    # per-microbatch losses/outputs are unscaled here.
                    hc.add_stacked("loss", losses)
                    hc.add_stacked("outputs", outs)
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g / loss_scale).astype(p.dtype), grads, params
                )
                if zero_mod.zero3_enabled(cfg):
                    # pp x zero3: grads leave rdp-sharded; the reduction
                    # itself is GSPMD's (per-stage, inside the tick loop).
                    grads = zero_mod.zero3_pin_grads(grads, model)
                finite = _grads_finite(grads) if use_scaler else None
                return grads, outs, finite

            return _make_runner(
                step_impl, "step_pipeline_1f1b", scan_meta, fused_update, model
            )

        def step_impl(params, scan_leaves, bcast_leaves, rng, loss_scale):
            keys = jax.random.split(rng, num_mb)
            stacked_inputs = capture_inputs(scan_leaves, bcast_leaves, keys)
            # Health entries added INSIDE forward_all belong to the
            # value_and_grad inner trace; they leave through the aux output
            # (names are static Python and escape via this box) and are
            # restored into the step-trace collector afterwards.
            health_names = []

            def forward_all(p):
                hc = health.active()
                hmark = hc.mark() if hc is not None else 0
                run_p = half_cast_util(p, half)
                outs, pipe_aux = pipeline_forward(model, run_p, stacked_inputs, rng)

                def post_body(_, xs):
                    mb_leaves, out, key = xs
                    rngs = {
                        s: jax.random.fold_in(key, h)
                        for h, s in enumerate(model.rng_streams)
                    }
                    model._begin_force(run_p, rngs, out)
                    try:
                        args, kwargs = reconstruct(mb_leaves, bcast_leaves)
                        user_out = fn(*args, **kwargs)
                    finally:
                        loss = model._end_step_trace()
                    if has_backward and loss is None:
                        raise StepUsageError(
                            "model.backward(loss) was not called in the step function."
                        )
                    return 0, (
                        loss if has_backward else jnp.zeros(()),
                        user_out,
                    )

                _, (losses, user_outs) = jax.lax.scan(
                    post_body, 0, (scan_leaves, outs, keys)
                )
                if hc is not None:
                    hc.add_stacked("loss", losses)
                    hc.add_stacked("outputs", user_outs)
                # MoE aux loss from the layer stack (0.0 for dense models);
                # mean-over-microbatch semantics matching the task loss.
                aux_w = float(getattr(cfg, "moe_aux_loss_weight", 1.0))
                total = jnp.mean(losses) + aux_w * pipe_aux / num_mb
                hvals = ()
                if hc is not None:
                    drained = hc.drain(hmark)
                    health_names[:] = [n for n, _, _, _ in drained]
                    hvals = tuple((b, a, m) for _, b, a, m in drained)
                return total * loss_scale, (user_outs, hvals)

            def restore_health(hvals):
                hc = health.active()
                if hc is not None:
                    hc.restore([
                        (n,) + tuple(v)
                        for n, v in zip(health_names, hvals)
                    ])

            if has_backward:
                (_, (outs, hvals)), grads = jax.value_and_grad(
                    forward_all, has_aux=True
                )(params)
                restore_health(hvals)
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g / loss_scale).astype(p.dtype), grads, params
                )
                if zero_mod.zero3_enabled(cfg):
                    grads = zero_mod.zero3_pin_grads(grads, model)
                finite = _grads_finite(grads) if use_scaler else None
                return grads, outs, finite
            _, (outs, hvals) = forward_all(params)
            restore_health(hvals)
            return None, outs, None

        return _make_runner(step_impl, "step_pipeline", scan_meta, fused_update, model)


def _quant():
    """Lazy quant-module accessor for the trace-time seams (keeps the
    import out of step.py's module load order)."""
    from smdistributed_modelparallel_tpu import quant

    return quant


def _make_runner(step_impl, name, scan_meta, fused_update, model,
                 raw_divisor=None):
    """Jit + AOT-compile the full per-step program once.

    The wrapper around ``step_impl`` performs, inside the SAME compiled
    program: the microbatch restack of raw batch leaves, the RNG-key advance
    (the next step's key is a program output, so no eager dispatch per
    step), and — under ``fused_optimizer_step`` — the optimizer update
    pinned to the partitioner's param shardings. Logs the one-time compile
    report (FLOPs / bytes / peak memory — the reference's one-time Studio
    metrics upload, ``torch/step.py:295-312``). Falls back to plain jit
    dispatch if the AOT path is unavailable."""
    from smdistributed_modelparallel_tpu.utils.metrics import (
        one_time_compile_report,
    )

    param_pin = model._param_shardings if model is not None else None
    opt_pin = None
    if fused_update is not None and state.optimizer is not None:
        # Captured eagerly (shardings are not queryable on tracers).
        opt_pin = jax.tree_util.tree_map(
            lambda l: l.sharding if isinstance(l, jax.Array) else None,
            state.optimizer._opt_state,
        )

    donate = (
        fused_update is not None
        and bool(getattr(state.cfg, "fused_step_donation", False))
    )

    # Health sentinel: the collector is live for exactly the span of each
    # step-program trace; the tags it gathers fuse into one [K, 3] "health
    # word" output. With SMP_HEALTH_CHECK=off the context yields None and
    # the program is byte-identical to a build without the sentinel.
    hmode = health.mode()
    schema_box = []

    # fp8 delayed scaling (matmul_precision: fp8): the runner decides
    # ONCE, at build time, whether this program threads QuantState —
    # mirroring the health sentinel: at the "bf16" default no context
    # installs, the quant output is () (flattens to nothing), and the
    # traced program is byte-identical to a build without smp.quant.
    quanted = _quant().matmul_precision_mode(state.cfg) == "fp8"

    def full_impl(params, opt_state, raw_scan, bcast_vals, rng, loss_scale,
                  *extra):
        # `extra` is the shape-bucketing microbatch-weight vector when the
        # step engine built a masked program, then the QuantState arrays
        # under fp8; empty otherwise (and the traced program is
        # byte-identical to the pre-bucketing build).
        qarrs = None
        if quanted:
            qarrs = extra[-1]
            extra = extra[:-1]
        with _quant().step_trace(qarrs), health.collecting(hmode) as hc:
            if hc is not None and hc.mode == "full":
                hc.add_tree("params", params)
            use_rng, next_rng = jax.random.split(rng)
            scan_leaves = [
                stack_leaf(v, *m) for v, m in zip(raw_scan, scan_meta)
            ]
            grads, outs, finite = step_impl(
                params, scan_leaves, bcast_vals, use_rng, loss_scale, *extra
            )
            if fused_update is not None:
                upd_grads = grads
                if raw_divisor is not None:
                    # Average the raw accumulator on the way into the update —
                    # this divide fuses into the optimizer's elementwise kernels
                    # instead of materializing an averaged-grads output. Under
                    # shape bucketing the accumulator holds only the ACTIVE
                    # microbatches' (weighted) grads, so the mean divides by
                    # the live active count instead of the static num_mb.
                    divisor = (
                        jnp.maximum(jnp.sum(extra[0]), 1.0) if extra
                        else raw_divisor
                    )
                    upd_grads = jax.tree_util.tree_map(
                        lambda g, p: (g / divisor).astype(p.dtype),
                        grads, params,
                    )
                new_params, new_opt = fused_update(params, opt_state, upd_grads)
                if param_pin is not None:
                    new_params = jax.lax.with_sharding_constraint(new_params, param_pin)
                if opt_pin is not None:
                    new_opt = jax.tree_util.tree_map(
                        lambda l, s: jax.lax.with_sharding_constraint(l, s)
                        if s is not None else l,
                        new_opt, opt_pin,
                        is_leaf=lambda x: x is None,
                    )
                fused_out = (new_params, new_opt)
            else:
                upd_grads = grads
                fused_out = ()
            if hc is not None and upd_grads is not None:
                # Global (averaged) grads: one entry for the whole tree.
                hc.add_tree("grads", upd_grads)
            word = ()
            if hc is not None:
                packed, names = hc.pack()
                if packed is not None:
                    word = packed
                    schema_box[:] = names
            # Rolled amax history + refreshed scales — the program's
            # quant output, absorbed into state.quant_state by the step
            # engine. () when not quanted: the flat outputs (and the
            # compiled program) are unchanged.
            qout = _quant().finalize(qarrs) if quanted else ()
        return grads, outs, finite, next_rng, fused_out, word, qout

    # fused_step_donation: params/opt_state buffers alias into
    # new_params/new_opt (same shapes + pinned shardings), dropping the
    # extra copy from peak HBM; the runner installs the update eagerly.
    jitted = jax.jit(full_impl, donate_argnums=(0, 1) if donate else ())
    mesh = state.mesh
    holder = {}

    def run(params, opt_state, scan_vals, bcast_vals, rng, loss_scale,
            *extra):
        with jax.set_mesh(mesh):
            if "compiled" not in holder:
                compiled = None
                source = "fresh"
                module_sha = None
                telemetry.set_phase(f"compile/{name}")
                t_lower = t_compile = 0.0
                disk_key = getattr(run, "disk_key", None)
                use_cache = bool(disk_key) and exec_cache.enabled()
                try:
                    # Trace+lower ALWAYS runs — shared by the fresh and
                    # warm paths (and, under the executable cache, the
                    # content check that catches changed user code or
                    # optimizer constants the shape key cannot see).
                    # Timed separately from the compile so the warm-start
                    # win (compile -> deserialize) is attributable.
                    t0 = time.perf_counter()
                    with profiling.region("step/lower"):
                        lowered = jitted.lower(
                            params, opt_state, scan_vals, bcast_vals,
                            rng, loss_scale, *extra,
                        )
                        if use_cache:
                            module_sha = exec_cache.module_hash(lowered)
                    t_lower = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    with profiling.region("step/compile"):
                        if use_cache:
                            # Persistent executable cache (smp.exec_cache):
                            # a verified disk hit replaces the XLA compile.
                            # The X-ray gauges/flight event are re-published
                            # from the post-load audit inside load(), so
                            # warm starts never bypass the drift gates.
                            with profiling.region("step/exec_cache_load"):
                                compiled, cached_audit = exec_cache.load(
                                    name, disk_key, module_sha=module_sha,
                                    params=params,
                                    expected_param_shardings=param_pin,
                                )
                            if compiled is not None:
                                source = "disk_cache"
                                run.hlo_audit = cached_audit
                        if compiled is None:
                            compiled = lowered.compile()
                    t_compile = time.perf_counter() - t0
                    state.last_compile_report = one_time_compile_report(
                        name, compiled
                    )
                except Exception as e:  # pragma: no cover - backend-specific
                    # A compile-time RESOURCE_EXHAUSTED gets its post-mortem
                    # here; the jit fallback below will hit it again and
                    # raise through the guarded call path.
                    health.maybe_oom_postmortem(name, None, e)
                    logger.debug("AOT compile report unavailable: %s", e)
                telemetry.histogram(
                    "smp_step_lower_seconds",
                    "trace+lower wall time (paid by fresh and warm paths)",
                ).observe(t_lower)
                telemetry.histogram(
                    "smp_step_compile_seconds",
                    "XLA compile wall time (disk_cache source: "
                    "deserialize+verify instead of compile)",
                ).labels(source=source).observe(t_compile)
                flight_recorder.record_compile("lower", name, t_lower)
                flight_recorder.record_compile("xla_compile", name, t_compile)
                exec_cache.record_compile_event(name, source, t_compile)
                if compiled is not None and source == "fresh":
                    # Compiled-program X-ray (smp.xray): collective census
                    # + replication detector + remat/memory fingerprint of
                    # the program just built. SMP_HLO_AUDIT=off makes this
                    # a no-op before the executable is touched.
                    run.hlo_audit = hlo_audit.maybe_audit(
                        name, compiled,
                        key=getattr(run, "audit_key", None),
                        params=params,
                        expected_param_shardings=param_pin,
                    )
                    if use_cache:
                        with profiling.region("step/exec_cache_store"):
                            exec_cache.store(
                                name, disk_key, compiled,
                                module_sha=module_sha,
                                audit=run.hlo_audit,
                                compile_seconds=t_compile,
                            )
                telemetry.set_phase(f"run/{name}")
                holder["compiled"] = compiled
            c = holder["compiled"]
            if c is not None:
                try:
                    with profiling.region("step/dispatch"):
                        return c(params, opt_state, scan_vals, bcast_vals,
                                 rng, loss_scale, *extra)
                except (TypeError, ValueError) as e:
                    # Input aval/sharding mismatch only (the step cache keys
                    # on shapes, so this is a layout drift, e.g. resharded
                    # params after checkpoint load). Real runtime failures
                    # (XlaRuntimeError etc.) propagate.
                    logger.warning(
                        "AOT step executable rejected inputs (%s); "
                        "falling back to jit dispatch.", e,
                    )
                    holder["compiled"] = None
                except Exception as e:
                    # RESOURCE_EXHAUSTED: dump the executable's XLA memory
                    # breakdown + live buffers + remat/offload config before
                    # the error reaches the user (utils/health.py).
                    health.maybe_oom_postmortem(name, c, e)
                    raise
            try:
                with profiling.region("step/dispatch"):
                    return jitted(params, opt_state, scan_vals, bcast_vals,
                                  rng, loss_scale, *extra)
            except Exception as e:
                health.maybe_oom_postmortem(name, holder.get("compiled"), e)
                raise

    run.jitted = jitted
    run.mesh = mesh
    run.holder = holder
    run.step_name = name
    run.raw_divisor = raw_divisor if fused_update is not None else None
    run.health_schema = schema_box
    return run


def _count_tokens(scan_vals, scan_meta):
    """Token count of one step's batch for the telemetry throughput
    counter: leading batch dims x sequence dim of the FIRST batch-like scan
    input ([B, T, ...] raw; [num_mb, mb, T, ...] pre-stacked). A proxy, not
    an exact semantic count — inputs without a sequence dim count their
    batch elements."""
    for v, (axis, num_mb, stacked) in zip(scan_vals, scan_meta):
        shape = getattr(v, "shape", None)
        if not shape or len(shape) < 2:
            continue
        lead = min(3 if stacked else 2, len(shape))
        tokens = 1
        for d in shape[:lead]:
            tokens *= int(d)
        return tokens
    return 0


def _place(v, sharding):
    if isinstance(v, jax.Array) and v.sharding == sharding:
        return v
    return jax.device_put(v, sharding)


_SCALAR_CACHE = {}


def _cached_scalar(value):
    """Device scalar for a host float, cached: avoids a host->device
    transfer per step for values that change rarely (the loss scale)."""
    key = float(value)
    out = _SCALAR_CACHE.get(key)
    if out is None:
        if len(_SCALAR_CACHE) > 64:
            _SCALAR_CACHE.clear()
        out = jnp.asarray(key, jnp.float32)
        _SCALAR_CACHE[key] = out
    return out


_MB_WEIGHTS_CACHE = {}


def _cached_mb_weights(num_mb, active, mesh):
    """Replicated [num_mb] 0/1 weight vector for a bucketed step: ones for
    the active (real) microbatches, zeros for the padding. Cached per
    occupancy so steady-state bucketed steps pay no host->device
    transfer."""
    import numpy as np

    key = (num_mb, active, mesh)
    out = _MB_WEIGHTS_CACHE.get(key)
    if out is None:
        if len(_MB_WEIGHTS_CACHE) > 64:
            _MB_WEIGHTS_CACHE.clear()
        w = np.zeros((num_mb,), np.float32)
        w[:active] = 1.0
        out = jax.device_put(w, NamedSharding(mesh, P()))
        _MB_WEIGHTS_CACHE[key] = out
    return out


def _apply_shape_buckets(args, kwargs, arg_names, splitter, policy, cfg):
    """Pad batch/sequence dims of the splittable step inputs up to the
    configured ``SMP_SHAPE_BUCKETS`` boundaries.

    Returns ``(args, kwargs, bucket_state)``; ``bucket_state`` is None
    when no masked program is needed (policy doesn't apply, batch already
    above every bucket, padding would create a partial microbatch, or
    the path doesn't support masking) and ``{"active_mb": k, ...}`` when
    the engine should build/reuse the microbatch-masked program.

    Exactness contract: batch padding fills whole trailing microbatches
    (rejected as ``unbucketable`` otherwise), masked to zero weight inside
    the compiled program — losses/grads equal the exact-shape run's.
    Sequence padding appends ``seq_pad``-valued positions on the right;
    masking those is the model's contract (causal attention + ignore-index
    losses are unaffected).
    """
    from smdistributed_modelparallel_tpu.backend.split import _is_array

    num_mb = cfg.microbatches
    # Masked batch bucketing composes with the plain scan path (fused
    # optimizer included — the update's microbatch divisor becomes the
    # active count); the pipeline schedules bake the microbatch layout
    # into the program and stay exact-shape.
    maskable = cfg.pipeline_parallel_degree <= 1

    def leaf_axis_pairs(value, name):
        if name is not None and name in splitter.non_split_inputs:
            return []
        axis = splitter.input_split_axes.get(name, 0)
        return [
            (leaf, axis)
            for leaf in jax.tree_util.tree_leaves(
                value, is_leaf=lambda x: hasattr(x, "smp_slice")
            )
            if _is_array(leaf) and not hasattr(leaf, "smp_slice")
            and leaf.ndim > axis
        ]

    named = [
        (v, arg_names[i] if i < len(arg_names) else None)
        for i, v in enumerate(args)
    ] + [(v, k) for k, v in kwargs.items()]
    pairs = [p for v, n in named for p in leaf_axis_pairs(v, n)]
    if not pairs:
        return args, kwargs, None
    batch = int(pairs[0][0].shape[pairs[0][1]])
    ref_seq = None
    for leaf, axis in pairs:
        if leaf.ndim > axis + 1:
            ref_seq = int(leaf.shape[axis + 1])
            break

    batch_tgt = None
    active_mb = None
    if maskable and policy.get("batch"):
        tgt = exec_cache.bucket_for(batch, policy["batch"])
        if tgt is None:
            exec_cache.record_bucket("unbucketable")
            logger.debug(
                "shape buckets: batch %d exceeds every bucket %s; exact "
                "compile.", batch, policy["batch"],
            )
        elif tgt % num_mb != 0 or batch % max(tgt // num_mb, 1) != 0:
            # A partial microbatch cannot be masked exactly (its loss
            # would mix real and padded rows); fall back to the exact
            # shape rather than silently change the numbers.
            exec_cache.record_bucket("unbucketable")
            logger.debug(
                "shape buckets: batch %d -> bucket %d not maskable at "
                "microbatches=%d; exact compile.", batch, tgt, num_mb,
            )
        else:
            batch_tgt = tgt
            active_mb = batch // (tgt // num_mb)
            exec_cache.record_bucket(
                "padded" if tgt != batch else "exact"
            )
    seq_tgt = None
    if policy.get("seq") and ref_seq is not None:
        st = exec_cache.bucket_for(ref_seq, policy["seq"])
        if st is not None and st != ref_seq:
            seq_tgt = st

    if batch_tgt is None and seq_tgt is None:
        return args, kwargs, None

    def pad_leaf(leaf, axis):
        pads = [(0, 0)] * leaf.ndim
        changed = False
        if (batch_tgt is not None and batch_tgt != batch
                and leaf.shape[axis] == batch):
            pads[axis] = (0, batch_tgt - batch)
            changed = True
        if changed:
            leaf = jnp.pad(leaf, pads)
            pads = [(0, 0)] * leaf.ndim
            changed = False
        if (seq_tgt is not None and leaf.ndim > axis + 1
                and leaf.shape[axis + 1] == ref_seq):
            pads[axis + 1] = (0, seq_tgt - ref_seq)
            leaf = jnp.pad(
                leaf, pads, constant_values=policy.get("seq_pad", 0)
            )
        return leaf

    def pad_value(value, name):
        if name is not None and name in splitter.non_split_inputs:
            return value
        axis = splitter.input_split_axes.get(name, 0)
        return jax.tree_util.tree_map(
            lambda leaf: pad_leaf(leaf, axis)
            if _is_array(leaf) and not hasattr(leaf, "smp_slice")
            and leaf.ndim > axis else leaf,
            value,
            is_leaf=lambda x: hasattr(x, "smp_slice"),
        )

    new_args = tuple(
        pad_value(v, arg_names[i] if i < len(arg_names) else None)
        for i, v in enumerate(args)
    )
    new_kwargs = {k: pad_value(v, k) for k, v in kwargs.items()}
    if batch_tgt is None:
        # Sequence-only padding needs no mask: the program is the
        # standard one at the bucketed shape.
        return new_args, new_kwargs, None
    return new_args, new_kwargs, {
        "active_mb": int(active_mb),
        "num_mb": int(num_mb),
        "batch": int(batch),
        "batch_target": int(batch_tgt),
        "seq_target": seq_tgt,
    }


def _input_sharding(mesh, cfg, arr, meta):
    """Batch sharding for a raw (or pre-stacked) scan input, dropping mesh
    axes that don't divide the corresponding dim (falls back to
    replication). For raw leaves the divisibility check applies to the
    post-stack per-microbatch dim."""
    axis, num_mb, stacked = meta
    ndim = len(arr.shape)
    spec = list(batch_spec(
        cfg, ndim, batch_axis=0 if stacked else axis, stacked=stacked
    ))
    batch_dim = 1 if stacked else axis
    for dim, axes in enumerate(spec):
        if axes is None:
            continue
        axes_tuple = axes if isinstance(axes, tuple) else (axes,)
        size = 1
        for a in axes_tuple:
            size *= mesh.shape[a]
        dim_size = arr.shape[dim]
        if dim == batch_dim and not stacked:
            dim_size = dim_size // num_mb
        if dim_size % size != 0:
            spec[dim] = None
    return NamedSharding(mesh, P(*spec))


def _grads_finite(grads):
    """Single bool: every grad element finite (the reference's overflow
    allgather across pp+tp collapses to this reduction under SPMD)."""
    leaves = [jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)]
    out = leaves[0]
    for l in leaves[1:]:
        out = jnp.logical_and(out, l)
    return out


def _acc_dtype(dtype, cfg):
    if jnp.issubdtype(dtype, jnp.floating) and cfg._fp32_grad_accumulation:
        return jnp.float32
    return dtype


def _resolve_model_refs(args, kwargs, model):
    def res(v):
        return model if isinstance(v, _ModelRef) else v

    args = jax.tree_util.tree_map(
        res, args, is_leaf=lambda x: isinstance(x, _ModelRef)
    )
    kwargs = jax.tree_util.tree_map(
        res, kwargs, is_leaf=lambda x: isinstance(x, _ModelRef)
    )
    return args, kwargs


def _is_jax_type(v):
    # Python scalars stay static (hashable cache keys): users branch on them
    # (`if training:`) and flax takes them as static flags; tracing them
    # would raise TracerBoolConversionError.
    import numpy as np

    return isinstance(v, (jax.Array, np.ndarray, jnp.ndarray))


def _static_key(v):
    try:
        hash(v)
        return v
    except TypeError:
        return repr(v)


def _positional_names(fn, n):
    import inspect

    try:
        params = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):
        return [None] * n
    names = []
    for p in params:
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            names.append(p.name)
    while len(names) < n:
        names.append(None)
    return names[:n]


def step(fn=None, *, non_split_inputs=None, input_split_axes=None):
    """Decorator: ``@smp.step`` or ``@smp.step(non_split_inputs=[...])``.

    Parity: reference ``torch/step.py:118`` / ``backend/split.py`` options.
    """
    if fn is not None:
        return StepFunction(fn)

    def wrap(f):
        return StepFunction(f, non_split_inputs, input_split_axes)

    return wrap
