"""fp16 training subsystem.

Parity target: reference ``torch/fp16/`` (``Bit16_Module``,
``Bit16_Optimizer``, ``LossScaler``/``DynamicLossScaler``,
``clip_grad_norm_fp32``). Under the SPMD design the module/optimizer
wrappers dissolve: parameter casting happens in the step engine
(``step.py``: master params stay fp32, the forward runs on half casts) and
distributed grad-norm clipping is a plain ``optax.global_norm`` over the
sharded grad tree (XLA inserts the cross-rank reductions the reference's
``clip_grad_norm_fp32`` performs by hand). What remains explicit is loss
scaling.
"""

from smdistributed_modelparallel_tpu.fp16.loss_scaler import (
    DynamicLossScaler,
    LossScaler,
)
