"""Loss scalers for fp16 training.

Parity target: reference ``torch/fp16/loss_scaler.py:33-261`` —
``LossScaler`` (static) and ``DynamicLossScaler`` (overflow-driven backoff
+ growth). The reference allgathers the overflow flag across pp+tp ranks so
all ranks skip together; under SPMD the finite-check is computed inside the
one compiled step over already-synchronized grads, so agreement is
automatic (the "dynamic-loss-scale agreement" hard part of SURVEY §7).
"""

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import record_loss_scale

logger = get_logger()


class LossScaler:
    """Static loss scale. Parity: reference ``LossScaler`` (``:33-99``)."""

    def __init__(self, scale=2.0 ** 16):
        self._scale = float(scale)

    @property
    def loss_scale(self):
        return self._scale

    def update(self, found_overflow):
        if found_overflow:
            # Overflow/skip decisions are health events: counter + scale
            # gauge + a flight-recorder entry (utils/health.py reads them
            # back into step reports and post-mortems).
            record_loss_scale("static_overflow", self._scale)
            logger.warning(
                "Gradient overflow with static loss scale %.1f; step skipped.",
                self._scale,
            )

    def state_dict(self):
        return {"scale": self._scale}

    def load_state_dict(self, sd):
        self._scale = float(sd["scale"])


class DynamicLossScaler(LossScaler):
    """Dynamic loss scale: halve on overflow, double after ``scale_window``
    consecutive clean steps. Parity: reference ``DynamicLossScaler``
    (``torch/fp16/loss_scaler.py:102-261``; same defaults: init 2**32,
    factor 2, window 1000, min_scale 1).
    """

    def __init__(self, init_scale=2.0 ** 32, scale_factor=2.0,
                 scale_window=1000, min_scale=1.0, delayed_shift=1,
                 consecutive_hysteresis=False, backoff_factor=None):
        super().__init__(init_scale)
        self.scale_factor = float(scale_factor)
        # Backoff multiplier on overflow; default 1/scale_factor preserves
        # the reference DynamicLossScaler's halve-on-overflow behavior.
        self.backoff_factor = (
            1.0 / self.scale_factor if backoff_factor is None
            else float(backoff_factor)
        )
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.delayed_shift = int(delayed_shift)
        self.consecutive_hysteresis = consecutive_hysteresis
        self.cur_hysteresis = self.delayed_shift
        self._good_steps = 0
        self.overflows = 0

    def update(self, found_overflow):
        if found_overflow:
            self.overflows += 1
            if self.delayed_shift == 1 or self.cur_hysteresis == 1:
                self._scale = max(self._scale * self.backoff_factor, self.min_scale)
                logger.info("Gradient overflow; loss scale -> %.1f", self._scale)
            else:
                self.cur_hysteresis -= 1
            self._good_steps = 0
            record_loss_scale("overflow", self._scale)
        else:
            if self.consecutive_hysteresis:
                self.cur_hysteresis = self.delayed_shift
            self._good_steps += 1
            if self._good_steps % self.scale_window == 0:
                if not self.consecutive_hysteresis:
                    self.cur_hysteresis = self.delayed_shift
                self._scale *= self.scale_factor
                logger.info("Loss scale grown -> %.1f", self._scale)
                record_loss_scale("growth", self._scale)

    def state_dict(self):
        return {
            "scale": self._scale,
            "good_steps": self._good_steps,
            "cur_hysteresis": self.cur_hysteresis,
            "overflows": self.overflows,
        }

    def load_state_dict(self, sd):
        self._scale = float(sd["scale"])
        self._good_steps = int(sd.get("good_steps", 0))
        self.cur_hysteresis = int(sd.get("cur_hysteresis", self.delayed_shift))
        self.overflows = int(sd.get("overflows", 0))
