"""Continuous-batching serving engine (``smp.serving``).

``smp.generate`` is a one-shot compiled program per (batch, prompt-len,
max-new-tokens) shape: no request queue, no cache reuse across requests,
and every ragged shape churns the program cache. This engine is the
serving tier the ROADMAP's "millions of users, heavy traffic" north star
asks for, built on three pieces:

**Paged KV cache.** One pool of fixed-size token blocks per layer
(``SMP_KV_BLOCK_TOKENS`` tokens each; ``nn/utils.PagedKVCache``), shared
by every in-flight sequence through per-sequence block tables that a
host-side allocator (``serving/kv_cache.BlockAllocator``) maintains.
Sequences of wildly different lengths share the pool, and a finished
sequence's blocks are reusable the moment it completes — no
[slots, max_len] worst-case rectangle.

**Continuous batching.** Requests queue; at every engine tick the
scheduler admits arrivals into free decode slots, runs ONE batched
decode step over every in-flight stream, and runs ONE prefill slice
(``SMP_PREFILL_CHUNK`` prompt tokens) of at most one admitting request —
chunked prefill interleaves with decode so a long prompt never stalls
the streams already flowing. Exactly TWO programs compile for the whole
workload (a bucket-keyed prefill-chunk and a decode-step), AOT-lowered
through ``exec_cache.aot_compile`` so the PR-11 persistent cache
warm-starts them and the PR-9 X-ray audits them (including the serving-
specific replicated-KV-pool detector).

**SLO observability.** Every latency the SLOs care about — queue wait,
TTFT, ITL, prefill wall, decode-step wall — streams into log-bucketed
histograms (``utils/telemetry.record_serve_latency``) with p50/p90/p99
gauges; queue depth and KV-pool occupancy are gauges; windowed rates
(req/s, tok/s over the last ``SMP_TIMESERIES_INTERVAL`` window, not
lifetime averages) come from the metrics time-series snapshotter
(``utils/timeseries.MetricsTimeSeries`` — the autoscaler feed, with
``SMP_SLO`` verdicts per window). Each request also carries a trace id
through queued → admitted → prefill chunk → first token → finished as
flight-recorder events, fused by ``scripts/trace_fuse.py`` into one
Perfetto span lane per decode slot. All timestamps are host-side reads
taken after the device call returns — tracing adds no per-token device
sync. Per-request logs (prompt + sampled tokens) are retained while a
request is in flight, which is what makes requests RESTARTABLE — the
replica-failover layer (``serving/replica.py``) re-admits a dead
replica's unfinished requests from its mirrored logs (trace id
included, so the resumed stream continues the same trace), idempotent
by request id.

Sampling parity contract: a request served here produces token-for-token
what ``smp.generate`` produces for the same prompt at batch size 1 with
``rng=jax.random.key(seed)`` — same key schedule
(``split(key, max_new_tokens)``), same filter composition (temperature,
then top-k, then top-p), same greedy argmax — across the paged vs
contiguous cache layouts (asserted in ``tests/test_serving.py``).

Model support: the ``TransformerLM`` zoo family (the paged decode path
is threaded through ``models/transformer_lm.py``); other families keep
``smp.generate``.
"""

import collections
import dataclasses
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.resilience.chaos import chaos
from smdistributed_modelparallel_tpu.serving.kv_cache import (
    TRASH_BLOCK,
    BlockAllocator,
    block_tokens,
    prefill_chunk_tokens,
    serve_slots,
)
from smdistributed_modelparallel_tpu.utils import exec_cache, profiling
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPValidationError,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    record_quant_dispatch,
    record_serve_latency,
    record_serve_occupancy,
    record_serve_programs,
    record_serve_request,
    record_serve_tokens,
    record_serve_trace,
    record_weight_update,
)
from smdistributed_modelparallel_tpu.utils.fleet import fleet
from smdistributed_modelparallel_tpu.utils.timeseries import (
    MetricsTimeSeries,
)

logger = get_logger()


@dataclasses.dataclass
class ServeRequest:
    """One generation request.

    ``seed`` fixes the sampling key schedule
    (``jax.random.split(jax.random.key(seed), max_new_tokens)`` — the
    exact schedule ``smp.generate`` uses, so serving output is
    reproducible and restartable). ``arrival_s`` is the request's arrival
    offset relative to the engine's start (synthetic traces); the
    scheduler never admits a request before it "arrives".
    ``resume_tokens`` carries already-sampled tokens when a failover
    re-admits a dead replica's in-flight request: the engine prefills
    prompt+resume and continues the key schedule at index
    ``len(resume_tokens)``, reproducing the exact tokens the dead replica
    would have produced. ``trace_id`` names the request's span trace in
    the flight-recorder ring (defaults to the request id at submit);
    failover re-admission carries the original id through the mirror
    log, so the resumed stream continues the SAME trace on the
    surviving replica.
    """

    request_id: str
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token_id: Optional[int] = None
    seed: int = 0
    arrival_s: float = 0.0
    deadline_s: Optional[float] = None
    resume_tokens: Tuple[int, ...] = ()
    trace_id: Optional[str] = None


def serve_request_from_record(rec):
    """Rebuild a restartable ``ServeRequest`` from a mirror-log record
    (the wire format ``_mirror`` writes). Used by replica failover and
    by the controller's drain protocol: the already-sampled tokens ride
    as ``resume_tokens`` so the re-admitting engine continues the key
    schedule exactly where the record left off, and the original trace
    id rides along so the fused timeline shows ONE request."""
    return ServeRequest(
        request_id=rec["rid"],
        prompt=rec["prompt"],
        max_new_tokens=rec["max_new_tokens"],
        temperature=rec.get("temperature", 0.0),
        top_k=rec.get("top_k"),
        top_p=rec.get("top_p"),
        eos_token_id=rec.get("eos_token_id"),
        seed=rec.get("seed", 0),
        deadline_s=rec.get("deadline_s"),
        resume_tokens=tuple(rec.get("tokens", ())),
        trace_id=rec.get("trace_id"),
    )


def serve_request_to_record(req):
    """Inverse of ``serve_request_from_record``: serialize a
    ``ServeRequest`` into the mirror-record wire format so the router
    can ship it to a remote replica as plain JSON."""
    return {
        "rid": req.request_id,
        "prompt": list(map(int, req.prompt)),
        "max_new_tokens": int(req.max_new_tokens),
        "temperature": req.temperature,
        "top_k": req.top_k,
        "top_p": req.top_p,
        "eos_token_id": req.eos_token_id,
        "seed": req.seed,
        "deadline_s": req.deadline_s,
        "tokens": list(map(int, req.resume_tokens)),
        "done": False,
        "trace_id": req.trace_id,
    }


class _Slot:
    __slots__ = (
        "req", "sid", "idx", "prompt_full", "resume_len", "pos",
        "new_tokens", "state", "rng_data", "t_arrival", "t_admit",
        "t_first_token", "t_last_token",
    )

    def __init__(self, req, rng_data, t_arrival, t_admit, idx):
        self.req = req
        self.sid = req.request_id
        self.idx = idx                   # decode-slot index (trace lane)
        self.prompt_full = list(map(int, req.prompt)) + list(
            map(int, req.resume_tokens)
        )
        self.resume_len = len(req.resume_tokens)
        self.pos = 0                     # tokens cached so far
        self.new_tokens = []             # sampled THIS incarnation
        self.state = "prefill"
        self.rng_data = rng_data         # [max_new, 2] uint32
        self.t_arrival = t_arrival
        self.t_admit = t_admit
        self.t_first_token = None
        self.t_last_token = None

    @property
    def sample_index(self):
        """Index into the request's key schedule for the NEXT sample."""
        return self.resume_len + len(self.new_tokens)

    @property
    def remaining(self):
        return self.req.max_new_tokens - self.sample_index

    @property
    def total_tokens(self):
        """Worst-case sequence length at completion."""
        return len(self.req.prompt) + self.req.max_new_tokens

    @property
    def all_tokens(self):
        return list(self.req.resume_tokens) + self.new_tokens


def _sample_rows(logits, temps, top_ks, top_ps, key_data):
    """Per-row sampler over [B, V] fp32 logits with traced per-row
    sampling parameters (one compiled program serves every request mix).

    Composition mirrors ``generation._make_sampler`` exactly —
    temperature scale, then top-k, then top-p on the k-filtered logits,
    then ``jax.random.categorical`` on a [1, V] row — so a single-request
    stream is token-for-token identical to ``smp.generate`` at batch 1.
    ``top_ks <= 0`` and ``top_ps >= 1`` disable the filters;
    ``temps <= 0`` is greedy argmax (keys unused).
    """
    import jax
    import jax.numpy as jnp

    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1)

    def stochastic(_):
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        k_idx = jnp.clip(top_ks, 1, V) - 1
        kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
        keep_k = (top_ks[:, None] <= 0) | (scaled >= kth)
        filtered = jnp.where(keep_k, scaled, -jnp.inf)
        sorted_p = jnp.sort(filtered, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_p, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_p = (cum - probs) < top_ps[:, None]
        thresh = jnp.min(
            jnp.where(keep_p, sorted_p, jnp.inf), axis=-1, keepdims=True
        )
        filtered = jnp.where(filtered >= thresh, filtered, -jnp.inf)

        def row(kd, lg):
            key = jax.random.wrap_key_data(kd)
            return jax.random.categorical(key, lg[None, :], axis=-1)[0]

        return jax.vmap(row)(key_data, filtered)

    # All-greedy batches (the serving default) skip the two full-vocab
    # sorts + softmax/cumsum at runtime — still ONE compiled program.
    sampled = jax.lax.cond(
        jnp.all(temps <= 0.0), lambda _: greedy, stochastic, None
    )
    return jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)


class ServingEngine:
    """Continuous-batching serving engine over a paged KV cache.

    Args:
      model: a ``TransformerLM`` (zoo family) module, or a
        ``DistributedModel`` wrapping one (pp-trained stacks regather for
        decode exactly like ``smp.generate``).
      params: parameter tree override (required for a raw module unless
        it was initialized through a ``DistributedModel``).
      max_slots: concurrent decode streams (default ``SMP_SERVE_SLOTS``).
      num_blocks: KV-pool size in blocks, INCLUDING the reserved trash
        block. Default fully provisions ``max_slots`` worst-case
        sequences; size it below that to let paging earn its keep —
        admission then waits for free blocks instead of OOMing.
      block_tokens / prefill_chunk: geometry overrides (default
        ``SMP_KV_BLOCK_TOKENS`` / ``SMP_PREFILL_CHUNK``).
    """

    def __init__(self, model, params=None, *, max_slots=None,
                 num_blocks=None, block_tokens_override=None,
                 prefill_chunk=None):
        import jax

        if hasattr(model, "module"):  # DistributedModel
            module = model.module
            if params is None:
                pp_active = (
                    state.cfg is not None
                    and state.cfg.pipeline_parallel_degree > 1
                )
                params = (
                    model.regather_for_decode() if pp_active
                    else model.params
                )
        else:
            module = model
        if params is None:
            raise SMPValidationError(
                "ServingEngine(module, ...) requires params=... (or pass "
                "an initialized DistributedModel)."
            )
        if "paged_blocks" not in getattr(module, "__dataclass_fields__", {}):
            raise SMPValidationError(
                f"{type(module).__name__} does not support paged decoding;"
                " smp.serving drives the TransformerLM zoo family (other "
                "families keep smp.generate)."
            )
        self.module = module
        from smdistributed_modelparallel_tpu import quant as quant_mod

        # SMP_DECODE_WEIGHTS=int8: weight-only quantization, applied ONCE
        # here (and at adopt_params) — the resident tree is int8 + per-
        # output-channel scales; programs dequantize on the way in.
        self._wq = quant_mod.decode_weights_mode() == "int8"
        if self._wq:
            params = quant_mod.quantize_decode_params(params)
            record_quant_dispatch("decode_weights", "int8")
        if quant_mod.kv_quant_mode() == "int8":
            record_quant_dispatch("kv_cache", "int8")
        self.params = params
        self.max_len = int(module.max_len)
        self.bt = int(block_tokens_override or block_tokens())
        self.chunk = int(prefill_chunk or prefill_chunk_tokens())
        self.slots_n = int(max_slots or serve_slots())
        self.max_blocks_per_seq = -(-self.max_len // self.bt)
        if num_blocks is None:
            num_blocks = 1 + self.slots_n * self.max_blocks_per_seq
        self.alloc = BlockAllocator(
            int(num_blocks), self.bt, self.max_blocks_per_seq
        )
        self.half = state.cfg.half_dtype if state.cfg is not None else None
        self.decode_mod = module.clone(
            paged_blocks=int(num_blocks), paged_block_tokens=self.bt,
            deterministic=True, decode=False, decode_cache_len=None,
        )
        self._mesh = state.mesh if state.initialized else None
        if self._mesh is not None:
            me = jax.process_index()
            if any(
                d.process_index != me for d in self._mesh.devices.flat
            ):
                # Multi-process world: serving runs dp-REPLICATED — each
                # replica compiles process-local programs (a cross-process
                # mesh would lockstep every replica into one collective
                # program, defeating independent streams and failover).
                self._mesh = None
        self._slots = [None] * self.slots_n
        self._queue = collections.deque()
        self._prefill_rr = 0
        self.results = {}
        self.finished = set()
        self._arrival_s = {}     # rid -> effective arrival (engine clock)
        self._occupancy_snap = None
        self.last_tick_worked = True
        self.mirror_log = {}     # rid -> restartable record (failover)
        self._dirty = set()      # rids with unmirrored progress
        self._admit_order = []   # rids in admission order (chaos seam)
        self._programs = {}
        self.audits = {}         # program kind -> ProgramAudit | None
        self._admitting = True   # drain protocol: False = quiesced
        self.weights_version = 0  # bumped by adopt_params (live updates)
        self.stats = collections.Counter()
        self._t0 = None
        self._gen_tokens = 0
        self._cache = self._init_cache()
        # Per-block KV bytes, summed over every cache leaf keyed by pool
        # block (all layers' K/V pools + any int8 scale sidecars) — the
        # multiplier behind the smp_serve_kv_bytes gauges, so the pool-
        # bytes halving under SMP_KV_QUANT=int8 is observable, not
        # inferred.
        nb = self.alloc.num_blocks
        self.kv_block_bytes = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(self._cache)
            if nb in getattr(leaf, "shape", ())
        ) // nb
        self._chips = max(len(jax.local_devices()), 1)
        # Metrics time-series snapshotter (the autoscaler feed):
        # SMP_TIMESERIES_INTERVAL=0 (the default) constructs NOTHING —
        # no ring, no thread. When armed, the engine also polls it from
        # the tick path so window edges stay sharp while the loop is
        # busy; the thread only covers idle gaps.
        self.timeseries = MetricsTimeSeries.from_env(chips=self._chips)
        if self.timeseries is not None:
            self.timeseries.start()

    def close(self):
        """Stop the time-series snapshotter thread, if armed, and stop
        admitting. Idempotent; the engine remains usable for draining
        (sampling continues via tick polling).

        A close with work still queued or in flight must not silently
        abandon it: every unfinished request's restartable record is
        re-marked dirty so the replica layer's next ``drain_dirty`` ships
        a final mirror frame — a peer can re-admit what this engine never
        served — and the abandonment is counted
        (``smp_serve_requests_total{event="abandoned"}``)."""
        self.quiesce()
        abandoned = [q.request_id for q in self._queue] + [
            s.sid for s in self._slots if s is not None
        ]
        for rid in abandoned:
            if rid in self.mirror_log:
                self._dirty.add(rid)
            record_serve_trace("abandoned", rid, detail="close")
        if abandoned:
            record_serve_request("abandoned", len(abandoned))
            logger.warning(
                "[serving] close() with %d unfinished request(s); their "
                "restartable records are mirror-logged for re-admission "
                "elsewhere.", len(abandoned),
            )
        if self.timeseries is not None:
            self.timeseries.stop()

    # -- drain protocol (scale-down / weight adoption / clean close) ----

    @property
    def in_flight(self):
        """Admitted, unfinished streams (excludes the queue)."""
        return sum(1 for s in self._slots if s is not None)

    def quiesce(self):
        """Stop admission: queued requests stay queued, in-flight streams
        keep decoding. ``submit`` refuses new work while quiesced (the
        router must not route to a draining replica). Idempotent."""
        if self._admitting:
            self._admitting = False
            record_serve_trace("quiesce", "-", detail="admission stopped")

    def resume_admission(self):
        """Reopen admission after a quiesce/drain (weight adoption and
        canary flows drain to idle, adopt, then resume)."""
        if not self._admitting:
            self._admitting = True
            record_serve_trace("resume_admission", "-")

    def drain(self, timeout_s=120.0):
        """The scale-down drain protocol: stop admitting, finish every
        IN-FLIGHT stream to completion, and hand back the queued-but-
        never-admitted requests as restartable straggler records for
        re-admission elsewhere (router/controller re-route them; submit
        idempotency guarantees zero duplicated tokens, the finished
        streams guarantee zero dropped ones).

        Returns the list of straggler mirror records (possibly empty).
        The engine stays usable afterwards — ``resume_admission()``
        reopens intake."""
        self.quiesce()
        stragglers = []
        while self._queue:
            req = self._queue.popleft()
            self._arrival_s.pop(req.request_id, None)
            rec = self.mirror_log.get(req.request_id)
            if rec is None:  # pragma: no cover - submit always mirrors
                self._mirror(req, list(req.resume_tokens), done=False)
                rec = self.mirror_log[req.request_id]
            stragglers.append(dict(rec, tokens=list(rec["tokens"])))
            self._dirty.add(req.request_id)
            record_serve_trace(
                "drained_straggler", req.request_id, trace=req.trace_id,
            )
        if stragglers:
            record_serve_request("drained_straggler", len(stragglers))
        deadline = time.monotonic() + timeout_s
        while self.in_flight:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"drain exceeded {timeout_s:g}s with "
                    f"{self.in_flight} stream(s) still in flight."
                )
            self.step()
            if not self.last_tick_worked:
                time.sleep(0.001)
        record_serve_trace(
            "drained", "-", detail=f"stragglers={len(stragglers)}",
        )
        return stragglers

    def adopt_params(self, params, *, version=None):
        """Live weight update: swap the parameter tree between ticks with
        ZERO recompile. The compiled programs take params as a call
        argument and their cache keys are weight-free (shapes, knobs,
        topology — never values), so adoption is a pointer swap; the
        compile-event ledger proves it (``compile_fresh`` must stay flat
        across the adoption — asserted in tests, gated by
        ``smp_weight_update_seconds``).

        Streams must not be mid-flight (their KV holds the OLD weights'
        activations): quiesce + drain to idle first — queued requests are
        fine, they prefill under the new weights. Raises on a tree whose
        structure/shapes/dtypes differ from the serving programs' avals
        (that WOULD recompile; re-shard the checkpoint instead)."""
        import jax

        if self.in_flight:
            raise SMPValidationError(
                f"adopt_params with {self.in_flight} stream(s) in flight "
                "would mix weights mid-stream; quiesce() and drain to "
                "idle first."
            )
        t0 = time.perf_counter()
        mark = exec_cache.compile_event_mark()
        new_version = (
            int(version) if version is not None else self.weights_version + 1
        )
        params = chaos.on_weight_update(new_version, params)
        if self._wq:
            # Quantize BEFORE the aval comparison: the resident tree is
            # the quantized layout, so like compares with like and the
            # compiled programs' input avals stay satisfied.
            from smdistributed_modelparallel_tpu import quant as quant_mod

            params = quant_mod.quantize_decode_params(params)
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(params)
        if old_def != new_def or [
            (getattr(a, "shape", None), getattr(a, "dtype", None))
            for a in old_leaves
        ] != [
            (getattr(a, "shape", None), getattr(a, "dtype", None))
            for a in new_leaves
        ]:
            raise SMPValidationError(
                "adopt_params: the new checkpoint's tree does not match "
                "the serving programs' parameter avals (structure/shape/"
                "dtype) — adopting it would force a recompile. Load the "
                "checkpoint through the shard catalog for this topology."
            )
        self.params = params
        self.weights_version = new_version
        fresh = sum(
            1 for e in exec_cache.compile_events_since(mark)
            if e.get("source") == "fresh"
        )
        seconds = time.perf_counter() - t0
        record_weight_update(seconds, self.weights_version, fresh=fresh)
        logger.info(
            "[serving] adopted weights version %s in %.3fs "
            "(fresh compiles: %d)", self.weights_version, seconds, fresh,
        )
        return seconds

    # -- device state ---------------------------------------------------

    def _init_cache(self):
        import jax
        import jax.numpy as jnp

        paged0 = {
            "block_tables": jnp.zeros(
                (1, self.max_blocks_per_seq), jnp.int32
            ),
            "positions": jnp.zeros((1,), jnp.int32),
            "valid": jnp.zeros((1,), jnp.int32),
        }

        def shape_fn(p):
            return self.decode_mod.apply(
                {"params": self._deq_params(p)},
                jnp.zeros((1, 1), jnp.int32), paged=paged0,
                mutable=["cache"],
            )[1]["cache"]

        shapes = jax.eval_shape(shape_fn, self.params)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes
        )

    # -- compiled programs ---------------------------------------------

    def _deq_params(self, params):
        """Weight-only int8: expand the resident {q, s} tree back to the
        module's float params INSIDE the program (the dequant fuses into
        the consuming matmuls' HBM reads). No-op at the default."""
        if self._wq:
            from smdistributed_modelparallel_tpu import quant as quant_mod

            params = quant_mod.dequantize_decode_params(params)
        return params

    def _half_params(self, params):
        from smdistributed_modelparallel_tpu.nn.utils import half_cast

        return half_cast(params, self.half)

    def _program(self, kind):
        """The two bucket-keyed programs: ``prefill`` ([1, chunk] tokens)
        and ``decode`` ([slots] single tokens). AOT-compiled through
        ``exec_cache.aot_compile`` (persistent warm start + X-ray audit,
        including the replicated-KV-pool detector)."""
        prog = self._programs.get(kind)
        if prog is not None:
            return prog
        import functools

        import jax
        import jax.numpy as jnp

        from smdistributed_modelparallel_tpu.utils import hlo_audit

        S, MB, C = self.slots_n, self.max_blocks_per_seq, self.chunk

        if kind == "decode":
            def fn(params, cache, toks, positions, tables, temps, top_ks,
                   top_ps, key_data):
                params = self._half_params(self._deq_params(params))
                logits, mut = self.decode_mod.apply(
                    {"params": params, "cache": cache}, toks[:, None],
                    paged={"block_tables": tables, "positions": positions},
                    mutable=["cache"],
                )
                nxt = _sample_rows(
                    logits[:, -1].astype(jnp.float32), temps, top_ks,
                    top_ps, key_data,
                )
                return nxt, mut["cache"]

            args = (
                self.params, self._cache,
                jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
                jnp.zeros((S, MB), jnp.int32), jnp.zeros((S,), jnp.float32),
                jnp.zeros((S,), jnp.int32), jnp.ones((S,), jnp.float32),
                jnp.zeros((S, 2), jnp.uint32),
            )
        elif kind == "prefill":
            def fn(params, cache, toks, table, start, valid, temps,
                   top_ks, top_ps, key_data):
                params = self._half_params(self._deq_params(params))
                logits, mut = self.decode_mod.apply(
                    {"params": params, "cache": cache}, toks,
                    paged={"block_tables": table, "positions": start,
                           "valid": valid},
                    mutable=["cache"],
                )
                last = jnp.take_along_axis(
                    logits, (valid - 1)[:, None, None], axis=1
                )[:, 0].astype(jnp.float32)
                tok = _sample_rows(last, temps, top_ks, top_ps, key_data)
                return tok, mut["cache"]

            args = (
                self.params, self._cache,
                jnp.zeros((1, C), jnp.int32), jnp.zeros((1, MB), jnp.int32),
                jnp.zeros((1,), jnp.int32), jnp.ones((1,), jnp.int32),
                jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.int32),
                jnp.ones((1,), jnp.float32), jnp.zeros((1, 2), jnp.uint32),
            )
        else:  # pragma: no cover - internal misuse
            raise ValueError(kind)

        name = f"serving_{kind}"
        from smdistributed_modelparallel_tpu import quant as quant_mod

        key_src = (
            "serving", kind, repr(self.decode_mod), S, MB, C, self.bt,
            str(self.half),
            tuple(sorted(self._mesh.shape.items())) if self._mesh else None,
        ) + quant_mod.serving_key_suffix()
        findings_fn = functools.partial(
            hlo_audit.serving_kv_findings, cache_template=self._cache
        )
        with profiling.region(f"serve/compile_{kind}"):
            jitted = jax.jit(fn, donate_argnums=(1,))
            if self._mesh is not None:
                with jax.set_mesh(self._mesh):
                    lowered = jitted.lower(*args)
                    compiled, audit, source = exec_cache.aot_compile(
                        name, key_src, lowered, params=self.params,
                        extra_findings_fn=findings_fn,
                        tp_ring_expected=False,
                    )
            else:
                lowered = jitted.lower(*args)
                compiled, audit, source = exec_cache.aot_compile(
                    name, key_src, lowered, params=self.params,
                    extra_findings_fn=findings_fn,
                    tp_ring_expected=False,
                )
        self.audits[kind] = audit
        self._programs[kind] = compiled
        record_serve_programs(len(self._programs))
        logger.info(
            "[serving] %s program ready (%s): slots=%d chunk=%d "
            "block_tokens=%d pool_blocks=%d", kind, source, S, C, self.bt,
            self.alloc.num_blocks,
        )
        return compiled

    # -- request intake -------------------------------------------------

    def submit(self, req):
        """Queue a request. Idempotent by request id: a rid that already
        finished (or is queued/in flight) is skipped — re-admitting the
        same request after a failover must not double-serve it."""
        if req.request_id in self.finished:
            return False
        if not self._admitting:
            # Quiesced/draining: new work belongs on another replica (the
            # router never routes here; a direct submit is refused so the
            # drain's "stop admitting" contract holds).
            return False
        if any(s is not None and s.sid == req.request_id
               for s in self._slots):
            return False
        if any(q.request_id == req.request_id for q in self._queue):
            return False
        total = len(req.prompt) + req.max_new_tokens
        if total > self.max_len:
            raise SMPValidationError(
                f"request {req.request_id!r}: prompt + max_new_tokens "
                f"({total}) exceeds the model's position limit "
                f"({self.max_len})."
            )
        if total > self.max_blocks_per_seq * self.bt:
            raise SMPValidationError(
                f"request {req.request_id!r}: {total} tokens exceed the "
                f"per-sequence table capacity "
                f"({self.max_blocks_per_seq * self.bt})."
            )
        req.trace_id = req.trace_id or req.request_id
        if len(req.resume_tokens) >= req.max_new_tokens:
            # Nothing left to generate: the dead replica had finished
            # sampling but not reported — complete it locally.
            self.results[req.request_id] = list(req.resume_tokens)
            self.finished.add(req.request_id)
            self._mirror(req, list(req.resume_tokens), done=True)
            record_serve_request("finished")
            record_serve_trace("queued", req.request_id, trace=req.trace_id)
            record_serve_trace(
                "finished", req.request_id, trace=req.trace_id,
                pos=len(req.resume_tokens), detail="fully_resumed",
            )
            return True
        self._queue.append(req)
        # A live submission "arrives" NOW (long-lived engine clock);
        # synthetic traces may place the arrival later. TTFT/deadline
        # measure from this instant, never from engine start.
        self._arrival_s[req.request_id] = max(
            self._now(), float(req.arrival_s)
        )
        # Mirrored from SUBMIT time, not admission: a replica dying with
        # requests still queued must not lose them — the survivor
        # re-admits queued and in-flight requests alike.
        self._mirror(req, list(req.resume_tokens), done=False)
        record_serve_trace("queued", req.request_id, trace=req.trace_id)
        return True

    def _rng_schedule(self, req):
        import jax

        keys = jax.random.split(
            jax.random.key(req.seed), req.max_new_tokens
        )
        data = np.asarray(jax.random.key_data(keys))
        if data.shape != (req.max_new_tokens, 2):  # pragma: no cover
            raise SMPValidationError(
                "unexpected PRNG key layout; smp.serving needs the "
                "2-word threefry key schedule smp.generate uses."
            )
        return data.astype(np.uint32)

    def _mirror(self, req, tokens, done):
        rid = req.request_id
        self.mirror_log[rid] = {
            "rid": rid,
            "prompt": list(map(int, req.prompt)),
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "top_k": req.top_k,
            "top_p": req.top_p,
            "eos_token_id": req.eos_token_id,
            "seed": int(req.seed),
            "deadline_s": req.deadline_s,
            "tokens": list(map(int, tokens)),
            "done": bool(done),
            # Trace continuity across failover: the surviving replica
            # re-admits under the SAME trace id, so the fused timeline
            # shows one request spanning both replicas' rings.
            "trace_id": req.trace_id or rid,
        }
        self._dirty.add(rid)

    def drain_dirty(self):
        """(rid, record) pairs with unmirrored progress — the replica
        layer ships these to peers and clears the dirty set."""
        out = [(rid, self.mirror_log[rid]) for rid in sorted(self._dirty)]
        self._dirty.clear()
        return out

    # -- scheduling -----------------------------------------------------

    @property
    def busy(self):
        return bool(self._queue) or any(
            s is not None for s in self._slots
        )

    def _now(self):
        if self._t0 is None:
            self._t0 = time.monotonic()
        return time.monotonic() - self._t0

    def _admit(self, now):
        if not self._admitting:
            return 0  # quiesced: the queue holds for drain/stragglers
        admitted = 0
        while self._queue:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                break
            # Arrival-ordered admission; requests that haven't "arrived"
            # yet (synthetic traces) gate everything behind them.
            req = self._queue[0]
            arrival = self._arrival_s.get(
                req.request_id, max(req.arrival_s, 0.0)
            )
            if arrival > now:
                break
            need = len(req.prompt) + req.max_new_tokens
            if not self.alloc.can_reserve(need):
                break
            self._queue.popleft()
            self._arrival_s.pop(req.request_id, None)
            self.alloc.reserve(req.request_id, need)
            idx = free[0]
            slot = _Slot(
                req, self._rng_schedule(req),
                t_arrival=arrival, t_admit=now, idx=idx,
            )
            self._slots[idx] = slot
            self._admit_order.append(req.request_id)
            self._mirror(req, slot.all_tokens, done=False)
            record_serve_request("admitted")
            record_serve_latency("queue_wait", max(now - arrival, 0.0))
            record_serve_trace(
                "readmitted" if slot.resume_len else "admitted",
                req.request_id, trace=req.trace_id, slot=idx,
                pos=slot.resume_len,
            )
            self.stats["admitted"] += 1
            admitted += 1
        return admitted

    def _sampling_row(self, slot):
        req = slot.req
        return (
            float(req.temperature),
            int(req.top_k or 0),
            float(req.top_p if req.top_p is not None else 1.0),
        )

    def _finish(self, idx, now):
        slot = self._slots[idx]
        rid = slot.sid
        self.results[rid] = slot.all_tokens
        self.finished.add(rid)
        self._slots[idx] = None
        self.alloc.release(rid)
        self._mirror(slot.req, slot.all_tokens, done=True)
        record_serve_request("finished")
        if slot.req.deadline_s is not None and (
            now - slot.t_arrival > slot.req.deadline_s
        ):
            record_serve_request("deadline_miss")
        self.stats["finished"] += 1
        # Throughput gauges (req/s, tok/s) are owned by the time-series
        # snapshotter now: counter deltas over its window, not lifetime
        # or ad-hoc sliding averages.
        record_serve_trace(
            "finished", rid, trace=slot.req.trace_id, slot=slot.idx,
            pos=len(slot.all_tokens),
        )

    def _on_token(self, slot, tok, now):
        first = slot.t_first_token is None
        if first:
            slot.t_first_token = now
            record_serve_latency("ttft", now - slot.t_arrival)
            record_serve_latency("prefill", now - slot.t_admit)
            record_serve_trace(
                "first_token", slot.sid, trace=slot.req.trace_id,
                slot=slot.idx, pos=slot.sample_index,
            )
        else:
            record_serve_latency("itl", now - slot.t_last_token)
        slot.t_last_token = now
        slot.new_tokens.append(int(tok))
        self._gen_tokens += 1
        record_serve_tokens("generated", 1)
        self._mirror(slot.req, slot.all_tokens, done=False)
        req = slot.req
        return (
            (req.eos_token_id is not None and int(tok) == req.eos_token_id)
            or slot.remaining <= 0
        )

    def _decode_step(self):
        active = [
            (i, s) for i, s in enumerate(self._slots)
            if s is not None and s.state == "decode"
        ]
        if not active:
            return False
        S, MB = self.slots_n, self.max_blocks_per_seq
        toks = np.zeros((S,), np.int32)
        positions = np.zeros((S,), np.int32)
        tables = np.full((S, MB), TRASH_BLOCK, np.int32)
        temps = np.zeros((S,), np.float32)
        top_ks = np.zeros((S,), np.int32)
        top_ps = np.ones((S,), np.float32)
        kd = np.zeros((S, 2), np.uint32)
        for i, slot in active:
            # The decode input is the latest sampled token; its K/V are
            # written at `pos`, so the allocator must cover pos+1 tokens.
            self.alloc.ensure(slot.sid, slot.pos + 1)
            toks[i] = slot.all_tokens[-1]
            positions[i] = slot.pos
            tables[i] = self.alloc.table(slot.sid)
            temps[i], top_ks[i], top_ps[i] = self._sampling_row(slot)
            kd[i] = slot.rng_data[slot.sample_index]
        program = self._program("decode")
        t_dispatch = self._now()
        with profiling.region("serve/decode_step"):
            sampled, self._cache = program(
                self.params, self._cache, toks, positions, tables, temps,
                top_ks, top_ps, kd,
            )
        sampled = np.asarray(sampled)
        self.stats["decode_steps"] += 1
        # Token timestamps read the clock AFTER the device call — the
        # dispatch+compute wall belongs to this token's latency. (The
        # np.asarray transfer above is the step's natural sync point; no
        # extra block_until_ready is ever issued on this path.)
        now = self._now()
        record_serve_latency("decode_step", max(now - t_dispatch, 0.0))
        for i, slot in active:
            slot.pos += 1
            if self._on_token(slot, sampled[i], now):
                self._finish(i, now)
        return True

    def _prefill_tick(self):
        prefilling = [
            (i, s) for i, s in enumerate(self._slots)
            if s is not None and s.state == "prefill"
        ]
        if not prefilling:
            return False
        # Round-robin across admitting requests so two long prompts make
        # progress together.
        self._prefill_rr += 1
        i, slot = prefilling[self._prefill_rr % len(prefilling)]
        P = len(slot.prompt_full)
        C = self.chunk
        valid = min(C, P - slot.pos)
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :valid] = slot.prompt_full[slot.pos:slot.pos + valid]
        self.alloc.ensure(slot.sid, slot.pos + valid)
        table = np.asarray([self.alloc.table(slot.sid)], np.int32)
        temps, top_ks, top_ps = self._sampling_row(slot)
        kd = slot.rng_data[slot.sample_index][None, :]
        program = self._program("prefill")
        with profiling.region("serve/prefill_chunk"):
            tok, self._cache = program(
                self.params, self._cache, chunk, table,
                np.asarray([slot.pos], np.int32),
                np.asarray([valid], np.int32),
                np.asarray([temps], np.float32),
                np.asarray([top_ks], np.int32),
                np.asarray([top_ps], np.float32),
                kd.astype(np.uint32),
            )
        slot.pos += valid
        self.stats["prefill_chunks"] += 1
        record_serve_tokens("prompt", valid)
        record_serve_trace(
            "prefill_chunk", slot.sid, trace=slot.req.trace_id,
            slot=slot.idx, pos=slot.pos, detail=f"valid={valid}",
        )
        if slot.pos >= P:
            # Prompt fully cached: the program's sample from the last
            # real position is the stream's first token (TTFT).
            slot.state = "decode"
            now = self._now()
            if self._on_token(slot, int(np.asarray(tok)[0]), now):
                self._finish(i, now)
        return True

    def _publish_occupancy(self):
        snap = (
            len(self._queue),
            sum(1 for s in self._slots if s is not None),
            self.alloc.used_blocks,
            self.alloc.reserved_unallocated,
        )
        if snap == self._occupancy_snap:
            return  # idle ticks must not spam the gauge registry
        self._occupancy_snap = snap
        record_serve_occupancy(
            queue_depth=snap[0],
            active_slots=snap[1],
            total_slots=self.slots_n,
            kv_used=snap[2],
            kv_free=self.alloc.free_blocks,
            kv_reserved=snap[3],
            kv_total=self.alloc.num_blocks,
            block_bytes=self.kv_block_bytes,
        )

    def _progress_of_admitted(self, n):
        """Chaos probe: (tokens emitted, finished?) of the n-th admitted
        request (1-based), or None when fewer than n were admitted."""
        if n < 1 or n > len(self._admit_order):
            return None
        rid = self._admit_order[n - 1]
        if rid in self.finished:
            return (len(self.results[rid]), True)
        for s in self._slots:
            if s is not None and s.sid == rid:
                return (len(s.all_tokens), False)
        return (0, False)

    def step(self):
        """One engine tick: admit arrivals into free slots, run one
        batched decode step, run one prefill chunk. Returns True while
        work remains; ``last_tick_worked`` says whether this tick did
        anything (False = waiting on arrivals or KV blocks — callers
        should back off instead of spinning)."""
        now = self._now()
        worked = bool(self._admit(now))
        worked = self._decode_step() or worked
        chaos.on_serve_decode(self._progress_of_admitted)
        worked = self._prefill_tick() or worked
        self._publish_occupancy()
        if self.timeseries is not None:
            self.timeseries.maybe_sample()
        # Same idle-gap contract as the time-series poll above: the
        # fleet publisher/aggregator ticks inline so a busy decode loop
        # keeps the fleet feed fresh (no-op when SMP_FLEET_INTERVAL is
        # off).
        fleet.tick()
        self.last_tick_worked = worked
        return self.busy

    def run(self, requests=(), timeout_s=300.0):
        """Submit ``requests`` and tick until every queued/in-flight
        request completes (or ``timeout_s`` elapses). Returns
        ``{request_id: generated token list}``."""
        for req in requests:
            self.submit(req)
        deadline = time.monotonic() + timeout_s
        while self.busy:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"serving run exceeded {timeout_s:g}s with "
                    f"{len(self._queue)} queued and "
                    f"{sum(1 for s in self._slots if s)} in flight."
                )
            self.step()
            if not self.last_tick_worked:
                # Waiting on an arrival or on KV blocks: don't burn a
                # host core polling.
                time.sleep(0.001)
        return dict(self.results)
