"""Host-side block allocator for the paged KV cache (``smp.serving``).

The device side (``nn/utils.PagedKVCache``) is a dumb pool of
``num_blocks`` fixed-size token blocks per layer; everything that makes
it *paged* lives here: a free list, per-sequence ordered block lists, and
the block tables the compiled programs consume. The allocator is plain
python (no jax imports — it runs in the serving engine's host loop every
tick) and deliberately strict: double-frees, foreign blocks, and
over-capacity growth raise instead of corrupting the pool, and the fuzz
test in ``tests/test_serving.py`` holds it to "never double-assign,
never leak".

Block 0 is RESERVED as the trash block: unused block-table entries point
at it, so writes from inactive decode slots and padded prefill tails
land there instead of in live sequences (see ``PagedKVCache``).

Admission safety: ``reserve`` books a sequence's worst-case block count
(prompt + max_new_tokens) without allocating; ``ensure`` then allocates
lazily as the sequence actually grows. A request is only admitted when
its worst case fits in ``free + unallocated-reservation`` headroom, so a
mid-stream pool exhaustion is impossible by construction — while
finished sequences still release every block (and their unused
reservation) immediately, which is what lets wildly different sequence
lengths share one pool.
"""

import os

from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

BLOCK_TOKENS_ENV = "SMP_KV_BLOCK_TOKENS"
PREFILL_CHUNK_ENV = "SMP_PREFILL_CHUNK"
SLOTS_ENV = "SMP_SERVE_SLOTS"

#: Reserved trash block (see module docstring).
TRASH_BLOCK = 0


def _env_int(name, default, floor=1):
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        logger.warning("ignoring non-integer %s=%r; using %d.",
                       name, raw, default)
        return default
    if val < floor:
        logger.warning("%s=%d below the floor %d; using %d.",
                       name, val, floor, floor)
        return floor
    return val


def block_tokens(default=16):
    """Tokens per KV-cache block (``SMP_KV_BLOCK_TOKENS``, default 16)."""
    return _env_int(BLOCK_TOKENS_ENV, default)


def prefill_chunk_tokens(default=32):
    """Prompt tokens per prefill slice (``SMP_PREFILL_CHUNK``, default
    32): one slice runs per engine tick, interleaved with decode steps,
    so a long prompt never stalls in-flight streams."""
    return _env_int(PREFILL_CHUNK_ENV, default)


def serve_slots(default=4):
    """Concurrent decode slots of the engine (``SMP_SERVE_SLOTS``)."""
    return _env_int(SLOTS_ENV, default)


class BlockAllocator:
    """Free list + per-sequence block tables over a fixed pool."""

    def __init__(self, num_blocks, block_tokens, max_blocks_per_seq):
        if num_blocks < 2:
            raise ValueError(
                "the pool needs at least 2 blocks (block 0 is the "
                "reserved trash block)."
            )
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self.max_blocks_per_seq = max_blocks_per_seq
        # LIFO free list: recently freed blocks are re-used first (their
        # pool slots are the likeliest still in cache on the host side,
        # and determinism helps the tests).
        self._free = list(range(num_blocks - 1, TRASH_BLOCK, -1))
        self._owned = {}      # sid -> ordered block ids
        self._reserved = {}   # sid -> worst-case block count

    # -- bookkeeping ----------------------------------------------------

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def used_blocks(self):
        return sum(len(b) for b in self._owned.values())

    @property
    def reserved_unallocated(self):
        """Blocks promised to admitted sequences but not yet allocated."""
        return sum(
            max(r - len(self._owned.get(sid, ())), 0)
            for sid, r in self._reserved.items()
        )

    def blocks_for_tokens(self, tokens):
        return -(-int(tokens) // self.block_tokens)  # ceil div

    def can_reserve(self, tokens):
        """True when a sequence of worst-case ``tokens`` length can be
        admitted without any possibility of mid-stream exhaustion."""
        need = self.blocks_for_tokens(tokens)
        if need > self.max_blocks_per_seq:
            return False
        return need <= self.free_blocks - self.reserved_unallocated

    # -- lifecycle ------------------------------------------------------

    def reserve(self, sid, tokens):
        if sid in self._reserved or sid in self._owned:
            raise ValueError(f"sequence {sid!r} already admitted")
        if not self.can_reserve(tokens):
            raise ValueError(
                f"pool cannot admit {sid!r} ({tokens} tokens): "
                f"{self.free_blocks} free, "
                f"{self.reserved_unallocated} already promised"
            )
        self._reserved[sid] = self.blocks_for_tokens(tokens)
        self._owned.setdefault(sid, [])

    def ensure(self, sid, tokens):
        """Allocate blocks so ``sid`` can hold ``tokens`` tokens."""
        if sid not in self._reserved:
            raise ValueError(f"sequence {sid!r} was never reserved")
        need = self.blocks_for_tokens(tokens)
        if need > self._reserved[sid]:
            raise ValueError(
                f"sequence {sid!r} grew past its reservation "
                f"({need} > {self._reserved[sid]} blocks)"
            )
        owned = self._owned[sid]
        while len(owned) < need:
            owned.append(self._free.pop())

    def release(self, sid):
        """Return every block (and the unused reservation) to the pool."""
        blocks = self._owned.pop(sid, [])
        self._reserved.pop(sid, None)
        self._free.extend(reversed(blocks))
        return len(blocks)

    def table(self, sid):
        """The sequence's block table as a fixed-width python list
        (length ``max_blocks_per_seq``; unused entries = trash block)."""
        row = [TRASH_BLOCK] * self.max_blocks_per_seq
        for j, b in enumerate(self._owned.get(sid, ())):
            row[j] = b
        return row

    def check(self):
        """Invariant audit (used by the fuzz test): every block is in
        exactly one place — the free list or one sequence's table — and
        the trash block is in neither."""
        seen = {}
        for b in self._free:
            seen[b] = seen.get(b, 0) + 1
        for sid, blocks in self._owned.items():
            for b in blocks:
                seen[b] = seen.get(b, 0) + 1
        problems = []
        if TRASH_BLOCK in seen:
            problems.append("trash block handed out")
        for b, n in seen.items():
            if n > 1:
                problems.append(f"block {b} assigned {n} times")
        missing = set(range(1, self.num_blocks)) - set(seen)
        if missing:
            problems.append(f"blocks leaked: {sorted(missing)}")
        return problems
