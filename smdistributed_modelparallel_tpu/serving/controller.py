"""Serving control plane (``smp.serving.controller``): SLO-driven
autoscaling, canaried live weight updates, and the drain protocol.

Armed by ``SMP_AUTOSCALE`` — unset, ``ServingController.from_env``
returns None and NOTHING is constructed: no thread, no bus traffic, no
telemetry registration (the PR-16/17/18 zero-cost-off convention,
asserted by the disarmed tests). Armed, the controller runs a control
loop on the fleet-aggregator rank that closes the loop the sensor PRs
opened: the fleet plane's aggregated windows (queue depth, TTFT/ITL
percentiles, tok/s, serve goodput) are evaluated against the
``SMP_SLO`` targets, and sustained breach/headroom becomes a scale
event instead of a dashboard alert.

Policy shape (``AutoscalePolicy``): **hysteresis** — a single bad
window never scales (``SMP_AUTOSCALE_HYSTERESIS`` consecutive breached
windows fire "up"; the same count of comfortable windows — SLO met,
queue empty, every upper-bound metric under half its threshold — fires
"down"); **cooldown** — after any event the policy holds fire for
``SMP_AUTOSCALE_COOLDOWN`` seconds so a slow-to-drain queue cannot flap
the fleet; **clamps** — ``SMP_AUTOSCALE_MIN``/``SMP_AUTOSCALE_MAX``
bound the replica count absolutely.

Scale-up rides the recovery machinery: a standby replica is activated
through the supervisor rendezvous path and compiles from the shared
exec cache (warm start — the ready report carries the compile-source
counts so ``fresh == 0`` is assertable), and the event records MTTR
phases exactly like a recovery: ``trigger`` (first breached window ->
decision) -> ``rendezvous`` -> ``warm_start`` -> ``first_token``.

Scale-down is the new DRAIN protocol: the victim replica stops
admitting, finishes its in-flight streams (their tokens are already
sampled — moving them would break the key schedule), and hands its
queued-never-admitted requests back as restartable mirror records the
router re-dispatches to the survivors. Zero dropped, zero duplicated
tokens — the E2E asserts token parity against a never-scaled run.

Live weight updates exploit the engine's weight-free program-cache
keys (params are call arguments, not compile constants):
``adopt_params`` swaps checkpoints between ticks with ZERO recompiles
(``smp_weight_update_seconds`` + a fresh-compile count of 0 prove it).
Blue/green: ``start_canary`` replays pinned prompts against the old
and new weights on the canary replica — ``smp.generate`` parity is the
oracle, bit-for-bit — then shifts ``SMP_CANARY_FRACTION`` of traffic
to the new version and watches ``SMP_CANARY_WINDOWS`` SLO windows.
Token mismatch or a breached window auto-rolls back (old weights
restored, split dropped, ``smp_canary_rollback_total`` latched, one
forensics bundle triggered); survival promotes the version fleet-wide.

Every decision lands in three places: ``smp_controller_*`` /
``smp_autoscale_*`` gauges, flight-recorder ``controller`` events (the
trace_fuse lane), and the ``SMP_CONTROLLER_PATH`` JSONL feed that
``scripts/slo_report.py --controller`` renders and gates.
"""

import dataclasses
import json
import os
import time

from smdistributed_modelparallel_tpu.resilience.chaos import chaos
from smdistributed_modelparallel_tpu.serving.engine import (
    serve_request_from_record,
)
from smdistributed_modelparallel_tpu.serving.router import (
    LocalReplicaHandle,
    RequestRouter,
)
from smdistributed_modelparallel_tpu.utils import exec_cache
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPValidationError,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    record_canary,
    record_controller_replicas,
    record_drain_stragglers,
    record_scale_event,
)
from smdistributed_modelparallel_tpu.utils.timeseries import (
    evaluate_slo,
    parse_slo,
)

logger = get_logger()

AUTOSCALE_ENV = "SMP_AUTOSCALE"
COOLDOWN_ENV = "SMP_AUTOSCALE_COOLDOWN"
MIN_ENV = "SMP_AUTOSCALE_MIN"
MAX_ENV = "SMP_AUTOSCALE_MAX"
HYSTERESIS_ENV = "SMP_AUTOSCALE_HYSTERESIS"
PATH_ENV = "SMP_CONTROLLER_PATH"
CANARY_FRACTION_ENV = "SMP_CANARY_FRACTION"
CANARY_WINDOWS_ENV = "SMP_CANARY_WINDOWS"

_TRUTHY = ("1", "on", "true", "yes")

#: Armed controllers, for core.shutdown / state.reset (lazy hooks — the
#: backend must not import this module unless something constructed one).
_ACTIVE = []


def _env_float(name, default):
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using default %g.",
                       name, raw, default)
        return default


def _env_int(name, default):
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using default %d.",
                       name, raw, default)
        return default


def _trigger_forensics(reason, detail=""):
    try:
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        goodput.trigger_forensics(reason, detail=detail)
    except Exception:
        logger.warning("forensics trigger (%s) failed", reason,
                       exc_info=True)


def shutdown_all():
    """core.shutdown hook: close pending scale events and unregister
    every armed controller (before the fleet plane stops — the last
    events still want the bus)."""
    for c in list(_ACTIVE):
        try:
            c.stop()
        except Exception:
            logger.warning("controller shutdown failed", exc_info=True)


def reset_all():
    """state.reset hook: drop registrations without running teardown
    (tests re-init from scratch)."""
    del _ACTIVE[:]


class AutoscalePolicy:
    """Pure decision function: windows in, "up"/"down"/None out.

    Deliberately free of I/O and injectable-clocked so the policy units
    run on a fake clock — hysteresis in both directions, the cooldown
    latch, min/max clamps and flap suppression are all table-driven
    tests, not sleeps."""

    def __init__(self, slo=None, *, min_replicas=1, max_replicas=4,
                 cooldown_s=30.0, hysteresis=2, scale_down_ratio=0.5,
                 clock=time.monotonic):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise SMPValidationError(
                f"autoscale clamps must satisfy 1 <= min <= max, got "
                f"min={min_replicas} max={max_replicas}."
            )
        if hysteresis < 1:
            raise SMPValidationError(
                f"autoscale hysteresis must be >= 1, got {hysteresis}."
            )
        self.slo = dict(slo or {})
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.hysteresis = int(hysteresis)
        self.scale_down_ratio = float(scale_down_ratio)
        self._clock = clock
        self._breach = 0
        self._comfort = 0
        self._last_event = None
        #: wall of the tick that STARTED the current streak — the scale
        #: event's ``trigger`` phase (how long the breach went unanswered).
        self.streak_started = None
        self.fired_streak_started = None
        self.last_verdict = {"ok": True, "violations": {}}

    def _headroom(self, window):
        """True when every upper-bound SLO metric present in the window
        sits under ``scale_down_ratio`` of its threshold — merely
        meeting the SLO is not evidence a replica is surplus."""
        for key, limit in self.slo.items():
            if key.endswith("_min") or key == "queue_depth":
                continue
            value = window.get(key)
            if value is not None and value > limit * self.scale_down_ratio:
                return False
        return True

    def observe(self, window, live, now=None):
        """Feed one aggregated window; returns "up", "down" or None.
        ``live`` is the current live-replica count (for the clamps)."""
        now = self._clock() if now is None else now
        verdict = (
            evaluate_slo(self.slo, window)
            if self.slo else {"ok": True, "violations": {}}
        )
        self.last_verdict = verdict
        breached = not verdict["ok"]
        comfortable = (
            not breached
            and float(window.get("queue_depth") or 0) == 0.0
            and self._headroom(window)
        )
        if breached:
            if self._breach == 0:
                self.streak_started = now
            self._breach += 1
            self._comfort = 0
        elif comfortable:
            if self._comfort == 0:
                self.streak_started = now
            self._comfort += 1
            self._breach = 0
        else:
            self._breach = 0
            self._comfort = 0
            self.streak_started = None
        in_cooldown = (
            self._last_event is not None
            and now - self._last_event < self.cooldown_s
        )
        if in_cooldown:
            return None
        if breached and self._breach >= self.hysteresis:
            if live >= self.max_replicas:
                return None   # clamped: keep the streak, re-ask next tick
            self._fire(now)
            return "up"
        if comfortable and self._comfort >= self.hysteresis:
            if live <= self.min_replicas:
                return None
            self._fire(now)
            return "down"
        return None

    def _fire(self, now):
        self._last_event = now
        self._breach = 0
        self._comfort = 0
        # Keep the fired streak's start readable: the scale event's
        # ``trigger`` phase is how long the breach went unanswered.
        self.fired_streak_started = self.streak_started
        self.streak_started = None


class ServingController:
    """The armed control loop: owns a ``RequestRouter``, a standby
    list, the scale-event ledger and the canary state machine."""

    def __init__(self, router=None, policy=None, *, window_source=None,
                 path=None, canary_fraction=0.25, canary_windows=2,
                 clock=time.monotonic):
        self.router = router if router is not None else RequestRouter()
        self.policy = policy if policy is not None else AutoscalePolicy()
        self._window_source = window_source
        self.path = path
        self.canary_fraction = float(canary_fraction)
        self.canary_windows = int(canary_windows)
        self._clock = clock
        self._standby = []          # (name, activate_fn) in preference order
        self._order = []            # activation order, scale-down victims
        self._pending = []          # scale-up events awaiting first token
        self._retired = {}          # results of drained/detached replicas
        self._seen_seq = None
        self.scale_events = []
        self.canary = None
        self.rollbacks = 0
        self.promotions = 0
        _ACTIVE.append(self)

    # -- construction ---------------------------------------------------

    @classmethod
    def from_env(cls, router=None, window_source=None,
                 clock=time.monotonic):
        """The arming gate: ``SMP_AUTOSCALE`` unset/falsy returns None
        and constructs NOTHING."""
        if os.environ.get(AUTOSCALE_ENV, "").lower() not in _TRUTHY:
            return None
        policy = AutoscalePolicy(
            parse_slo(os.environ.get("SMP_SLO", "")),
            min_replicas=_env_int(MIN_ENV, 1),
            max_replicas=_env_int(MAX_ENV, 4),
            cooldown_s=_env_float(COOLDOWN_ENV, 30.0),
            hysteresis=_env_int(HYSTERESIS_ENV, 2),
            clock=clock,
        )
        return cls(
            router=router,
            policy=policy,
            window_source=window_source,
            path=os.environ.get(PATH_ENV) or None,
            canary_fraction=_env_float(CANARY_FRACTION_ENV, 0.25),
            canary_windows=_env_int(CANARY_WINDOWS_ENV, 2),
            clock=clock,
        )

    # -- membership -----------------------------------------------------

    def register_live(self, handle):
        """Attach an already-running replica (the deployment's initial
        set)."""
        self.router.attach(handle)
        self._order.append(handle.name)
        record_controller_replicas(len(self.router.live_handles()))
        return handle

    def add_standby(self, name, activate_fn):
        """Register scale-up capacity: ``activate_fn()`` must return a
        live router handle (building the engine is the warm start; a
        ``RemoteReplicaHandle`` wraps the rendezvous too)."""
        self._standby.append((str(name), activate_fn))

    @property
    def replicas(self):
        return len(self.router.live_handles())

    def results(self):
        merged = dict(self._retired)
        merged.update(self.router.results())
        return merged

    # -- JSONL feed -----------------------------------------------------

    def _append_jsonl(self, rec):
        if not self.path:
            return
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            logger.warning("controller feed write to %s failed",
                           self.path, exc_info=True)

    # -- control loop ---------------------------------------------------

    def _window(self):
        if self._window_source is not None:
            return self._window_source()
        from smdistributed_modelparallel_tpu.utils.fleet import fleet

        return fleet.last_window()

    def tick(self):
        """One control-loop evaluation: close pending first-token
        phases, then feed the newest UNSEEN aggregated window to the
        canary gate (when one is live) or the autoscale policy.
        Returns "up"/"down" when a scale event fired, else None."""
        self._close_pending()
        window = self._window()
        if window is None:
            return None
        seq = window.get("seq")
        if seq is not None and seq == self._seen_seq:
            return None
        self._seen_seq = seq
        if self.canary is not None:
            self._canary_window(window)
            return None
        decision = self.policy.observe(window, live=self.replicas)
        if decision == "up":
            return "up" if self.scale_up(window=window) else None
        if decision == "down":
            return "down" if self.scale_down(window=window) else None
        return None

    def _reason(self):
        bad = self.policy.last_verdict.get("violations", {})
        return "slo:" + ",".join(sorted(bad)) if bad else "headroom"

    # -- scale events ---------------------------------------------------

    def scale_up(self, reason=None, window=None):
        """Activate the next standby replica. The event's MTTR phases
        mirror a recovery: trigger (breach start -> now), rendezvous,
        warm_start (engine construction, exec-cache hot), first_token
        (closed lazily — the first request the new replica finishes)."""
        if not self._standby:
            logger.warning(
                "[controller] scale-up wanted but no standby replica is "
                "registered; staying at %d.", self.replicas,
            )
            return None
        now = self._clock()
        trigger_s = (
            max(now - self.policy.fired_streak_started, 0.0)
            if getattr(self.policy, "fired_streak_started", None)
            is not None else 0.0
        )
        name, activate_fn = self._standby.pop(0)
        t0 = self._clock()
        mark = exec_cache.compile_event_mark()
        handle = activate_fn()
        total = self._clock() - t0
        warm_s = getattr(handle, "activate_seconds", None)
        if warm_s is None:
            warm_s, rendezvous_s = total, 0.0
        else:
            rendezvous_s = max(total - warm_s, 0.0)
        # Warm-start evidence: a remote handle ships the peer's
        # compile-source counts in its ready frame; a local activation
        # compiled in-process, so read this process's event ledger.
        warm = dict(getattr(handle, "warm", None) or {})
        if not warm:
            for ev in exec_cache.compile_events_since(mark):
                src = ev.get("source", "?")
                warm[src] = warm.get(src, 0) + 1
        handle.live = True
        self.router.attach(handle)
        self._order.append(handle.name)
        event = {
            "kind": "scale_event",
            "direction": "up",
            "seq": len(self.scale_events) + 1,
            "t_wall": time.time(),
            "reason": reason or self._reason(),
            "replicas": self.replicas,
            "replica": handle.name,
            "warm": warm,
            "window_seq": window.get("seq") if window else None,
            "phases": {
                "trigger": trigger_s,
                "rendezvous": rendezvous_s,
                "warm_start": warm_s,
            },
        }
        self.scale_events.append(event)
        self._pending.append({
            "event": event,
            "handle": handle,
            "t0": self._clock(),
            "baseline": len(handle.results()),
        })
        logger.warning(
            "[controller] SCALE UP -> %d replicas (%s): trigger %.2fs, "
            "rendezvous %.2fs, warm start %.2fs.",
            self.replicas, event["reason"], trigger_s, rendezvous_s, warm_s,
        )
        return handle

    def _close_pending(self, force=False):
        for pend in list(self._pending):
            served = len(pend["handle"].results()) > pend["baseline"]
            if not served and not force:
                continue
            self._pending.remove(pend)
            first_token = self._clock() - pend["t0"] if served else 0.0
            event = pend["event"]
            event["phases"]["first_token"] = first_token
            self._finalize(event)

    def _finalize(self, event):
        event["seconds"] = sum(event["phases"].values())
        record_scale_event(
            event["direction"], event["seconds"],
            phases=event["phases"], replicas=event["replicas"],
        )
        self._append_jsonl(event)
        chaos.on_scale_event(event["seq"])

    def scale_down(self, reason=None, window=None):
        """Drain-protocol shrink: the last-activated live replica stops
        admitting, finishes its in-flight streams, and its queued
        stragglers are re-dispatched to the survivors as restartable
        mirror records. Zero dropped or duplicated tokens."""
        live = [
            self.router.handles[n] for n in self._order
            if n in self.router.handles and self.router.handles[n].live
        ]
        if len(live) <= max(self.policy.min_replicas, 1):
            return None
        self._close_pending(force=True)   # never shrink with an open event
        victim = live[-1]
        t0 = self._clock()
        stragglers = victim.drain()
        drain_s = self._clock() - t0
        self._retired.update(victim.results())
        self.router.detach(victim.name)
        self._order.remove(victim.name)
        if hasattr(victim, "deactivate"):
            victim.deactivate()
        t1 = self._clock()
        for rec in stragglers:
            self.router.dispatch(serve_request_from_record(rec))
        record_drain_stragglers(len(stragglers))
        reroute_s = self._clock() - t1
        event = {
            "kind": "scale_event",
            "direction": "down",
            "seq": len(self.scale_events) + 1,
            "t_wall": time.time(),
            "reason": reason or "sustained_headroom",
            "replicas": self.replicas,
            "replica": victim.name,
            "stragglers": len(stragglers),
            "window_seq": window.get("seq") if window else None,
            "phases": {"drain": drain_s, "reroute": reroute_s},
        }
        self.scale_events.append(event)
        self._finalize(event)
        logger.warning(
            "[controller] SCALE DOWN -> %d replicas: drained %s in "
            "%.2fs (%d straggler(s) re-dispatched).",
            self.replicas, victim.name, drain_s, len(stragglers),
        )
        return victim

    # -- live weight updates + canary -----------------------------------

    def _replay(self, engine, pinned, tag):
        """Run the pinned prompts under fresh request ids and return
        ``{original rid: tokens}`` — the bit-for-bit parity oracle."""
        fresh = [
            dataclasses.replace(
                req, request_id=f"{req.request_id}__{tag}", trace_id=None,
            )
            for req in pinned
        ]
        results = engine.run(fresh, timeout_s=120.0)
        return {
            req.request_id: list(results[f.request_id])
            for req, f in zip(pinned, fresh)
        }

    def start_canary(self, params, version, pinned, target=None):
        """Begin a blue/green rollout of ``params`` as weights version
        ``version``: token-parity gate first (pinned prompts replayed
        against old then new weights on the canary replica — any
        mismatch rolls back IMMEDIATELY), then a traffic split of
        ``canary_fraction`` watched for ``canary_windows`` clean SLO
        windows before fleet-wide promotion. Returns True when the
        canary passed the parity gate (promotion may still be pending),
        False when it rolled back."""
        if self.canary is not None:
            raise SMPValidationError(
                "a canary rollout is already in progress."
            )
        if target is None:
            target = next(
                (h for h in self.router.live_handles()
                 if isinstance(h, LocalReplicaHandle)),
                None,
            )
        if target is None or not hasattr(target, "engine"):
            raise SMPValidationError(
                "canary needs a local replica handle (an engine to "
                "replay pinned prompts on)."
            )
        engine = target.engine
        version = int(version)
        if version == engine.weights_version:
            raise SMPValidationError(
                f"canary version {version} is already live."
            )
        # Drain to idle: adopt_params refuses mid-stream swaps (a stream
        # sampled under two weight versions is silently wrong output).
        stragglers = engine.drain()
        engine.resume_admission()
        reference = self._replay(engine, pinned, f"v{engine.weights_version}")
        old_params = engine.params
        old_version = engine.weights_version
        seconds = engine.adopt_params(params, version=version)
        self._append_jsonl({
            "kind": "weight_update", "version": version,
            "seconds": seconds, "t_wall": time.time(),
        })
        candidate = self._replay(engine, pinned, f"v{version}")
        for rec in stragglers:
            self.router.dispatch(serve_request_from_record(rec))
        mismatched = sorted(
            rid for rid in reference
            if candidate.get(rid) != reference[rid]
        )
        state = {
            "version": version, "old_version": old_version,
            "old_params": old_params, "params": params,
            "target": target, "windows_ok": 0,
        }
        if mismatched:
            self.canary = state
            self._rollback_canary(
                f"token_parity:{len(mismatched)}/{len(reference)} "
                "pinned prompts diverged"
            )
            return False
        target.version = version
        record_canary("started", version,
                      detail=f"fraction={self.canary_fraction:g}")
        self._append_jsonl({
            "kind": "canary", "verdict": "started", "version": version,
            "t_wall": time.time(),
            "detail": f"fraction={self.canary_fraction:g}",
        })
        if len(self.router.live_handles()) > 1:
            self.router.set_split({
                old_version: 1.0 - self.canary_fraction,
                version: self.canary_fraction,
            })
        self.canary = state
        if self.canary_windows <= 0:
            self._promote()
        return True

    def _canary_window(self, window):
        verdict = (
            evaluate_slo(self.policy.slo, window)
            if self.policy.slo else {"ok": True, "violations": {}}
        )
        if not verdict["ok"]:
            self._rollback_canary(
                "slo_window:" + ",".join(sorted(verdict["violations"]))
            )
            return
        self.canary["windows_ok"] += 1
        if self.canary["windows_ok"] >= self.canary_windows:
            self._promote()

    def _adopt_idle(self, engine, params, version):
        """Adopt between ticks: drain in-flight work first, then swap,
        then re-admit the drained stragglers on the SAME engine (their
        sampled prefixes are already committed output)."""
        stragglers = []
        if engine.in_flight or engine._queue:
            stragglers = engine.drain()
            engine.resume_admission()
        engine.adopt_params(params, version=version)
        for rec in stragglers:
            engine.submit(serve_request_from_record(rec))

    def _promote(self):
        state, self.canary = self.canary, None
        for h in self.router.live_handles():
            if h.version != state["version"] and hasattr(h, "engine"):
                self._adopt_idle(h.engine, state["params"],
                                 state["version"])
                h.version = state["version"]
        self.router.set_split(None)
        self.promotions += 1
        record_canary("promoted", state["version"])
        self._append_jsonl({
            "kind": "canary", "verdict": "promoted",
            "version": state["version"], "t_wall": time.time(),
            "detail": "",
        })
        logger.warning("[controller] canary PROMOTED: weights version "
                       "%d is live fleet-wide.", state["version"])

    def _rollback_canary(self, reason):
        state, self.canary = self.canary, None
        target = state["target"]
        self._adopt_idle(target.engine, state["old_params"],
                         state["old_version"])
        target.version = state["old_version"]
        self.router.set_split(None)
        self.rollbacks += 1
        record_canary("rolled_back", state["version"], detail=reason)
        self._append_jsonl({
            "kind": "canary", "verdict": "rolled_back",
            "version": state["version"], "t_wall": time.time(),
            "detail": reason,
        })
        _trigger_forensics(
            "canary_rollback", detail=f"version={state['version']} {reason}"
        )
        logger.warning(
            "[controller] canary ROLLED BACK (%s): weights version %d "
            "restored.", reason, state["old_version"],
        )

    # -- teardown -------------------------------------------------------

    def stop(self):
        """Close any scale event still waiting on its first token and
        unregister; idempotent."""
        self._close_pending(force=True)
        if self in _ACTIVE:
            _ACTIVE.remove(self)
