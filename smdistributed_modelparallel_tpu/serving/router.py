"""Request router + replica handles for the serving control plane.

The router is the serving deployment's front door: every
``ServeRequest`` enters here and is dispatched to the least-loaded
LIVE replica, optionally split across weight VERSIONS (the blue/green
canary shifts a fraction of traffic to the new version and the router
keeps that split deterministic — the same request id always lands on
the same version, so a retried request cannot flap between weights
mid-canary).

Two handle flavors present the same surface to the router:

- ``LocalReplicaHandle`` wraps an in-process ``ServingEngine``
  (single-host deployments, and the controller rank's own replica).
- ``RemoteReplicaHandle`` speaks JSON frames over the native bus's
  reserved control tx ``ROUTER_TX`` (-8) to a ``ReplicaServer`` loop
  on a peer process — the same quiet ``send_raw``/``drain_bytes``
  path heartbeats and mirror frames use, so router traffic never
  consumes chaos bus-send ordinals. A standby peer parks in
  ``ReplicaServer.serve()`` until the controller's scale-up activates
  it; activation constructs the engine through the caller's factory,
  which is where the exec-cache warm start happens (the ready frame
  carries the compile-source counts so the controller can assert
  ``fresh == 0`` on a warm scale-up).

Reserved control tx map: -1 exit relay, -2 preempt notice, -3 preempt
step-edge, -4 heartbeats, -5 recovery rendezvous, -6 serve mirror,
-7 fleet snapshots, -8 THIS (see backend/native.py).

Wire frames (all JSON, controller -> replica):
  ``{"op": "activate", "version": V}``   build engine, reply ready
  ``{"op": "submit", "req": record}``    mirror-record wire format
  ``{"op": "drain"}``                    drain protocol, reply drained
  ``{"op": "deactivate"}``               leave the serve loop

replica -> controller:
  ``{"op": "ready", "seconds": s, "warm": {...}}``
  ``{"op": "finished", "rid": r, "tokens": [...]}``
  ``{"op": "load", "queue": q, "active": a}``
  ``{"op": "drained", "stragglers": [...], "results": {...}}``
"""

import hashlib
import json
import time

from smdistributed_modelparallel_tpu.serving.engine import (
    serve_request_to_record,
)
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPValidationError,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import record_route

logger = get_logger()

#: Reserved control tx for router/controller frames (see module doc).
ROUTER_TX = -8


def _rid_fraction(rid):
    """Deterministic [0, 1) position for a request id: the first 8 hex
    digits of its sha1. Version splits cut this line into segments, so
    the SAME rid always maps to the same version regardless of replica
    count or arrival order."""
    h = hashlib.sha1(str(rid).encode()).hexdigest()[:8]
    return int(h, 16) / float(0x100000000)


class LocalReplicaHandle:
    """Router-facing wrapper around an in-process ``ServingEngine``."""

    def __init__(self, name, engine, version=0):
        self.name = str(name)
        self.engine = engine
        self.version = int(version)
        self.live = True

    def load(self):
        """Queued + in-flight request count (the least-loaded metric)."""
        return len(self.engine._queue) + self.engine.in_flight

    def submit(self, req):
        return self.engine.submit(req)

    def step(self):
        return self.engine.step()

    def poll(self):
        """No transport to pump for a local engine."""

    def drain(self, timeout_s=120.0):
        return self.engine.drain(timeout_s=timeout_s)

    def results(self):
        return dict(self.engine.results)

    @property
    def busy(self):
        return self.engine.busy


class RequestRouter:
    """Least-loaded dispatch across live replica handles with
    deterministic per-version traffic splits."""

    def __init__(self):
        self.handles = {}
        self._split = None       # list of (version, cumulative fraction)
        self.routed = {}         # handle name -> dispatched count

    # -- membership -----------------------------------------------------

    def attach(self, handle):
        if handle.name in self.handles:
            raise SMPValidationError(
                f"router: a handle named {handle.name!r} is already "
                "attached"
            )
        self.handles[handle.name] = handle
        self.routed.setdefault(handle.name, 0)
        return handle

    def detach(self, name):
        return self.handles.pop(str(name), None)

    def live_handles(self, version=None):
        out = [h for h in self.handles.values() if h.live]
        if version is not None:
            out = [h for h in out if h.version == int(version)]
        return out

    # -- version splits -------------------------------------------------

    def set_split(self, split):
        """``{version: fraction}`` with fractions summing to ~1, or None
        to route by load alone (all versions eligible)."""
        if split is None:
            self._split = None
            return
        total = float(sum(split.values()))
        if not split or abs(total - 1.0) > 1e-6:
            raise SMPValidationError(
                f"router: split fractions must sum to 1.0, got {split!r}"
            )
        acc, table = 0.0, []
        for version in sorted(split):
            acc += float(split[version])
            table.append((int(version), acc))
        self._split = table

    @property
    def split(self):
        return dict((v, f) for v, f in self._split or ())

    def _pick_version(self, rid):
        if self._split is None:
            return None
        x = _rid_fraction(rid)
        for version, cum in self._split:
            if x < cum:
                return version
        return self._split[-1][0]

    # -- dispatch -------------------------------------------------------

    def dispatch(self, req):
        """Route one request: version by rid hash (when a split is
        active), then the least-loaded live replica of that version
        (falling back to ANY live replica if none serves it — a split
        must degrade to availability, not to a drop). Returns the
        handle name, or None when no live replica exists."""
        version = self._pick_version(req.request_id)
        candidates = self.live_handles(version)
        if not candidates:
            candidates = self.live_handles()
        if not candidates:
            return None
        handle = min(candidates, key=lambda h: (h.load(), h.name))
        if not handle.submit(req):
            return None
        self.routed[handle.name] = self.routed.get(handle.name, 0) + 1
        record_route(handle.version)
        return handle.name

    def step_all(self):
        """One tick of every live handle; True while any has work."""
        busy = False
        for h in self.live_handles():
            busy = bool(h.step()) or busy
            h.poll()
        return busy

    def results(self):
        merged = {}
        for h in self.handles.values():
            merged.update(h.results())
        return merged


class RemoteReplicaHandle:
    """Controller-side proxy for a ``ReplicaServer`` on a peer
    process. Load/finished/drained state is whatever the last drained
    frames reported — ``poll()`` (called from ``step_all``) pumps the
    transport."""

    def __init__(self, name, bus, peer, version=0):
        self.name = str(name)
        self.bus = bus
        self.peer = int(peer)
        self.version = int(version)
        self.live = False
        self._load = 0
        self._results = {}
        self._stragglers = None
        self.warm = {}
        self.activate_seconds = None

    def _send(self, frame):
        self.bus.send_raw(self.peer, json.dumps(frame).encode(), ROUTER_TX)

    def _frames(self):
        out = []
        for raw in self.bus.drain_bytes(self.peer, ROUTER_TX):
            try:
                out.append(json.loads(raw))
            except ValueError:
                continue
        return out

    def activate(self, version=None, timeout_s=120.0):
        """Ask the standby peer to build its engine; blocks until the
        ready frame lands. Returns the warm-start report (exec-cache
        compile sources) so the caller can assert fresh == 0."""
        if version is not None:
            self.version = int(version)
        self._send({"op": "activate", "version": self.version})
        deadline = time.monotonic() + timeout_s
        while True:
            for frame in self._frames():
                if frame.get("op") == "ready":
                    self.live = True
                    self.warm = frame.get("warm", {})
                    self.activate_seconds = float(frame.get("seconds", 0.0))
                    return self.warm
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"router: replica {self.name} did not activate "
                    f"within {timeout_s:.0f}s"
                )
            time.sleep(0.002)

    def load(self):
        return self._load

    def submit(self, req):
        self._send({"op": "submit", "req": serve_request_to_record(req)})
        self._load += 1   # optimistic until the next load frame lands
        return True

    def step(self):
        self.poll()
        return self._load > 0

    def poll(self):
        for frame in self._frames():
            op = frame.get("op")
            if op == "finished":
                self._results[frame["rid"]] = list(frame["tokens"])
            elif op == "load":
                self._load = int(frame.get("queue", 0)) + int(
                    frame.get("active", 0)
                )
            elif op == "drained":
                self._stragglers = list(frame.get("stragglers", ()))
                for rid, toks in frame.get("results", {}).items():
                    self._results[rid] = list(toks)
                self._load = 0

    def drain(self, timeout_s=120.0):
        """Run the drain protocol on the remote replica: it stops
        admitting, finishes in-flight streams, and ships back the
        queued-never-admitted stragglers as restartable records."""
        self._send({"op": "drain"})
        self._stragglers = None
        deadline = time.monotonic() + timeout_s
        while self._stragglers is None:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"router: replica {self.name} did not drain within "
                    f"{timeout_s:.0f}s"
                )
            self.poll()
            time.sleep(0.002)
        self.live = False
        return list(self._stragglers)

    def deactivate(self):
        self._send({"op": "deactivate"})
        self.live = False

    def results(self):
        self.poll()
        return dict(self._results)

    @property
    def busy(self):
        return self._load > 0


class ReplicaServer:
    """Standby/serve loop for a replica process driven by a remote
    controller over ``ROUTER_TX``. ``factory()`` builds the local
    ``ServingEngine`` on activation — with ``SMP_EXEC_CACHE=on`` and a
    shared cache dir that construction is the warm start the scale-up
    MTTR measures."""

    def __init__(self, factory, bus, controller_rank=0):
        self.factory = factory
        self.bus = bus
        self.controller = int(controller_rank)
        self.engine = None

    def _send(self, frame):
        self.bus.send_raw(
            self.controller, json.dumps(frame).encode(), ROUTER_TX
        )

    def serve(self, timeout_s=300.0):
        """Park until activated, serve until deactivated (or drained and
        then deactivated). Returns the engine's results dict."""
        from smdistributed_modelparallel_tpu.serving.engine import (
            serve_request_from_record,
        )
        from smdistributed_modelparallel_tpu.utils import exec_cache

        deadline = time.monotonic() + timeout_s
        reported = set()
        last_load = None
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError("ReplicaServer.serve timed out")
            frames = [
                json.loads(raw)
                for raw in self.bus.drain_bytes(self.controller, ROUTER_TX)
            ]
            for frame in frames:
                op = frame.get("op")
                if op == "activate" and self.engine is None:
                    t0 = time.perf_counter()
                    mark = exec_cache.compile_event_mark()
                    self.engine = self.factory()
                    warm = {}
                    for ev in exec_cache.compile_events_since(mark):
                        src = ev.get("source", "?")
                        warm[src] = warm.get(src, 0) + 1
                    self._send({
                        "op": "ready",
                        "seconds": time.perf_counter() - t0,
                        "warm": warm,
                    })
                    logger.info(
                        "[router] replica activated in %.2fs (%s)",
                        time.perf_counter() - t0, warm or "no compiles",
                    )
                elif op == "submit" and self.engine is not None:
                    self.engine.submit(
                        serve_request_from_record(frame["req"])
                    )
                elif op == "drain" and self.engine is not None:
                    stragglers = self.engine.drain()
                    self._send({
                        "op": "drained",
                        "stragglers": stragglers,
                        "results": {
                            rid: list(toks)
                            for rid, toks in self.engine.results.items()
                            if rid not in reported
                        },
                    })
                    reported.update(self.engine.results)
                    self.engine.resume_admission()
                elif op == "deactivate":
                    results = {}
                    if self.engine is not None:
                        results = dict(self.engine.results)
                        self.engine.close()
                        self.engine = None
                    return results
            if self.engine is None:
                time.sleep(0.002)
                continue
            self.engine.step()
            for rid in list(self.engine.finished):
                if rid in reported:
                    continue
                reported.add(rid)
                self._send({
                    "op": "finished",
                    "rid": rid,
                    "tokens": list(self.engine.results.get(rid, ())),
                })
            loadnow = (
                len(self.engine._queue) + self.engine.in_flight
            )
            if loadnow != last_load:
                last_load = loadnow
                self._send({
                    "op": "load",
                    "queue": len(self.engine._queue),
                    "active": self.engine.in_flight,
                })
            if not self.engine.last_tick_worked:
                time.sleep(0.001)
