"""Serving-replica failover (``smp.serving.ReplicatedServingEngine``).

Each process of a multi-process serving deployment runs its own
``ServingEngine`` over its own devices (dp-replicated traffic: the
control plane is shared, the compute is local) and MIRRORS every
in-flight request's restartable log — prompt, sampling params, seed,
sampled-tokens-so-far — to its peers over the native bus (reserved
control tx ``SERVE_MIRROR_TX``, the quiet ``send_raw`` path heartbeats
use: mirror traffic must not consume chaos bus-send ordinals or flood
the flight ring).

Failure detection rides the PR-10 supervisor: with ``SMP_SUPERVISOR=on``
the heartbeat detector classifies a SIGKILLed replica **dead** within
the miss budget; without it, the bus's receive-side death marks
(``peer_down``) carry the signal. Either way, the surviving replica
re-admits the dead replica's unfinished requests from its mirror shadow
— idempotent by request id (a request the survivor already served is
skipped), and EXACT: the resumed request continues the dead replica's
key schedule at ``len(tokens_so_far)``, so the survivor emits
token-for-token what the dead replica would have (asserted by the
2-process E2E in ``tests/test_multiprocess.py``).

The MTTR gauges become availability SLOs: a completed failover records
``smp_recoveries_total`` / ``smp_recovery_seconds`` with the serving
phase breakdown ``detect`` (last mirror frame -> classification) /
``readmit`` (shadow scan + re-admission) / ``first_token`` (first
re-admitted token sampled), which ``scripts/resilience_probe.py
--recovery`` parses and gates exactly like training recoveries.
"""

import json
import time

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.serving.engine import (
    serve_request_from_record,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    record_failure_detected,
    record_recovery,
    record_serve_request,
)

logger = get_logger()

#: Reserved control tx for serving mirror frames (-1 exit relay, -2
#: preempt notice, -3 preempt step-edge, -4 heartbeats, -5 recovery
#: rendezvous, -7 fleet metric snapshots, -8 controller/router frames —
#: see backend/native.py and serving/router.py).
SERVE_MIRROR_TX = -6


def _flight():
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )

    return flight_recorder


class ReplicatedServingEngine:
    """Failover wrapper around a local ``ServingEngine``."""

    def __init__(self, engine, bus=None):
        self.engine = engine
        if bus is None:
            comm = state._comm
            bus = comm._bus if comm is not None else None
        if bus is None or bus.world <= 1:
            raise ValueError(
                "ReplicatedServingEngine needs a multi-process native bus "
                "(replica failover is between processes)."
            )
        self.bus = bus
        self.rank = bus.rank
        self.peers = [p for p in range(bus.world) if p != bus.rank]
        self.shadow = {p: {} for p in self.peers}   # peer -> rid -> record
        self._last_frame = {p: time.monotonic() for p in self.peers}
        self._handled = set()                        # peers failed over
        # Per-peer pending MTTR closures: concurrent failovers (3+
        # replicas, two deaths in one window) each record their own
        # recovery with their own re-admitted streams.
        self._pending_mttr = {}                      # peer -> pending
        self._sent_tokens = {}   # rid -> tokens already mirrored out

    # -- mirror plane ---------------------------------------------------
    #
    # Wire format: the FIRST frame for a request ships the full
    # restartable record; every later frame ships only the token tail
    # since the last send ({"rid", "base", "tokens", "done"}). The bus
    # delivers in order per link, so the receiver reconstructs by
    # appending at ``base`` — without the delta form, a long stream
    # re-serializes its whole history every token (O(n^2) per stream).

    def _mirror_out(self):
        updates = self.engine.drain_dirty()
        if not updates:
            return
        wire = []
        for rid, rec in updates:
            sent = self._sent_tokens.get(rid)
            if sent is None or sent > len(rec["tokens"]):
                wire.append(dict(rec, full=True))
            else:
                wire.append({
                    "rid": rid, "base": sent,
                    "tokens": rec["tokens"][sent:],
                    "done": rec["done"],
                })
            self._sent_tokens[rid] = len(rec["tokens"])
        payload = json.dumps(
            {"from": self.rank, "records": wire}
        ).encode()
        for p in self.peers:
            if p in self._handled:
                continue
            # Quiet best-effort enqueue: a dead link's rc is detection
            # signal, not an error — the detector owns classification.
            self.bus.send_raw(p, payload, SERVE_MIRROR_TX)

    def _mirror_in(self):
        now = time.monotonic()
        for p in self.peers:
            frames = self.bus.drain_bytes(p, SERVE_MIRROR_TX)
            if frames:
                self._last_frame[p] = now
            for raw in frames:
                try:
                    frame = json.loads(raw)
                except ValueError:
                    continue
                for rec in frame.get("records", ()):
                    rid = rec.get("rid")
                    if not rid:
                        continue
                    if rec.get("full") or "prompt" in rec:
                        rec = dict(rec)
                        rec.pop("full", None)
                        self.shadow[p][rid] = rec
                        continue
                    known = self.shadow[p].get(rid)
                    if known is None:
                        continue  # never saw the header; cannot apply
                    base = int(rec.get("base", 0))
                    if base <= len(known["tokens"]):
                        known["tokens"] = (
                            known["tokens"][:base] + list(rec["tokens"])
                        )
                        known["done"] = bool(rec.get("done"))

    # -- failure detection + re-admission -------------------------------

    def _failed_peers(self):
        from smdistributed_modelparallel_tpu.resilience.supervisor import (
            classify_failed,
        )

        failed = classify_failed(self.bus, self.peers)
        return {
            p: kind for p, kind in failed.items() if p not in self._handled
        }

    def _failover(self, peer, kind):
        from smdistributed_modelparallel_tpu.resilience.supervisor import (
            heartbeat_interval,
            miss_budget,
            supervisor,
        )

        t0 = time.monotonic()
        # The mirror-frame gap over-reports detection latency for a peer
        # that was idle (nothing dirty = nothing sent); the heartbeat
        # detector's classification window bounds the REAL latency, so
        # cap the phase by it when the detector is armed.
        detect_s = max(t0 - self._last_frame.get(peer, t0), 0.0)
        if supervisor.detector is not None:
            detect_s = min(
                detect_s, heartbeat_interval() * (miss_budget() + 1)
            )
        self._handled.add(peer)
        _flight().record_supervisor(
            "recover_begin", peer=peer,
            detail=f"mode=serving kind={kind}",
        )
        if supervisor.detector is None:
            # No heartbeat detector running (SMP_SUPERVISOR=off): the bus
            # death mark was the classification — count it ourselves.
            record_failure_detected(kind, peer, detail="serving bus probe")
        readmitted = {}
        for rid, rec in sorted(self.shadow[peer].items()):
            if rec.get("done"):
                continue
            # The record carries the dead replica's trace id, so the
            # fused timeline shows one request spanning both rings
            # instead of a new request materializing on the survivor.
            req = serve_request_from_record(rec)
            if self.engine.submit(req):
                readmitted[rid] = len(req.resume_tokens)
                record_serve_request("readmitted")
        t1 = time.monotonic()
        logger.warning(
            "[serving] replica %d is %s: re-admitted %d unfinished "
            "request(s) from the mirror shadow (%.3fs).",
            peer, kind, len(readmitted), t1 - t0,
        )
        pending = {
            "peer": peer,
            "t_detect": t0,
            "detect_s": detect_s,
            "readmit_s": t1 - t0,
            # rid -> token count at re-admission: closure needs progress
            # BEYOND this baseline, not just the resumed prefix.
            "rids": readmitted,
        }
        if readmitted:
            self._pending_mttr[peer] = pending
        else:
            # Nothing in flight died with the replica: close immediately.
            self._close_mttr(pending, first_token_s=0.0)

    def _close_mttr(self, pending, first_token_s):
        self._pending_mttr.pop(pending["peer"], None)
        phases = {
            "detect": pending["detect_s"],
            "readmit": pending["readmit_s"],
            "first_token": first_token_s,
        }
        mttr = sum(phases.values())
        record_recovery(mttr, phases=phases)
        logger.warning(
            "[serving] FAILOVER complete: first re-admitted token %.2fs "
            "after detection (phases: %s).", mttr,
            {k: round(v, 3) for k, v in phases.items()},
        )

    def _check_mttr_closure(self):
        for pending in list(self._pending_mttr.values()):
            for rid, baseline in pending["rids"].items():
                rec = self.engine.mirror_log.get(rid)
                if rec is None:
                    continue
                if len(rec["tokens"]) > baseline or rec["done"]:
                    self._close_mttr(
                        pending,
                        first_token_s=max(
                            time.monotonic() - pending["t_detect"]
                            - pending["readmit_s"], 0.0,
                        ),
                    )
                    break

    # -- driving --------------------------------------------------------

    def step(self):
        """One replicated tick: local engine tick, mirror exchange,
        failover check. Returns True while local work remains."""
        busy = self.engine.step()
        self._mirror_out()
        self._mirror_in()
        for peer, kind in self._failed_peers().items():
            self._failover(peer, kind)
        self._check_mttr_closure()
        return busy or bool(self._pending_mttr)

    def run(self, requests=(), timeout_s=300.0, linger_s=0.0):
        """Serve ``requests`` (plus any failover re-admissions) to
        completion. ``linger_s`` keeps ticking that long after local work
        drains so late peer deaths are still absorbed (the E2E uses it to
        hold the survivor open across the kill window)."""
        for req in requests:
            self.engine.submit(req)
        deadline = time.monotonic() + timeout_s
        linger_until = None
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError("replicated serving run timed out")
            busy = self.step()
            if busy:
                linger_until = None
                if not self.engine.last_tick_worked:
                    time.sleep(0.001)  # blocked on arrivals/blocks/MTTR
                continue
            if linger_s <= 0.0:
                break
            if self._handled >= set(self.peers):
                # Every peer already failed over — nothing left to linger
                # for.
                break
            if linger_until is None:
                linger_until = time.monotonic() + linger_s
            elif time.monotonic() >= linger_until:
                break
            time.sleep(0.02)
        return dict(self.engine.results)
