"""``smp.serving`` — continuous-batching serving engine.

The production serving tier (ISSUE 14 / ROADMAP "millions of users,
heavy traffic"): a paged/block-allocated KV cache shared by every
in-flight sequence, a continuous-batching scheduler with chunked
prefill, exactly two bucket-keyed compiled programs warm-started by the
persistent executable cache, SLO telemetry through ``smp.telemetry``,
and replica failover driven by the PR-10 heartbeat supervisor.

Typical use::

    engine = smp.serving.ServingEngine(model)   # or (module, params=...)
    results = engine.run([
        smp.serving.ServeRequest("r0", prompt_ids, max_new_tokens=64),
        smp.serving.ServeRequest("r1", other_ids, max_new_tokens=8,
                                 temperature=0.8, top_p=0.9, seed=7),
    ])

Multi-process deployments wrap the engine in
``ReplicatedServingEngine`` for mirror-log failover.

Import-hygiene contract: importing this package must never initialize an
accelerator backend (jax work happens only inside the engine's runtime
entry points).
"""

from smdistributed_modelparallel_tpu.serving.engine import (
    ServeRequest,
    ServingEngine,
)
from smdistributed_modelparallel_tpu.serving.kv_cache import (
    BlockAllocator,
    block_tokens,
    prefill_chunk_tokens,
    serve_slots,
)
from smdistributed_modelparallel_tpu.serving.replica import (
    SERVE_MIRROR_TX,
    ReplicatedServingEngine,
)

__all__ = [
    "BlockAllocator",
    "ReplicatedServingEngine",
    "SERVE_MIRROR_TX",
    "ServeRequest",
    "ServingEngine",
    "block_tokens",
    "prefill_chunk_tokens",
    "serve_slots",
]
