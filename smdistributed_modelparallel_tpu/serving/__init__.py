"""``smp.serving`` — continuous-batching serving engine.

The production serving tier (ISSUE 14 / ROADMAP "millions of users,
heavy traffic"): a paged/block-allocated KV cache shared by every
in-flight sequence, a continuous-batching scheduler with chunked
prefill, exactly two bucket-keyed compiled programs warm-started by the
persistent executable cache, SLO telemetry through ``smp.telemetry``,
and replica failover driven by the PR-10 heartbeat supervisor.

Typical use::

    engine = smp.serving.ServingEngine(model)   # or (module, params=...)
    results = engine.run([
        smp.serving.ServeRequest("r0", prompt_ids, max_new_tokens=64),
        smp.serving.ServeRequest("r1", other_ids, max_new_tokens=8,
                                 temperature=0.8, top_p=0.9, seed=7),
    ])

Multi-process deployments wrap the engine in
``ReplicatedServingEngine`` for mirror-log failover.

The serving CONTROL PLANE (ISSUE 19) lives in
``smp.serving.controller`` / ``smp.serving.router``: SLO-driven
autoscaling with hysteresis + cooldown, least-loaded request routing
with per-version traffic splits, the zero-loss drain protocol, and
canaried live weight updates with automatic rollback. Armed by
``SMP_AUTOSCALE`` (``ServingController.from_env()`` returns None when
unset — nothing is constructed).

Import-hygiene contract: importing this package must never initialize an
accelerator backend (jax work happens only inside the engine's runtime
entry points).
"""

from smdistributed_modelparallel_tpu.serving import controller, router
from smdistributed_modelparallel_tpu.serving.controller import (
    AutoscalePolicy,
    ServingController,
)
from smdistributed_modelparallel_tpu.serving.engine import (
    ServeRequest,
    ServingEngine,
    serve_request_from_record,
    serve_request_to_record,
)
from smdistributed_modelparallel_tpu.serving.kv_cache import (
    BlockAllocator,
    block_tokens,
    prefill_chunk_tokens,
    serve_slots,
)
from smdistributed_modelparallel_tpu.serving.replica import (
    SERVE_MIRROR_TX,
    ReplicatedServingEngine,
)
from smdistributed_modelparallel_tpu.serving.router import (
    ROUTER_TX,
    LocalReplicaHandle,
    RemoteReplicaHandle,
    ReplicaServer,
    RequestRouter,
)

__all__ = [
    "AutoscalePolicy",
    "BlockAllocator",
    "LocalReplicaHandle",
    "ROUTER_TX",
    "RemoteReplicaHandle",
    "ReplicaServer",
    "ReplicatedServingEngine",
    "RequestRouter",
    "SERVE_MIRROR_TX",
    "ServeRequest",
    "ServingController",
    "ServingEngine",
    "block_tokens",
    "controller",
    "prefill_chunk_tokens",
    "router",
    "serve_request_from_record",
    "serve_request_to_record",
    "serve_slots",
]
