"""Autoregressive generation with KV caches — ``smp.generate``.

TPU extension (no reference counterpart): the reference
(``smdistributed.modelparallel``) is a training library; its users sample
from fine-tuned models by exporting to HF. A complete switch-over needs
generation in-framework: this module drives the attention layers' decode
mode (``nn/utils.DecodeKVCache``) as one compiled program — a prefill pass
over the prompt (full flash-attention fast path) followed by a
``lax.scan`` of single-token decode steps, with greedy / temperature /
top-k / top-p sampling and per-row EOS early-stop masking.

Design notes (TPU-first):
- The whole generation (prefill + all decode steps) is ONE jitted
  program: no per-token host round trips, and XLA keeps the cache update
  (``dynamic_update_slice`` on a scan carry) in place.
- Under tensor parallelism nothing changes here: the decode forward runs
  the same TP-sharded layers; GSPMD shards the [B, C, H, hd] caches over
  the head axis exactly like the activations they buffer.
- Generation requires ``pp == 1`` (the pipeline head protocol has no
  decode path); tp/dp/fsdp meshes are fine.
"""

import jax
import jax.numpy as jnp
import numpy as np

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

# Compiled-generator cache: flax modules are frozen dataclasses (hashable
# when their fields are), so (module, shapes, sampling config) keys a
# ready program across repeated generate() calls.
_COMPILED = {}


def _top_k_filter(logits, top_k):
    top_k = min(top_k, logits.shape[-1])  # HF convention: clamp to vocab
    kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
    return jnp.where(logits >= kth, logits, -jnp.inf)


def _top_p_filter(logits, top_p):
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens whose cumulative probability BEFORE them is < top_p
    # (always keeps the most likely token).
    keep = (cum - probs) < top_p
    thresh = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= thresh, logits, -jnp.inf)


def _make_sampler(temperature, top_k, top_p):
    if temperature == 0.0:
        return lambda logits, rng: jnp.argmax(logits, axis=-1)

    def sample(logits, rng):
        logits = logits / temperature
        if top_k is not None:
            logits = _top_k_filter(logits, top_k)
        if top_p is not None:
            logits = _top_p_filter(logits, top_p)
        return jax.random.categorical(rng, logits, axis=-1)

    return sample


def _decode_clone(module, cache_len):
    try:
        return module.clone(
            decode=True, decode_cache_len=cache_len, deterministic=True
        )
    except TypeError as e:
        raise SMPValidationError(
            f"{type(module).__name__} does not support KV-cache decoding "
            "(needs decode/decode_cache_len/deterministic fields — the "
            "TransformerLM zoo family and smp.nn DistributedTransformerLMHead "
            "do)."
        ) from e


def _decode_loop(apply_step, prefill_out, max_new_tokens,
                 sampler, eos_token_id, pad_token_id, rng):
    """Shared sample-feed-sample loop after a prefill: returns the
    [B, max_new_tokens] generated ids."""
    logits, cache = prefill_out
    B = logits.shape[0]
    rngs = jax.random.split(rng, max_new_tokens)
    tok = sampler(logits[:, -1].astype(jnp.float32), rngs[0])
    done = jnp.zeros((B,), bool)
    if eos_token_id is not None:
        done = tok == eos_token_id

    def body(carry, step_rng):
        cache, tok, done = carry
        logits, cache = apply_step(cache, tok[:, None])
        nxt = sampler(logits[:, -1].astype(jnp.float32), step_rng)
        if eos_token_id is not None:
            nxt = jnp.where(done, pad_token_id, nxt)
            new_done = done | (nxt == eos_token_id)
        else:
            new_done = done
        return (cache, nxt, new_done), nxt

    (_, _, _), rest = jax.lax.scan(body, (cache, tok, done), rngs[1:])
    return jnp.concatenate([tok[:, None], rest.transpose(1, 0)], axis=1)


def _build_generator(decode_mod, max_new_tokens, sampler, eos_token_id,
                     pad_token_id):
    """Decoder-only generation body: (params, ids, rng) -> [B, total] ids."""

    def run(params, ids, rng):
        logits, mut = decode_mod.apply(
            {"params": params}, ids, mutable=["cache"]
        )

        def apply_step(cache, tok):
            logits, mut = decode_mod.apply(
                {"params": params, "cache": cache}, tok, mutable=["cache"]
            )
            return logits, mut["cache"]

        new_tokens = _decode_loop(
            apply_step, (logits, mut["cache"]), max_new_tokens,
            sampler, eos_token_id, pad_token_id, rng,
        ).astype(ids.dtype)
        return jnp.concatenate([ids, new_tokens], axis=1)

    return run


def _build_seq2seq_generator(decode_mod, max_new_tokens, sampler,
                             eos_token_id, pad_token_id,
                             decoder_start_token_id):
    """Seq2seq generation body: encode once, KV-cached decoder steps.
    (params, encoder_ids, encoder_mask, rng) -> [B, 1 + max_new] decoder
    ids (start token first, HF ``generate`` convention)."""

    def run(params, enc_ids, enc_mask, rng):
        B = enc_ids.shape[0]
        h_e, _ = decode_mod.apply(
            {"params": params}, enc_ids, enc_mask,
            method="encode", mutable=["cache"],
        )
        start = jnp.full((B, 1), decoder_start_token_id, enc_ids.dtype)
        logits, mut = decode_mod.apply(
            {"params": params}, start, h_e, enc_mask,
            method="decode_step", mutable=["cache"],
        )

        def apply_step(cache, tok):
            logits, mut = decode_mod.apply(
                {"params": params, "cache": cache}, tok, h_e, enc_mask,
                method="decode_step", mutable=["cache"],
            )
            return logits, mut["cache"]

        new_tokens = _decode_loop(
            apply_step, (logits, mut["cache"]), max_new_tokens,
            sampler, eos_token_id, pad_token_id, rng,
        ).astype(enc_ids.dtype)
        return jnp.concatenate([start, new_tokens], axis=1)

    return run


def generate(model, input_ids, max_new_tokens, *, temperature=0.0,
             top_k=None, top_p=None, eos_token_id=None, pad_token_id=0,
             rng=None, params=None, encoder_mask=None,
             decoder_start_token_id=0):
    """Generate ``max_new_tokens`` continuation tokens for each prompt.

    Args:
      model: a ``DistributedModel`` wrapping a decode-capable LM (the
        ``TransformerLM`` zoo family, ``smp.nn.DistributedTransformerLMHead``,
        the ``EncoderDecoderLM`` seq2seq family, or an
        ``smp.from_hf``-translated causal/seq2seq LM), or such a flax
        module directly (then ``params`` is required).
      input_ids: [B, T] int prompt tokens — the ENCODER input for a
        seq2seq model. Decoder-only prompts are taken as unpadded (same
        true length per row); pad/trim on the host beforehand.
      max_new_tokens: number of tokens to append.
      temperature: 0.0 = greedy argmax (default); > 0 samples.
      top_k / top_p: optional sampling filters (compose: k then p).
      eos_token_id: when set, rows that emit EOS are frozen and padded
        with ``pad_token_id`` for the remaining steps.
      rng: ``jax.random`` key for sampling (required when temperature > 0).
      params: parameter tree override (defaults to the model's).
      encoder_mask: seq2seq only — [B, S] encoder padding mask (1/True =
        keep), forwarded to cross-attention.
      decoder_start_token_id: seq2seq only — the decoder's BOS.

    Returns:
      Decoder-only: [B, T + max_new_tokens] — prompts with continuations.
      Seq2seq: [B, 1 + max_new_tokens] — start token + generated ids.
    """
    if state.cfg is not None and state.cfg.pipeline_parallel_degree > 1:
        raise SMPValidationError(
            "smp.generate requires pipeline_parallel_degree == 1 "
            "(tp/dp/fsdp are supported)."
        )
    if max_new_tokens < 1:
        raise SMPValidationError("max_new_tokens must be >= 1.")
    input_ids = jnp.asarray(input_ids)
    if hasattr(model, "module"):  # DistributedModel
        module = model.module
        seq2seq = hasattr(module, "encode") and hasattr(module, "decode_step")
        if params is None:
            if model.params is None:
                init_args = (
                    (input_ids, input_ids[:, :1]) if seq2seq else (input_ids,)
                )
                model._eager_init(init_args, {})
            params = model.params
    else:
        module = model
        seq2seq = hasattr(module, "encode") and hasattr(module, "decode_step")
        if params is None:
            raise SMPValidationError(
                "generate(flax_module, ...) requires params=..."
            )
    if temperature > 0.0 and rng is None:
        raise SMPValidationError("temperature > 0 requires rng=jax.random.key(...)")
    if rng is None:
        rng = jax.random.key(0)

    B, T = input_ids.shape
    cache_len = (1 + max_new_tokens) if seq2seq else (T + max_new_tokens)
    limit = getattr(module, "max_len", None) or getattr(
        module, "num_positions", None
    )
    if limit is not None and cache_len > limit:
        raise SMPValidationError(
            f"{'decoder length' if seq2seq else 'prompt'} + max_new_tokens "
            f"({cache_len}) exceeds the model's position limit ({limit})."
        )
    if limit is not None and seq2seq and T > limit:
        raise SMPValidationError(
            f"encoder prompt length ({T}) exceeds the model's position "
            f"limit ({limit})."
        )

    has_mask = encoder_mask is not None
    key = None
    try:
        # The mesh is part of the key: sharding constraints traced into the
        # program bind the mesh active at trace time (smp.reset + re-init
        # with a different mesh must not reuse a stale program).
        key = (module, B, T, max_new_tokens, float(temperature), top_k,
               top_p, eos_token_id, pad_token_id, decoder_start_token_id,
               has_mask, state.mesh if state.initialized else None)
        compiled = _COMPILED.get(key)
    except TypeError:  # unhashable module fields: compile uncached
        key = None
        compiled = None
    if compiled is None:
        decode_mod = _decode_clone(module, cache_len)
        sampler = _make_sampler(float(temperature), top_k, top_p)
        if seq2seq:
            run = _build_seq2seq_generator(
                decode_mod, max_new_tokens, sampler, eos_token_id,
                pad_token_id, decoder_start_token_id,
            )
        else:
            run = _build_generator(decode_mod, max_new_tokens, sampler,
                                   eos_token_id, pad_token_id)
        compiled = jax.jit(run)
        if key is not None:
            _COMPILED[key] = compiled

    args = (
        (params, input_ids, encoder_mask, rng) if seq2seq
        else (params, input_ids, rng)
    )
    mesh = state.mesh if state.initialized else None
    if mesh is not None:
        with jax.set_mesh(mesh):
            return compiled(*args)
    return compiled(*args)
