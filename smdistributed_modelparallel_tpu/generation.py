"""Autoregressive generation with KV caches — ``smp.generate``.

TPU extension (no reference counterpart): the reference
(``smdistributed.modelparallel``) is a training library; its users sample
from fine-tuned models by exporting to HF. A complete switch-over needs
generation in-framework: this module drives the attention layers' decode
mode (``nn/utils.DecodeKVCache``) as one compiled program — a prefill pass
over the prompt (full flash-attention fast path) followed by a
``lax.scan`` of single-token decode steps, with greedy / temperature /
top-k / top-p sampling and per-row EOS early-stop masking.

Design notes (TPU-first):
- The whole generation (prefill + all decode steps) is ONE jitted
  program: no per-token host round trips, and XLA keeps the cache update
  (``dynamic_update_slice`` on a scan carry) in place.
- Under tensor parallelism nothing changes here: the decode forward runs
  the same TP-sharded layers; GSPMD shards the [B, C, H, hd] caches over
  the head axis exactly like the activations they buffer.
- Under pipeline parallelism the decode path does not run the pipeline
  schedule: a ``DistributedModel``'s pp-stage-sharded layer stacks are
  regathered onto the full mesh (``model.regather_for_decode``, cached
  until the params change) and decode runs as a plain tp/dp forward —
  train at pp x tp, then sample, without a topology change.
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

# Compiled-generator cache: flax modules are frozen dataclasses (hashable
# when their fields are), so (module, shapes, sampling config) keys a
# ready program across repeated generate() calls. LRU-bounded: serving
# ragged prompt shapes would otherwise leak one compiled program per
# (B, T, max_new_tokens, ...) combination for the process lifetime —
# callers with more than _COMPILED_CAP live shapes should pad prompts to
# a fixed set of bucket shapes.
_COMPILED_CAP = 32
_COMPILED = collections.OrderedDict()


def _top_k_filter(logits, top_k):
    top_k = min(top_k, logits.shape[-1])  # HF convention: clamp to vocab
    kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
    return jnp.where(logits >= kth, logits, -jnp.inf)


def _top_p_filter(logits, top_p):
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens whose cumulative probability BEFORE them is < top_p
    # (always keeps the most likely token).
    keep = (cum - probs) < top_p
    thresh = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= thresh, logits, -jnp.inf)


def _make_sampler(temperature, top_k, top_p):
    if temperature == 0.0:
        return lambda logits, rng: jnp.argmax(logits, axis=-1)

    def sample(logits, rng):
        logits = logits / temperature
        if top_k is not None:
            logits = _top_k_filter(logits, top_k)
        if top_p is not None:
            logits = _top_p_filter(logits, top_p)
        return jax.random.categorical(rng, logits, axis=-1)

    return sample


def _decode_clone(module, cache_len):
    try:
        return module.clone(
            decode=True, decode_cache_len=cache_len, deterministic=True
        )
    except TypeError as e:
        raise SMPValidationError(
            f"{type(module).__name__} does not support KV-cache decoding "
            "(needs decode/decode_cache_len/deterministic fields — the "
            "TransformerLM zoo family and smp.nn DistributedTransformerLMHead "
            "do)."
        ) from e


def _decode_loop(apply_step, prefill_out, max_new_tokens,
                 sampler, eos_token_id, pad_token_id, rng):
    """Shared sample-feed-sample loop after a prefill: returns the
    [B, max_new_tokens] generated ids."""
    logits, cache = prefill_out
    B = logits.shape[0]
    rngs = jax.random.split(rng, max_new_tokens)
    tok = sampler(logits[:, -1].astype(jnp.float32), rngs[0])
    done = jnp.zeros((B,), bool)
    if eos_token_id is not None:
        done = tok == eos_token_id

    def body(carry, step_rng):
        cache, tok, done = carry
        logits, cache = apply_step(cache, tok[:, None])
        nxt = sampler(logits[:, -1].astype(jnp.float32), step_rng)
        if eos_token_id is not None:
            nxt = jnp.where(done, pad_token_id, nxt)
            new_done = done | (nxt == eos_token_id)
        else:
            new_done = done
        return (cache, nxt, new_done), nxt

    (_, _, _), rest = jax.lax.scan(body, (cache, tok, done), rngs[1:])
    return jnp.concatenate([tok[:, None], rest.transpose(1, 0)], axis=1)


def _half_cast(params, half):
    """Match the training step's compute dtype: under bf16/fp16 configs
    the decode forward runs on half-precision params, so generation
    throughput and numerics track training (shared predicate:
    nn/utils.half_cast). Under ``SMP_DECODE_WEIGHTS=int8`` the params
    first round-trip through the serving path's per-channel int8 grid
    (fake-quant — value-identical to store-int8 + dequant), so
    ``smp.generate`` and the serving engine emit the same tokens under
    the same knob."""
    from smdistributed_modelparallel_tpu import quant
    from smdistributed_modelparallel_tpu.nn.utils import half_cast

    if quant.decode_weights_mode() == "int8":
        params = quant.fake_quant_decode_params(params)
    return half_cast(params, half)


def _step_masks(mask, max_new_tokens):
    """(prefill [B,1,1,T], step [B,1,1,C]) boolean masks from a [B, T]
    LEFT-padded prompt mask; generated columns are always kept."""
    mask = mask.astype(bool)
    B = mask.shape[0]
    step = jnp.concatenate(
        [mask, jnp.ones((B, max_new_tokens), bool)], axis=1
    )
    return mask[:, None, None, :], step[:, None, None, :]


def _build_generator(decode_mod, max_new_tokens, sampler, eos_token_id,
                     pad_token_id, half=None):
    """Decoder-only generation body:
    (params, ids, mask | None, rng) -> [B, total] ids."""

    def run(params, ids, mask, rng):
        params = _half_cast(params, half)
        pre_kw, step_kw = {}, {}
        if mask is not None:
            pre_mask, step_mask = _step_masks(mask, max_new_tokens)
            pre_kw = {"attention_mask": pre_mask}
            step_kw = {"attention_mask": step_mask}
        logits, mut = decode_mod.apply(
            {"params": params}, ids, mutable=["cache"], **pre_kw
        )

        def apply_step(cache, tok):
            logits, mut = decode_mod.apply(
                {"params": params, "cache": cache}, tok,
                mutable=["cache"], **step_kw,
            )
            return logits, mut["cache"]

        new_tokens = _decode_loop(
            apply_step, (logits, mut["cache"]), max_new_tokens,
            sampler, eos_token_id, pad_token_id, rng,
        ).astype(ids.dtype)
        return jnp.concatenate([ids, new_tokens], axis=1)

    return run


def _build_seq2seq_generator(decode_mod, max_new_tokens, sampler,
                             eos_token_id, pad_token_id,
                             decoder_start_token_id, half=None):
    """Seq2seq generation body: encode once, KV-cached decoder steps.
    (params, encoder_ids, encoder_mask, rng) -> [B, 1 + max_new] decoder
    ids (start token first, HF ``generate`` convention)."""

    def run(params, enc_ids, enc_mask, rng):
        params = _half_cast(params, half)
        B = enc_ids.shape[0]
        h_e, _ = decode_mod.apply(
            {"params": params}, enc_ids, enc_mask,
            method="encode", mutable=["cache"],
        )
        start = jnp.full((B, 1), decoder_start_token_id, enc_ids.dtype)
        logits, mut = decode_mod.apply(
            {"params": params}, start, h_e, enc_mask,
            method="decode_step", mutable=["cache"],
        )

        def apply_step(cache, tok):
            logits, mut = decode_mod.apply(
                {"params": params, "cache": cache}, tok, h_e, enc_mask,
                method="decode_step", mutable=["cache"],
            )
            return logits, mut["cache"]

        new_tokens = _decode_loop(
            apply_step, (logits, mut["cache"]), max_new_tokens,
            sampler, eos_token_id, pad_token_id, rng,
        ).astype(enc_ids.dtype)
        return jnp.concatenate([start, new_tokens], axis=1)

    return run


# ----------------------------------------------------------------------
# Beam search (greedy beams, HF-compatible scoring: length_penalty
# normalization at EOS time, early_stopping=True semantics).
# ----------------------------------------------------------------------

# Plain python float: a module-level jnp array would initialize the
# accelerator backend at import time (and hang outright if the TPU
# tunnel is wedged).
_NEG = -1e9


def _reorder_beam_cache(cache, parent_flat):
    """Gather the growing self-attention caches along the folded [B*N]
    beam axis. Under ``nn.scan`` the per-layer caches stack on a leading
    layer axis — ``cached_key``/``cached_value`` are [L, B*N, C, H, hd],
    so the gather is on axis 1. ``cross_kv`` (encoder K/V) is identical
    across the beams of a row and index counters are scalars; both pass
    through untouched."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(cache)
    out = []
    for path, leaf in flat:
        name = getattr(path[-1], "key", None)
        if name in ("cached_key", "cached_value"):
            out.append(jnp.take(leaf, parent_flat, axis=1))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def _build_beam_generator(decode_mod, max_new_tokens, num_beams,
                          eos_token_id, pad_token_id, length_penalty,
                          seq2seq, decoder_start_token_id,
                          num_return_sequences=1, half=None):
    """Compiled beam-search body. Beams fold into the batch axis (the
    model sees [B*N, ...]); each step takes the top-2N candidates over
    [N x vocab], routes EOS candidates into a best-N finished store
    (scores normalized by HF's ``cur_len ** length_penalty``), continues
    the top-N non-EOS beams, and gathers the KV caches to the surviving
    parents. Everything — prefill, all steps, finalize — is one program.
    """
    N = num_beams

    def select(logprobs, cache, beam_scores, seqs, fin, stopped, step):
        B = beam_scores.shape[0]
        V = logprobs.shape[-1]
        fin_scores, fin_seqs, fin_len = fin
        cand = beam_scores[:, :, None] + logprobs.reshape(B, N, V)
        s2, i2 = jax.lax.top_k(cand.reshape(B, N * V), 2 * N)
        tok2 = i2 % V
        par2 = i2 // V
        rows = jnp.arange(B)[:, None]
        if eos_token_id is not None:
            eos2 = tok2 == eos_token_id
            # Finished store: merge this step's EOS candidates (parent
            # sequence WITHOUT the eos token; only EOS ranked within the
            # top N counts — HF drops worse-than-top-N EOS) with the kept
            # hypotheses; keep the best N overall. Scores normalize by
            # the GENERATED length including the eos (transformers >=
            # 4.38: ``cur_len + 1 - decoder_prompt_len``); frozen rows
            # (early_stopping reached) contribute nothing.
            norm = s2 / jnp.float32(step + 1) ** length_penalty
            in_top_n = jnp.arange(2 * N)[None, :] < N
            cand_fin = jnp.where(
                eos2 & in_top_n & ~stopped[:, None], norm, _NEG
            )
            all_scores = jnp.concatenate([fin_scores, cand_fin], axis=1)
            all_seqs = jnp.concatenate([fin_seqs, seqs[rows, par2]], axis=1)
            all_len = jnp.concatenate(
                [fin_len, jnp.full((B, 2 * N), step, jnp.int32)], axis=1
            )
            fin_scores, fidx = jax.lax.top_k(all_scores, N)
            fin_seqs = jnp.take_along_axis(all_seqs, fidx[:, :, None], 1)
            fin_len = jnp.take_along_axis(all_len, fidx, 1)
            stopped = stopped | (
                jnp.sum(fin_scores > _NEG / 2, axis=1) >= N
            )
            s2 = jnp.where(eos2, _NEG, s2)
        new_scores, pos = jax.lax.top_k(s2, N)
        tokN = jnp.take_along_axis(tok2, pos, 1)
        parN = jnp.take_along_axis(par2, pos, 1)
        new_seqs = seqs[rows, parN]
        new_seqs = jax.lax.dynamic_update_slice_in_dim(
            new_seqs, tokN[:, :, None], step, axis=2
        )
        parent_flat = (rows * N + parN).reshape(-1)
        cache = _reorder_beam_cache(cache, parent_flat)
        return (cache, tokN.reshape(-1), new_scores, new_seqs,
                (fin_scores, fin_seqs, fin_len), stopped)

    def finish(beam_scores, seqs, fin, stopped, out_dtype):
        """HF finalize: non-stopped rows also offer their live beams
        (normalized by the full generated length — the last-iteration
        max-length merge in transformers); best hypothesis wins; output
        is hyp + eos + pad."""
        B = beam_scores.shape[0]
        fin_scores, fin_seqs, fin_len = fin
        final_norm = beam_scores / (
            jnp.float32(max_new_tokens) ** length_penalty
        )
        live = jnp.where(~stopped[:, None], final_norm, _NEG)
        if eos_token_id is None:
            live = final_norm
        all_scores = jnp.concatenate([fin_scores, live], axis=1)
        all_seqs = jnp.concatenate([fin_seqs, seqs], axis=1)
        all_len = jnp.concatenate(
            [fin_len,
             jnp.full((B, N), max_new_tokens, jnp.int32)], axis=1
        )
        R = num_return_sequences
        _, best = jax.lax.top_k(all_scores, R)              # [B, R]
        seq = jnp.take_along_axis(all_seqs, best[:, :, None], 1)  # [B,R,L]
        length = jnp.take_along_axis(all_len, best, 1)       # [B, R]
        cols = jnp.arange(max_new_tokens)[None, None, :]
        eos_fill = eos_token_id if eos_token_id is not None else pad_token_id
        out = jnp.where(
            cols < length[:, :, None], seq,
            jnp.where(cols == length[:, :, None], eos_fill, pad_token_id),
        ).astype(out_dtype)
        return out[:, 0] if R == 1 else out

    def loop(cache, first_logits, seqs0, apply_step, B, out_dtype):
        logprobs = jax.nn.log_softmax(
            first_logits[:, -1].astype(jnp.float32), axis=-1
        ).reshape(B, N, -1)
        # Step 0: the N beams of a row are identical clones — only beam 0
        # may propose candidates (HF seeds beam scores [0, -inf, ...]).
        beam_scores = jnp.full((B, N), _NEG).at[:, 0].set(0.0)
        fin = (
            jnp.full((B, N), _NEG),
            jnp.zeros((B, N, max_new_tokens), jnp.int32),
            jnp.zeros((B, N), jnp.int32),
        )
        stopped = jnp.zeros((B,), bool)
        cache, tok, beam_scores, seqs, fin, stopped = select(
            logprobs.reshape(B * N, -1), cache, beam_scores, seqs0, fin,
            stopped, 0,
        )

        def body(carry, step):
            cache, tok, beam_scores, seqs, fin, stopped = carry
            logits, cache = apply_step(cache, tok[:, None])
            logprobs = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32), axis=-1
            )
            return select(logprobs, cache, beam_scores, seqs, fin,
                          stopped, step), None

        (cache, tok, beam_scores, seqs, fin, stopped), _ = jax.lax.scan(
            body,
            (cache, tok, beam_scores, seqs, fin, stopped),
            jnp.arange(1, max_new_tokens),
        )
        return finish(beam_scores, seqs, fin, stopped, out_dtype)

    if seq2seq:
        def run(params, enc_ids, enc_mask, rng):
            params = _half_cast(params, half)
            B, S = enc_ids.shape
            h_e = decode_mod.apply(
                {"params": params}, enc_ids, enc_mask,
                method="encode", mutable=["cache"],
            )[0]
            h_e = jnp.repeat(h_e, N, axis=0)
            enc_mask_t = (
                None if enc_mask is None else jnp.repeat(enc_mask, N, axis=0)
            )
            start = jnp.full((B * N, 1), decoder_start_token_id,
                             enc_ids.dtype)
            logits, mut = decode_mod.apply(
                {"params": params}, start, h_e, enc_mask_t,
                method="decode_step", mutable=["cache"],
            )

            def apply_step(cache, tok):
                logits, mut = decode_mod.apply(
                    {"params": params, "cache": cache}, tok, h_e,
                    enc_mask_t, method="decode_step", mutable=["cache"],
                )
                return logits, mut["cache"]

            seqs0 = jnp.zeros((B, N, max_new_tokens), jnp.int32)
            gen = loop(mut["cache"], logits, seqs0, apply_step, B,
                       enc_ids.dtype)
            if num_return_sequences > 1:
                s = jnp.broadcast_to(
                    start[::N][:, None],
                    (B, num_return_sequences, 1),
                )
                return jnp.concatenate([s, gen], axis=2)
            return jnp.concatenate([start[::N], gen], axis=1)
    else:
        def run(params, ids, mask, rng):
            params = _half_cast(params, half)
            B, T = ids.shape
            ids_t = jnp.repeat(ids, N, axis=0)
            pre_kw, step_kw = {}, {}
            if mask is not None:
                mask_t = jnp.repeat(mask, N, axis=0)
                pre_mask, step_mask = _step_masks(mask_t, max_new_tokens)
                pre_kw = {"attention_mask": pre_mask}
                step_kw = {"attention_mask": step_mask}
            logits, mut = decode_mod.apply(
                {"params": params}, ids_t, mutable=["cache"], **pre_kw
            )

            def apply_step(cache, tok):
                logits, mut = decode_mod.apply(
                    {"params": params, "cache": cache}, tok,
                    mutable=["cache"], **step_kw,
                )
                return logits, mut["cache"]

            seqs0 = jnp.zeros((B, N, max_new_tokens), jnp.int32)
            gen = loop(mut["cache"], logits, seqs0, apply_step, B,
                       ids.dtype)
            if num_return_sequences > 1:
                idsr = jnp.broadcast_to(
                    ids[:, None], (B, num_return_sequences, T)
                )
                return jnp.concatenate([idsr, gen], axis=2)
            return jnp.concatenate([ids, gen], axis=1)

    return run


def generate(model, input_ids, max_new_tokens, *, temperature=0.0,
             top_k=None, top_p=None, eos_token_id=None, pad_token_id=0,
             rng=None, params=None, encoder_mask=None, attention_mask=None,
             decoder_start_token_id=0, num_beams=1, length_penalty=1.0,
             num_return_sequences=1):
    """Generate ``max_new_tokens`` continuation tokens for each prompt.

    Args:
      model: a ``DistributedModel`` wrapping a decode-capable LM (the
        ``TransformerLM`` zoo family, ``smp.nn.DistributedTransformerLMHead``,
        the ``EncoderDecoderLM`` seq2seq family, or an
        ``smp.from_hf``-translated causal/seq2seq LM), or such a flax
        module directly (then ``params`` is required).
      input_ids: [B, T] int prompt tokens — the ENCODER input for a
        seq2seq model. Decoder-only prompts of different true lengths
        must be LEFT-padded, with ``attention_mask`` marking real tokens;
        without a mask they are taken as unpadded.
      max_new_tokens: number of tokens to append.
      temperature: 0.0 = greedy argmax (default); > 0 samples.
      top_k / top_p: optional sampling filters (compose: k then p).
      eos_token_id: when set, rows that emit EOS are frozen and padded
        with ``pad_token_id`` for the remaining steps.
      rng: ``jax.random`` key for sampling (required when temperature > 0).
      params: parameter tree override (defaults to the model's).
      encoder_mask: seq2seq only — [B, S] encoder padding mask (1/True =
        keep), forwarded to cross-attention.
      attention_mask: decoder-only — [B, T] LEFT-padded prompt mask
        (1/True = real token). Positions shift per row by the pad count
        (HF convention) and padded columns never attend.
      decoder_start_token_id: seq2seq only — the decoder's BOS.
      num_beams: > 1 switches to beam search (greedy beams; requires
        temperature == 0). HF-compatible scoring: hypothesis scores are
        sum-logprob / (cur_len ** length_penalty), ``early_stopping=True``
        semantics (a row freezes once num_beams hypotheses finish).
      length_penalty: beam-score length normalization exponent.
      num_return_sequences: beams only — return the top R hypotheses per
        row (R <= num_beams) as a [B, R, L] array instead of [B, L].

    Pipeline parallelism: with a ``DistributedModel`` trained at pp > 1,
    generation regathers the pp-sharded layer stacks for decode
    automatically (see ``DistributedModel.regather_for_decode``); a raw
    flax module under pp needs explicit ``params``.

    Returns:
      Decoder-only: [B, T + max_new_tokens] — prompts with continuations.
      Seq2seq: [B, 1 + max_new_tokens] — start token + generated ids.
      With beams, finished rows are "hypothesis + EOS + pad" padded; with
      ``num_return_sequences`` R > 1 the shape gains a rank-R axis.
    """
    pp_active = (
        state.cfg is not None and state.cfg.pipeline_parallel_degree > 1
    )
    if pp_active and params is None and not hasattr(
        model, "regather_for_decode"
    ):
        raise SMPValidationError(
            "smp.generate under pipeline_parallel_degree > 1 needs a "
            "DistributedModel (whose pp-sharded params are regathered "
            "for decode) or explicit params=..."
        )
    if max_new_tokens < 1:
        raise SMPValidationError("max_new_tokens must be >= 1.")
    input_ids = jnp.asarray(input_ids)
    if hasattr(model, "module"):  # DistributedModel
        module = model.module
        seq2seq = hasattr(module, "encode") and hasattr(module, "decode_step")
        if params is None:
            if model.params is None:
                init_args = (
                    (input_ids, input_ids[:, :1]) if seq2seq else (input_ids,)
                )
                model._eager_init(init_args, {})
            if pp_active:
                # Decode is a plain forward (no pipeline schedule): the
                # pp-stage-sharded layer stacks regather onto the full
                # mesh, tp/ZeRO axes intact. Cached until the params
                # change, so steady-state sampling pays no re-gather.
                params = model.regather_for_decode()
            else:
                params = model.params
    else:
        module = model
        seq2seq = hasattr(module, "encode") and hasattr(module, "decode_step")
        if params is None:
            raise SMPValidationError(
                "generate(flax_module, ...) requires params=..."
            )
    if encoder_mask is not None and not seq2seq:
        raise SMPValidationError(
            "decoder-only models take attention_mask, not encoder_mask."
        )
    if attention_mask is not None:
        if seq2seq:
            raise SMPValidationError(
                "seq2seq models take encoder_mask, not attention_mask."
            )
        import inspect

        if "attention_mask" not in inspect.signature(
            type(module).__call__
        ).parameters:
            raise SMPValidationError(
                f"{type(module).__name__} does not accept attention_mask; "
                "padded-prompt generation needs the smp.nn "
                "DistributedTransformerLMHead family (incl. smp.from_hf "
                "models)."
            )
        attention_mask = jnp.asarray(attention_mask)
        if attention_mask.shape != input_ids.shape:
            raise SMPValidationError(
                f"attention_mask shape {attention_mask.shape} != prompt "
                f"shape {input_ids.shape}."
            )
        # Eager left-paddedness check (the mask is a concrete host array
        # here): a right-padded mask would silently sample the first
        # continuation from a masked pad position's logits.
        m = np.asarray(attention_mask).astype(bool)
        if not ((m[:, 1:] >= m[:, :-1]).all() and m[:, -1].all()):
            raise SMPValidationError(
                "attention_mask must be LEFT-padded (rows 0..0 1..1 with "
                "the last column kept); right-padded prompts would "
                "generate from a pad position."
            )
    if temperature < 0.0:
        raise SMPValidationError(
            "temperature must be >= 0 (0 = greedy); a negative value "
            "would sample from the probability-inverted distribution."
        )
    if temperature > 0.0 and rng is None:
        raise SMPValidationError("temperature > 0 requires rng=jax.random.key(...)")
    if temperature == 0.0 and num_beams == 1 and (
        top_k is not None or top_p is not None
    ):
        # HF warns here; we refuse — a user passing top_p=0.9 without a
        # temperature would silently get greedy output.
        raise SMPValidationError(
            "top_k/top_p have no effect with temperature == 0 (greedy "
            "argmax); pass temperature > 0 to sample (e.g. temperature"
            "=1.0), or drop the filters."
        )
    if top_k is not None and top_k < 1:
        raise SMPValidationError("top_k must be >= 1.")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise SMPValidationError("top_p must be in (0, 1].")
    if num_beams > 1 and (temperature > 0.0 or top_k is not None
                          or top_p is not None):
        raise SMPValidationError(
            "beam search is greedy (num_beams > 1 requires temperature == "
            "0 and no top_k/top_p filters)."
        )
    if not 1 <= num_return_sequences <= num_beams:
        raise SMPValidationError(
            "num_return_sequences must be in [1, num_beams]."
        )
    if rng is None:
        rng = jax.random.key(0)

    # Decode-length shape buckets (SMP_SHAPE_BUCKETS "seq" sizes, the
    # PR-11 policy): ragged (prompt-len, max-new-tokens) pairs round UP
    # to bucket boundaries so serving-style traffic reuses cached
    # programs instead of churning the _COMPILED LRU. max_new_tokens
    # buckets for every decoder-only model (the extra steps are sliced
    # off; EOS-frozen rows just emit pad there); prompt length buckets by
    # LEFT-padding through the existing padded-prompt machinery, so it
    # needs a mask-capable module (the smp.nn family). Greedy output is
    # invariant; stochastic sampling draws from the bucketed key schedule
    # (split(rng, bucketed_max_new) — reproducible for a fixed bucket
    # config, documented in README). Beam search is excluded: its
    # hypothesis scores normalize by max_new_tokens, so padding it would
    # change the ranking.
    orig_input_ids = input_ids
    orig_T = input_ids.shape[1]
    orig_new = max_new_tokens
    if num_beams == 1 and not seq2seq:
        from smdistributed_modelparallel_tpu.utils import exec_cache

        policy = exec_cache.bucket_policy()
        seqs = (policy or {}).get("seq")
        if seqs:
            padded = False
            unbucketable = False
            limit = getattr(module, "max_len", None) or getattr(
                module, "num_positions", None
            )
            new_b = exec_cache.bucket_for(max_new_tokens, seqs)
            if new_b is not None and limit is not None and (
                orig_T + new_b > limit
            ):
                # Never let a bucket push a fitting request past the
                # model's position limit — decode length stays exact.
                new_b = None
            if new_b is None:
                unbucketable = True
            elif new_b != max_new_tokens:
                max_new_tokens = new_b
                padded = True
            t_b = exec_cache.bucket_for(orig_T, seqs)
            if t_b is not None and limit is not None and (
                t_b + max_new_tokens > limit
            ):
                t_b = None
            if t_b is not None and t_b != orig_T:
                import inspect

                if "attention_mask" in inspect.signature(
                    type(module).__call__
                ).parameters:
                    nb = input_ids.shape[0]
                    pad_w = t_b - orig_T
                    input_ids = jnp.concatenate(
                        [jnp.full((nb, pad_w), pad_token_id,
                                  input_ids.dtype), input_ids], axis=1
                    )
                    keep = (
                        attention_mask.astype(jnp.int32)
                        if attention_mask is not None
                        else jnp.ones((nb, orig_T), jnp.int32)
                    )
                    attention_mask = jnp.concatenate(
                        [jnp.zeros((nb, pad_w), jnp.int32), keep], axis=1
                    )
                    padded = True
                else:
                    unbucketable = True
            elif t_b is None:
                unbucketable = True
            # "padded" wins over "unbucketable": a call whose decode
            # length bucketed (program shared) but whose prompt dim
            # couldn't must count as a bucket hit, not a miss.
            exec_cache.record_bucket(
                "padded" if padded
                else ("unbucketable" if unbucketable else "exact")
            )

    B, T = input_ids.shape
    cache_len = (1 + max_new_tokens) if seq2seq else (T + max_new_tokens)
    limit = getattr(module, "max_len", None) or getattr(
        module, "num_positions", None
    )
    if limit is not None and cache_len > limit:
        raise SMPValidationError(
            f"{'decoder length' if seq2seq else 'prompt'} + max_new_tokens "
            f"({cache_len}) exceeds the model's position limit ({limit})."
        )
    if limit is not None and seq2seq and T > limit:
        raise SMPValidationError(
            f"encoder prompt length ({T}) exceeds the model's position "
            f"limit ({limit})."
        )

    has_mask = encoder_mask is not None
    half = state.cfg.half_dtype if state.cfg is not None else None
    key = None
    try:
        # The mesh is part of the key: sharding constraints traced into the
        # program bind the mesh active at trace time (smp.reset + re-init
        # with a different mesh must not reuse a stale program).
        from smdistributed_modelparallel_tpu import quant as _quant

        key = (module, B, T, max_new_tokens, float(temperature), top_k,
               top_p, eos_token_id, pad_token_id, decoder_start_token_id,
               has_mask, attention_mask is not None, num_beams,
               float(length_penalty), num_return_sequences, str(half),
               state.mesh if state.initialized else None
               ) + _quant.serving_key_suffix()
        compiled = _COMPILED.get(key)
        if compiled is not None:
            _COMPILED.move_to_end(key)
    except TypeError:  # unhashable module fields: compile uncached
        key = None
        compiled = None
    if compiled is None:
        decode_mod = _decode_clone(module, cache_len)
        if num_beams > 1:
            run = _build_beam_generator(
                decode_mod, max_new_tokens, num_beams, eos_token_id,
                pad_token_id, float(length_penalty), seq2seq,
                decoder_start_token_id, num_return_sequences, half,
            )
        elif seq2seq:
            sampler = _make_sampler(float(temperature), top_k, top_p)
            run = _build_seq2seq_generator(
                decode_mod, max_new_tokens, sampler, eos_token_id,
                pad_token_id, decoder_start_token_id, half,
            )
        else:
            sampler = _make_sampler(float(temperature), top_k, top_p)
            run = _build_generator(decode_mod, max_new_tokens, sampler,
                                   eos_token_id, pad_token_id, half)
        compiled = jax.jit(run)
        if key is not None:
            _COMPILED[key] = compiled
            while len(_COMPILED) > _COMPILED_CAP:
                _COMPILED.popitem(last=False)

    args = (
        (params, input_ids, encoder_mask, rng) if seq2seq
        else (params, input_ids, attention_mask, rng)
    )
    mesh = state.mesh if state.initialized else None
    if mesh is not None:
        with jax.set_mesh(mesh):
            out = compiled(*args)
    else:
        out = compiled(*args)
    if T != orig_T or max_new_tokens != orig_new:
        # Bucketed run: drop the left-pad columns and the extra decode
        # steps — callers see exactly the (prompt, max_new) they asked
        # for.
        out = jnp.concatenate(
            [orig_input_ids, out[:, T:T + orig_new]], axis=1
        )
    return out
