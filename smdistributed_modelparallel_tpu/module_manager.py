"""ModuleManager: module-tree naming, annotations, and sharding resolution.

Parity target: reference ``torch/module_manager.py:60-1392`` — names the
module tree, stores partition assignments, TP markings, and activation-
checkpoint configs, and feeds the partitioner. The reference's runtime
bookkeeping (per-microbatch output stacks, pending-backward counters,
execution traces) has no SPMD counterpart and is dropped; what remains is
the *annotation registry* keyed by parameter-tree paths, plus resolution of
each parameter's PartitionSpec from (tp metadata, pipeline stage, ZeRO).

Module identity: flax parameter trees are nested dicts; a "module" is a
'/'-joined path prefix (e.g. "transformer/h_3/attn"). Annotation APIs accept
such prefixes (with the reference's "main" root alias).
"""

import re
from contextlib import contextmanager

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.exceptions import PartitionError
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


def path_key(path):
    """Canonical '/'-joined string for a jax pytree key path. The single
    stringifier used for model and optimizer state_dict keys."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _normalize_prefix(prefix):
    if prefix in ("main", "", "/"):
        return ""
    return prefix.strip("/")


def _prefix_matches(path, prefix):
    """Component-boundary prefix match: 'h_1' matches 'h_1/...' but not 'h_10'."""
    if prefix == "":
        return True
    return path == prefix or path.startswith(prefix + "/")


class ModuleManager:
    def __init__(self, root_module):
        self.root_module = root_module
        self.param_paths = []            # flat list of '/'-joined param paths
        self._manual_partitions = {}     # path prefix -> stage id
        self._tp_marks = {}              # path prefix -> tp_config dict
        self._ckpt_configs = {}          # path prefix -> checkpoint config
        self._spec_providers = []        # callables: path -> PartitionSpec | None
        self._partition_assignment = None  # path prefix -> stage (after partitioning)

    # -- param tree recording ------------------------------------------

    def record_param_tree(self, params):
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        self.param_paths = [path_key(p) for p, _ in flat]

    # -- manual pipeline partition (parity: smp.partition ctx) ----------

    @contextmanager
    def partition(self, stage):
        """Parity: reference ``smp.partition(i)`` context
        (``torch/module_manager.py:1161``). Module constructions inside the
        context are assigned to pipeline stage `i`; in the flax design the
        context records a pending prefix registered at DistributedModel
        construction via ``assign_partition``."""
        prev = getattr(self, "_active_partition", None)
        self._active_partition = stage
        try:
            yield
        finally:
            self._active_partition = prev

    def set_partition(self, prefix, stage):
        pp = state.cfg.pipeline_parallel_degree if state.cfg else 1
        if not (0 <= stage < pp):
            raise PartitionError(f"Partition {stage} out of range [0, {pp}).")
        self._manual_partitions[_normalize_prefix(prefix)] = stage

    def get_manual_partitions(self):
        return dict(self._manual_partitions)

    def set_partition_assignment(self, assignment):
        self._partition_assignment = {
            _normalize_prefix(k): v for k, v in assignment.items()
        }

    def stage_of(self, path):
        if self._partition_assignment is None:
            return 0
        best, best_len = 0, -1
        for prefix, stage in self._partition_assignment.items():
            if _prefix_matches(path, prefix) and len(prefix) > best_len:
                best, best_len = stage, len(prefix)
        return best

    # -- tensor parallelism marking ------------------------------------

    def set_tensor_parallelism(self, prefix, enabled=True, **tp_config):
        if enabled:
            self._tp_marks[_normalize_prefix(prefix)] = tp_config
        else:
            self._tp_marks.pop(_normalize_prefix(prefix), None)

    def tp_marked(self, prefix):
        return _normalize_prefix(prefix) in self._tp_marks

    def tp_config(self, prefix):
        return self._tp_marks.get(_normalize_prefix(prefix), {})

    @property
    def tp_marks(self):
        return dict(self._tp_marks)

    # -- activation checkpointing registry ------------------------------

    def set_activation_checkpointing(self, prefix, **config):
        self._ckpt_configs[_normalize_prefix(prefix)] = config

    def checkpoint_config(self, prefix):
        return self._ckpt_configs.get(_normalize_prefix(prefix))

    @property
    def checkpoint_configs(self):
        return dict(self._ckpt_configs)

    # -- sharding resolution -------------------------------------------

    def register_spec_provider(self, fn, name=None):
        """fn(path: str, leaf) -> PartitionSpec | None. Later providers win.
        Used by the pipeline (M2), TP layer (M3) and ZeRO (M4). A named
        provider replaces any previous provider of the same name."""
        if name is not None:
            self._spec_providers = [
                p for p in self._spec_providers if getattr(p, "_smp_name", None) != name
            ]
            fn._smp_name = name
        self._spec_providers.append(fn)

    def spec_for(self, path, leaf):
        """Merge provider specs dimension-wise: a later provider's axis wins
        on a dim where both name axes; None dims are transparent. This is
        how the pipeline's stage sharding (dim 0 of stacked layer params on
        'pp') composes with TP axes on inner dims ('tp' from flax
        with_partitioning metadata) and ZeRO sharding (M4)."""
        ndim = getattr(leaf, "ndim", 0)
        merged = [None] * ndim
        seen = False
        for provider in self._spec_providers:
            got = provider(path, leaf)
            if got is None:
                continue
            if len(got) > ndim:
                raise PartitionError(
                    f"Sharding spec {got} from provider "
                    f"'{getattr(provider, '_smp_name', provider)}' has more "
                    f"dims than parameter '{path}' (ndim={ndim})."
                )
            seen = True
            for i, axes in enumerate(got):
                if axes is not None:
                    merged[i] = axes
        return P(*merged) if seen else P()

    def param_shardings(self, mesh, params):
        def leaf_sharding(path, leaf):
            return NamedSharding(mesh, self.spec_for(path_key(path), leaf))

        return jax.tree_util.tree_map_with_path(leaf_sharding, params)
