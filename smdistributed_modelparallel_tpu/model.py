"""DistributedModel: the central model wrapper.

Parity target: reference ``torch/model.py:110-1608`` (``DistributedModel``).
The reference wraps an ``nn.Module`` tree, re-instantiates TP-marked modules,
wraps in a DDP fork, patches forwards to route cross-partition calls through
the module-server, and manages parameter placement after partitioning.

TPU-native re-design: the wrapped module is a Flax module; parameters are an
explicit pytree initialized lazily on the first ``@smp.step`` call (the
reference's first-step trace/partition moment, ``torch/server.py:345-352``).
Instead of moving parameters between processes, partitioning produces a
``NamedSharding`` per parameter over the mesh (pp stage assignment -> pp
axis specs in M2, TP specs in M3, ZeRO/rdp specs in M4); XLA moves the data.
``model(...)`` inside a step function applies the module with the parameters
of the current trace, and ``model.backward(loss)`` records the loss tracer
so the step engine can differentiate — the SPMD replacement for the
reference's autograd-graph-driven distributed backward
(``torch/patches/execution.py:400-441``).
"""

import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.module_manager import path_key
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPValidationError,
    StepUsageError,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


class DistributedModel:
    """Wraps a Flax module for distributed execution under @smp.step.

    Args:
      module: a ``flax.linen.Module`` (including ``smp.nn`` modules).
      loss_scale / dtype policy are handled by the step engine via config
      (fp16/bf16 keys), not here.
      rngs: names of RNG streams the module needs besides "params"
        (e.g. ("dropout",)).
      trace_device: device used for the one-time eager init run.
    """

    def __init__(self, module, rngs=("dropout",), name="main",
                 translate_functions=None):
        if state.cfg is None:
            raise SMPValidationError("Call smp.init(config) before DistributedModel().")
        self.module = module
        self.name = name
        self.rng_streams = tuple(rngs)
        # (to_hf, from_hf) state-dict translators for this instance (set by
        # smp.from_hf); checkpoint translate_if_full prefers these over the
        # class-keyed registry entry (several HF families share one
        # distributed class).
        self._translate_functions = translate_functions
        self._params = None               # materialized param pytree (jax.Arrays)
        self._param_shardings = None      # pytree of NamedSharding
        self._grads_store = None          # ("avg", tree) | ("raw", tree, divisor, avg_cache)
        self._grads_finite = None         # device bool under fp16 loss scaling
        self._pending_update = None       # fused-step (grads_token, params, opt_state)
        self._tls = threading.local()     # per-trace bound params / backward loss
        self._partition_result = None     # set by the pipeline partitioner (M2)
        self._pipeline_spec = None        # PipelineSpec when pp > 1 (M2)
        self._output_aval = None          # output shapes of the model call
        self._input_aval = None
        self._post_partition_hooks = []
        self._train = True
        state.model = self

        from smdistributed_modelparallel_tpu.module_manager import ModuleManager

        # Annotations (set_partition / set_tensor_parallelism / ...) may have
        # been made before DistributedModel construction; adopt the existing
        # manager rather than dropping them.
        if state.module_manager is not None and state.module_manager.root_module is None:
            self.module_manager = state.module_manager
            self.module_manager.root_module = module
        else:
            self.module_manager = ModuleManager(module)
        state.module_manager = self.module_manager

        # Re-instantiate tp-marked registered modules as their smp.nn
        # counterparts (parity: reference _replace_tp_counterparts,
        # torch/model.py:285-333).
        from smdistributed_modelparallel_tpu.nn.auto_distribute import distribute_tree

        self.module, self._tp_replaced = distribute_tree(
            module, self.module_manager, state.tp_registry
        )
        self.module_manager.root_module = self.module

    # ------------------------------------------------------------------
    # Tracing-time interface (used inside @smp.step user functions)
    # ------------------------------------------------------------------

    def __call__(self, *args, **kwargs):
        # Pipeline capture/force modes (pp > 1, see step.py): the step engine
        # traces the user fn with the model call intercepted — 'capture'
        # records the inputs and returns a dummy of the right shape; 'force'
        # substitutes the pipelined output.
        mode = getattr(self._tls, "call_mode", None)
        if mode is not None:
            kind, payload = mode
            self._tls.captured_calls.append((args, kwargs))
            if kind == "capture":
                return jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, a.dtype), payload
                )
            return payload  # force

        params = getattr(self._tls, "bound_params", None)
        if params is None:
            # Eager call outside a step: use materialized params (init first).
            if self._params is None:
                self._eager_init(args, kwargs)
            params = self._params
        rngs = getattr(self._tls, "rngs", None)
        variables = {"params": params}
        # Run with intermediates mutable so MoE router load-balancing losses
        # (sown under "moe_aux_loss", nn/moe.py) reach the step engine; they
        # are folded into the differentiated loss in _end_step_trace.
        from smdistributed_modelparallel_tpu.nn.moe import collect_moe_aux

        out, mut = self.module.apply(
            variables, *args, rngs=rngs, mutable=["intermediates"], **kwargs
        )
        if getattr(self._tls, "in_step", False):
            aux = collect_moe_aux(mut.get("intermediates"))
            if aux is not None:
                prev = getattr(self._tls, "aux_loss", None)
                self._tls.aux_loss = aux if prev is None else prev + aux
        self._output_aval = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), out
        )
        self._input_aval = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") else a,
            (args, kwargs),
        )
        return out

    def backward(self, loss):
        """Record the scalar to differentiate for this microbatch.

        Parity: reference ``model.backward(loss)`` inside @smp.step
        (``torch/model.py:1113-1146``). Under the functional design this
        marks the loss; actual differentiation happens in the step engine.
        """
        if getattr(self._tls, "in_step", False):
            if getattr(self._tls, "backward_loss", None) is not None:
                raise StepUsageError("model.backward() called twice in one microbatch.")
            self._tls.backward_loss = loss
        else:
            # Outside a step: reference raises; we record for forward-only use.
            raise StepUsageError("model.backward() must be called inside an @smp.step function.")
        return loss

    # -- step-engine hooks ---------------------------------------------

    def _begin_step_trace(self, params, rngs):
        self._tls.bound_params = params
        self._tls.rngs = rngs
        self._tls.backward_loss = None
        self._tls.in_step = True
        self._tls.call_mode = None
        self._tls.captured_calls = []
        self._tls.aux_loss = None

    def _begin_capture(self, out_aval):
        """Intercept the model call: record inputs, return zeros(out_aval)."""
        self._begin_step_trace(None, None)
        self._tls.call_mode = ("capture", out_aval)

    def _begin_force(self, params, rngs, value):
        """Intercept the model call: record inputs, return `value`."""
        self._begin_step_trace(params, rngs)
        self._tls.call_mode = ("force", value)

    def _end_step_trace(self):
        loss = getattr(self._tls, "backward_loss", None)
        aux = getattr(self._tls, "aux_loss", None)
        self._tls.captured = getattr(self._tls, "captured_calls", [])
        self._tls.bound_params = None
        self._tls.rngs = None
        self._tls.backward_loss = None
        self._tls.in_step = False
        self._tls.call_mode = None
        self._tls.captured_calls = []
        self._tls.aux_loss = None
        if loss is not None and aux is not None:
            weight = getattr(state.cfg, "moe_aux_loss_weight", 1.0)
            if weight:
                loss = loss + jnp.asarray(weight, loss.dtype) * aux.astype(
                    loss.dtype
                )
        return loss

    @property
    def _last_captured(self):
        return getattr(self._tls, "captured", [])

    # ------------------------------------------------------------------
    # Initialization / partitioning
    # ------------------------------------------------------------------

    @property
    def initialized(self):
        return self._params is not None

    def _init_rngs(self):
        mgr = state.rng_manager
        rngs = {"params": mgr.next_key("params")}
        for s in self.rng_streams:
            rngs[s] = mgr.next_key(s)
        return rngs

    def _eager_init(self, args, kwargs):
        """Materialize parameters from example inputs (first model call).

        Parity note: this is the reference's first-step tracing moment
        (``torch/worker.py:248-278``); here it both creates params and
        gives the partitioner concrete shapes. Under
        ``delayed_parameter_initialization`` parameters are born sharded
        (never materialized whole on one device).
        """
        if state.cfg is not None and state.cfg.delayed_parameter_initialization:
            self._sharded_init(args, kwargs)
            return
        logger.info("Initializing model parameters from first batch shapes.")
        # set_mesh: partial-manual shard_map regions (context parallelism)
        # inside the init need the mesh bound at the jit call site.
        with jax.set_mesh(state.mesh):
            variables = jax.jit(self.module.init)(
                self._init_rngs(), *args, **kwargs
            )
        params = variables["params"]
        self._set_params(params)

    def _sharded_init(self, args, kwargs):
        """Delayed (sharded) parameter initialization.

        Parity: reference ``delay_param_initialization``
        (``torch/parameter.py:24-123`` + ``torch/model.py:511-584``,
        torchdistx deferred init: parameters materialize only on their
        owning rank after partitioning). TPU-native: ``jax.eval_shape`` the
        init to learn shapes + sharding metadata, build the NamedShardings
        from the registered specs, then compile the init with
        ``out_shardings`` so every parameter materializes directly in its
        sharded placement — per-device init memory is the shard, not the
        tree.
        """
        from flax.core import meta as flax_meta

        logger.info("Delayed init: materializing parameters directly sharded.")
        rngs = self._init_rngs()
        aval_vars = jax.eval_shape(
            lambda r, a, kw: self.module.init(r, *a, **kw), rngs, args, kwargs
        )
        aval_params = self._adopt_param_metadata(aval_vars["params"])
        self.module_manager.record_param_tree(aval_params)
        mesh = state.mesh
        shardings = self.module_manager.param_shardings(mesh, aval_params)

        def init_unboxed(r, a, kw):
            return flax_meta.unbox(self.module.init(r, *a, **kw)["params"])

        with jax.set_mesh(mesh):
            compiled = (
                jax.jit(init_unboxed, out_shardings=shardings)
                .lower(rngs, args, kwargs)
                .compile()
            )
            try:
                self._init_memory_analysis = compiled.memory_analysis()
            except Exception:  # pragma: no cover - backend-specific
                self._init_memory_analysis = None
            params = compiled(rngs, args, kwargs)
        self._set_params(params)

    def _set_params(self, params):
        params = self._adopt_param_metadata(params)
        self._params = params
        self.module_manager.record_param_tree(params)
        self._apply_shardings()
        if state.loaded_model_state is not None:
            # Deferred resume_from_checkpoint payload (parity: reference
            # torch/model.py:245-251).
            from smdistributed_modelparallel_tpu.shard_io import ShardCatalog

            logger.info("Applying deferred checkpoint state to model.")
            payload = state.loaded_model_state
            state.loaded_model_state = None
            if isinstance(payload, ShardCatalog):
                self.load_sharded(payload)
            else:
                self.load_state_dict(payload)
        for hook in self._post_partition_hooks:
            hook(self)

    def _adopt_param_metadata(self, params):
        """Unbox flax ``Partitioned`` metadata (smp.nn modules attach tp axis
        names via ``nn.with_partitioning``) and register the resulting specs
        with the module manager.

        TPU-native counterpart of the reference's ``parameter_creation_scope``
        distribution-axis registry (``torch/nn/utils.py:120-154``,
        ``torch/module_manager.py:240-277``): where the reference records
        which dim of each param is sliced across tp_ranks, here the record is
        the param's PartitionSpec, consumed during ``_apply_shardings``.
        """
        import flax.linen as fnn
        from flax.core import meta as flax_meta

        boxed = [
            leaf for leaf in jax.tree_util.tree_leaves(
                params, is_leaf=lambda x: isinstance(x, flax_meta.AxisMetadata)
            )
            if isinstance(leaf, flax_meta.AxisMetadata)
        ]
        if not boxed:
            return params
        spec_tree = fnn.get_partition_spec(params)
        flat_specs = {}
        for path, spec in jax.tree_util.tree_flatten_with_path(
            spec_tree, is_leaf=lambda x: isinstance(x, P)
        )[0]:
            if any(axis is not None for axis in spec):
                flat_specs[path_key(path)] = spec

        def provider(path, leaf):
            return flat_specs.get(path)

        self.module_manager.register_spec_provider(provider, name="tp_params")
        return flax_meta.unbox(params)

    def _apply_shardings(self):
        """Compute and apply parameter shardings.

        M1: replicate everything (DP only). M2/M3/M4 refine this with
        pp-stage, tp, and ZeRO specs via the module_manager's partition
        and the nn modules' sharding metadata.
        """
        mesh = state.mesh
        self._param_shardings = self.module_manager.param_shardings(mesh, self._params)
        self._params = jax.device_put(self._params, self._param_shardings)
        # The identity-keyed regather_for_decode cache can never serve the
        # replaced tree, but the superseded full-size gathered copy would
        # stay pinned in HBM until the next params-setter call — drop it
        # with the tree it was built from (ADVICE round 5).
        self._decode_params_cache = None

    def post_partition(self, partition_result):
        """Install a pipeline-partition result (M2)."""
        self._partition_result = partition_result
        if self._params is not None:
            self._apply_shardings()

    def register_post_partition_hook(self, hook):
        """Parity: reference ``smp.register_post_partition_hook``."""
        self._post_partition_hooks.append(hook)
        return hook

    # ------------------------------------------------------------------
    # Parameter access / state_dict
    # ------------------------------------------------------------------

    @property
    def params(self):
        return self._params

    @params.setter
    def params(self, new_params):
        self._params = new_params
        # The pp-regathered decode copy (regather_for_decode) is keyed to
        # the old tree; dropping it here frees the full-size gathered
        # params as soon as they go stale instead of pinning them in HBM
        # across the following training steps.
        self._decode_params_cache = None

    @property
    def grads(self):
        return self._grads

    # _grads backs onto a store that can hold the RAW microbatch-sum tree
    # from a fused step (averaging folds into the optimizer update, so the
    # mean is only computed if someone actually reads the grads).
    @property
    def _grads(self):
        store = self._grads_store
        if store is None:
            return None
        if store[0] == "avg":
            return store[1]
        _, raw, divisor, avg = store
        if avg is None:
            avg = jax.tree_util.tree_map(
                lambda g, p: (g / divisor).astype(p.dtype), raw, self._params
            )
            self._grads_store = ("raw", raw, divisor, avg)
        return avg

    @_grads.setter
    def _grads(self, value):
        self._grads_store = None if value is None else ("avg", value)

    def _set_raw_grads(self, raw, divisor):
        self._grads_store = ("raw", raw, divisor, None)

    def _grads_token_is(self, token):
        """Identity check against the step's grads output without forcing
        the lazy average."""
        store = self._grads_store
        if store is None:
            return False
        return (store[1] is token)

    def parameters(self):
        """Flat list of parameter arrays (reference-compat-ish)."""
        return jax.tree_util.tree_leaves(self._params)

    def local_parameters(self):
        """Parity: reference ``local_parameters`` — params owned by this
        rank's partition. Under SPMD all params are mesh-sharded; the local
        view is the addressable shards."""
        return jax.tree_util.tree_leaves(self._params)

    def num_parameters(self):
        return sum(int(np.prod(p.shape)) for p in self.parameters())

    def state_dict(self):
        """Full (gathered) state dict of numpy arrays, keyed by '/'-joined
        paths. Parity: reference ``torch/model.py:863-932``."""
        flat = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(self._params)[0]:
            key = path_key(path)
            flat[key] = np.asarray(jax.device_get(leaf))
        return flat

    def local_state_dict(self):
        """Per-process shard payload. Parity: reference ``local_state_dict``
        (``torch/model.py:1482+``); the replica-0 shards addressable from
        this process, round-trippable through ``load_state_dict``."""
        from smdistributed_modelparallel_tpu.shard_io import shard_payload

        return shard_payload(self._params, dedupe_global=False)

    def load_state_dict(self, flat_dict):
        """Load a '/'-keyed flat dict into the param tree (resharding as
        needed). Shard payloads (``local_state_dict`` output) load
        shard-wise."""
        from smdistributed_modelparallel_tpu.shard_io import (
            InMemoryCatalog,
            is_shard_payload,
        )

        if is_shard_payload(flat_dict):
            self.load_sharded(InMemoryCatalog(flat_dict))
            return
        if self._params is None:
            raise SMPValidationError(
                "Model parameters are not initialized; run a step or call "
                "init_from_state_dict with example inputs first."
            )
        leaves, treedef = jax.tree_util.tree_flatten_with_path(self._params)
        new_leaves = []
        for path, old in leaves:
            key = path_key(path)
            if key not in flat_dict:
                raise SMPValidationError(f"Missing parameter '{key}' in state dict.")
            arr = jnp.asarray(flat_dict[key], dtype=old.dtype)
            if arr.shape != old.shape:
                raise SMPValidationError(
                    f"Shape mismatch for '{key}': {arr.shape} vs {old.shape}"
                )
            new_leaves.append(arr)
        params = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self._params), new_leaves
        )
        self._params = jax.device_put(params, self._param_shardings)
        self._decode_params_cache = None

    def load_sharded(self, catalog):
        """Load a sharded checkpoint (``shard_io`` catalog): each process
        reads only the pieces its addressable shards need — no full-tree
        materialization anywhere. Parity: reference per-rank partial load
        (``torch/checkpoint.py:42-122``)."""
        if self._params is None:
            raise SMPValidationError(
                "Model parameters are not initialized; run a step first."
            )
        try:
            self._params = catalog.load_tree(
                self._params, self._param_shardings
            )
            self._decode_params_cache = None
        finally:
            catalog.close()

    # ------------------------------------------------------------------
    # train / eval mode (dropout etc. is explicit in flax; kept for parity)
    # ------------------------------------------------------------------

    def generate(self, input_ids, max_new_tokens, **kwargs):
        """Autoregressive sampling via the KV-cache decode path; see
        ``smp.generate`` (``generation.py``)."""
        from smdistributed_modelparallel_tpu.generation import generate

        return generate(self, input_ids, max_new_tokens, **kwargs)

    def regather_for_decode(self):
        """Decode-ready view of the parameters under pipeline parallelism.

        Training at pp > 1 shards stacked layer parameters over the 'pp'
        mesh axis (one stage's layers per submesh). The decode path is a
        plain forward — no pipeline schedule — so it wants those stacks
        whole: this re-places the parameter tree onto shardings with the
        pp axis stripped (an all-gather along pp over ICI), leaving
        tp/ZeRO axes in place. Training state is untouched: the original
        pp-sharded ``self.params`` remain installed, and the regathered
        tree is cached until the next optimizer step replaces the params.

        Enables the train-at-pp-then-sample workflow the reference
        supports by exporting to HF (SURVEY §2.3; the reference has no
        in-framework decode at all).
        """
        from jax.sharding import NamedSharding

        from smdistributed_modelparallel_tpu.backend.topology import PP_AXIS
        from smdistributed_modelparallel_tpu.parallel.sharding import (
            strip_axis,
        )

        if self._params is None:
            raise SMPValidationError(
                "Model parameters are not initialized; run a step first."
            )
        cached = getattr(self, "_decode_params_cache", None)
        if cached is not None and cached[0] is self._params:
            return cached[1]

        def strip_pp(sharding):
            return NamedSharding(
                sharding.mesh, strip_axis(sharding.spec, PP_AXIS)
            )

        shardings = jax.tree_util.tree_map(strip_pp, self._param_shardings)
        gathered = jax.device_put(self._params, shardings)
        self._decode_params_cache = (self._params, gathered)
        return gathered

    def train(self):
        self._train = True
        return self

    def eval(self):
        self._train = False
        return self

    @property
    def training(self):
        return self._train

