"""Checkpoint save/load/resume.

Parity target: reference ``torch/checkpoint.py:124-536``:
- ``smp.save`` / ``smp.load`` partial per-rank files named
  ``{f}_{pp}_{tp}[_{rdp}].pt`` with format auto-detection (``:42-165``);
- ``save_checkpoint``: ``{tag}_partial/`` directories holding
  ``model_*.pt`` / ``optimizer_*.pt`` / ``fp16_states_*.pt`` /
  ``user_content.pt`` / ``smp_config.pt``, a ``newest`` pointer file, and
  ``num_kept_partial_checkpoints`` retention GC (``:180-298``);
- ``resume_from_checkpoint`` with saved-config compatibility verification
  (``verify_smp_config``, ``:381+,487+``) and deferred load until the model
  and optimizer exist (``state.loaded_model_state``).

TPU-native notes: a "rank's partial state" is the set of addressable shards
of the process (SPMD replaces parameter ownership with sharding); on a
single host a partial checkpoint holds the full tree. Full checkpoints
gather to numpy and can be translated to HF layout via the tp_registry's
translate functions (``translate_if_full`` parity).
"""

import os
import pickle
import re
import shutil
import time

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPRuntimeError,
    SMPValidationError,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

_PARTIAL_RE = re.compile(r"^(?P<stem>.*)_(?P<pp>\d+)_(?P<tp>\d+)(_(?P<rdp>\d+))?$")

# Save ordinals (_SAVE_SEQ) restart at 0 in every process incarnation, but
# marker files survive on disk — so ordinal comparisons are only
# meaningful against markers THIS run wrote. Anything with an mtime before
# the process started is debris of a dead incarnation: without this
# anchor, a stale `.inflight_s37` would outrank every fresh save's ordinal
# forever (blocking `.committed` on a perfectly good re-save), and a stale
# `.done_p1` holding 37 would satisfy a fresh commit's `>= 2` wait before
# the peer's shards actually landed. 2s of slack absorbs coarse filesystem
# timestamp granularity; a dead incarnation's files predate the crash and
# therefore this process by far more than that.
_RUN_START = time.time() - 2.0


def _fresh(path_):
    """True when `path_` was written by THIS process incarnation."""
    try:
        return os.path.getmtime(path_) >= _RUN_START
    except OSError:
        return False


def _coords():
    import smdistributed_modelparallel_tpu as smp

    return smp.pp_rank(), smp.tp_rank(), smp.rdp_rank()


def _partial_name(f, v3=True):
    pp, tp, rdp = _coords()
    stem, ext = os.path.splitext(f)
    if v3:
        return f"{stem}_{pp}_{tp}_{rdp}{ext}"
    return f"{stem}_{pp}_{tp}{ext}"


def save(obj, f, partial=True, v3=True):
    """Parity: reference ``smp.save`` (``torch/checkpoint.py:124-145``)."""
    path = _partial_name(f, v3) if partial else f
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as fh:
        pickle.dump(obj, fh, protocol=4)
    return path


def load(f, partial=True):
    """Parity: reference ``smp.load`` with filename-format auto-detection
    (``torch/checkpoint.py:42-122``): tries v3 ``_{pp}_{tp}_{rdp}``, then v2
    ``_{pp}_{tp}``, then the bare (full) name."""
    candidates = [f]
    if partial:
        candidates = [_partial_name(f, v3=True), _partial_name(f, v3=False), f]
    for path in candidates:
        if os.path.exists(path):
            with open(path, "rb") as fh:
                return pickle.load(fh)
    raise SMPRuntimeError(
        f"Checkpoint not found: tried {candidates}"
    )


# ----------------------------------------------------------------------
# Directory checkpoints
# ----------------------------------------------------------------------


def _smp_config_snapshot():
    cfg = state.cfg
    if cfg is None:
        return {}
    snapshot = dict(cfg.as_dict())
    # Writer census: bounds-based coverage cannot see a missing TAIL shard
    # file (the inferred global extent shrinks with it), so the number of
    # writer processes is the one reliable completeness check a reader
    # has. Consumed by ShardCatalog.verify_complete and
    # scripts/resilience_probe.py; present on the RESUME side too so
    # elastic.classify_mismatches can report a world-size change.
    snapshot["num_processes"] = _process_count()
    # The step edge this checkpoint represents: the recovery supervisor
    # restarts the step engine from it (resilience/supervisor.py) without
    # relying on tag-name conventions or user_content.
    snapshot["step_count"] = state.step_count
    return snapshot


def verify_smp_config(saved):
    """Raise when the saved parallelism layout is incompatible.

    Parity: reference ``verify_smp_config`` (``torch/checkpoint.py:487+``) —
    degrees and TP-relevant flags must match to reuse partial checkpoints.
    """
    cfg = state.cfg
    if cfg is None:
        raise SMPValidationError("smp.init must run before resume_from_checkpoint.")
    keys = (
        "pipeline_parallel_degree",
        "tensor_parallel_degree",
        "microbatches",
        "optimize",
        "prescaled_batch",
        "shard_optimizer_state",
        "sharded_data_parallel_degree",
        "sharded_params",
    )
    mismatches = {
        k: (saved.get(k), getattr(cfg, k))
        for k in keys
        if k in saved and saved.get(k) != getattr(cfg, k)
    }
    if mismatches:
        raise SMPValidationError(
            "Saved checkpoint smp config is incompatible with the current "
            f"config: {mismatches}"
        )


def save_checkpoint(path, tag=None, model=None, optimizer=None,
                    user_content=None, partial=True,
                    num_kept_partial_checkpoints=None, translate_if_full=True,
                    blocking=True):
    """Write a checkpoint directory.

    Parity: reference ``smp.save_checkpoint`` (``torch/checkpoint.py:180-298``):
    ``{path}/{tag}_partial/`` with per-rank files, ``newest`` pointer,
    retention GC. With ``partial=False`` a single gathered file
    ``{path}/{tag}`` is written (optionally HF-translated).

    ``blocking=False`` (TPU extension; the reference has no async saves):
    everything mutable is snapshotted at submission time — this process's
    addressable shards are copied to HOST memory immediately (so later
    ``optimizer.step()`` donation can free the device buffers safely) and
    ``user_content`` is deep-copied — then serialization and disk IO run
    on a background thread while training continues. Saves are serialized
    in submission order (one writer thread), so ``newest`` always ends at
    the latest tag; call ``smp.wait_for_checkpoints()`` to drain and
    surface errors (also runs at exit). For full (gathered) checkpoints
    the gather itself happens eagerly — only serialization/IO is deferred.
    """
    model = model if model is not None else state.model
    optimizer = optimizer if optimizer is not None else state.optimizer
    tag = tag if tag is not None else f"step_{state.step_count}"
    os.makedirs(path, exist_ok=True)
    # Commit ordinal: processes call save_checkpoint in the same order
    # (SPMD discipline), so this per-process counter agrees globally and
    # lets the commit rendezvous distinguish THIS save's markers from a
    # previous save of the same tag. Taken at submission time so async
    # saves keep submission order.
    global _SAVE_SEQ
    _SAVE_SEQ += 1
    seq = _SAVE_SEQ

    # Snapshot everything NOW; the job below touches only captured values.
    # Device trees become host numpy shard payloads eagerly: holding jax
    # Array references would break under the standalone optimizer update's
    # donation (donate_argnums deletes the exact captured buffers).
    user_content = pickle.loads(pickle.dumps(user_content, protocol=4))
    if partial:
        from smdistributed_modelparallel_tpu.shard_io import shard_payload

        model_payload = (
            shard_payload(model.params)
            if model is not None and model.params is not None else None
        )
        opt_payload = (
            shard_payload(optimizer.opt_state)
            if optimizer is not None and optimizer.opt_state is not None
            else None
        )
        scaler_sd = (
            state.loss_scaler.state_dict() if state.loss_scaler else None
        )
        quant_sd = (
            state.quant_state.state_dict()
            if getattr(state, "quant_state", None) is not None else None
        )
        cfg_snapshot = _smp_config_snapshot()
        import smdistributed_modelparallel_tpu as smp

        live_degrees = (smp.pp_size(), smp.tp_size(), smp.rdp_size())

        def job():
            import numpy as np

            ckpt_dir = os.path.join(path, f"{tag}_partial")
            os.makedirs(ckpt_dir, exist_ok=True)
            # In-flight marker before the first shard write: it is the
            # positive evidence the GC orphan sweep requires, so dirs from
            # versions that predate the marker protocol (no markers at
            # all) are never mistaken for interrupted saves. The save
            # ordinal is in the NAME: markers are immutable facts, so a
            # concurrent commit of save N can never delete or mistake
            # save N+1's stamp (see _finish_checkpoint).
            _write_atomic(os.path.join(ckpt_dir, f".inflight_s{seq}"), str(seq))
            # A re-save of an already-committed tag overwrites its shard
            # files IN PLACE; drop the stale .committed so a crash
            # mid-overwrite classifies as an interrupted save (orphan),
            # not a committed checkpoint full of half-written files. Safe
            # under multi-process: every rank runs this before any shard
            # write, and the commit rendezvous (which rewrites .committed)
            # only completes after all ranks' shards land.
            try:
                os.unlink(os.path.join(ckpt_dir, ".committed"))
            except OSError:
                pass
            me = _process_index()
            world = _process_count()
            if me == 0:
                # An elastic re-save of the same tag from a SMALLER world
                # (preempt at 4 processes, resume+save at 2) overwrites
                # p0..p{world-1} in place but would leave the old world's
                # higher-indexed shard files as stale overlap that makes
                # every later load fail coverage; no live rank writes
                # those indexes, so deleting them here cannot race the
                # peers' writers.
                for fname in os.listdir(ckpt_dir):
                    for comp in ("model_shards_p", "optimizer_shards_p"):
                        if fname.startswith(comp) and fname.endswith(".npz"):
                            try:
                                idx = int(fname[len(comp):-4])
                            except ValueError:
                                continue
                            if idx >= world:
                                try:
                                    os.unlink(os.path.join(ckpt_dir, fname))
                                except OSError:
                                    pass
                # Same hazard for the per-(pp,tp,rdp)-coordinate scaler
                # files: a re-save under a different topology leaves the
                # old coordinates' copies (with an outdated loss scale)
                # that the elastic fallback glob in resume could pick.
                # Only coordinates OUTSIDE the live degree ranges are
                # stale — no current rank writes those — plus every copy
                # when this save carries no scaler at all.
                for prefix, present in (
                    ("fp16_states_", scaler_sd is not None),
                    # Same per-coordinate replicated-struct layout for the
                    # fp8 delayed-scaling state (quant_states_*.pt).
                    ("quant_states_", quant_sd is not None),
                ):
                    for fname in os.listdir(ckpt_dir):
                        if not (fname.startswith(prefix)
                                and fname.endswith(".pt")):
                            continue
                        parts = fname[len(prefix):-3].split("_")
                        try:
                            coords = [int(p) for p in parts]
                        except ValueError:
                            continue
                        stale = not present or len(coords) != 3 or any(
                            c >= d for c, d in zip(coords, live_degrees)
                        )
                        if stale:
                            try:
                                os.unlink(os.path.join(ckpt_dir, fname))
                            except OSError:
                                pass
            if model_payload is not None:
                # True per-rank shards (reference: per-rank partial files,
                # torch/checkpoint.py:124-165): each process writes only
                # its replica-0 addressable shards; no process gathers the
                # tree.
                np.savez(
                    os.path.join(ckpt_dir, f"model_shards_p{me}.npz"),
                    **model_payload,
                )
            if opt_payload is not None:
                np.savez(
                    os.path.join(ckpt_dir, f"optimizer_shards_p{me}.npz"),
                    **opt_payload,
                )
            if scaler_sd is not None:
                save(scaler_sd, os.path.join(ckpt_dir, "fp16_states.pt"))
            if quant_sd is not None:
                save(quant_sd, os.path.join(ckpt_dir, "quant_states.pt"))
            with open(os.path.join(ckpt_dir, "user_content.pt"), "wb") as fh:
                pickle.dump(user_content, fh, protocol=4)
            with open(os.path.join(ckpt_dir, "smp_config.pt"), "wb") as fh:
                pickle.dump(cfg_snapshot, fh, protocol=4)
            _commit_checkpoint(
                path, ckpt_dir, tag, num_kept_partial_checkpoints, seq
            )
    else:
        sd = model.state_dict() if model is not None else {}
        if translate_if_full:
            sd = _maybe_translate_to_hf(model, sd)
        payload = {
            "model": sd,
            "user_content": user_content,
            "smp_config": _smp_config_snapshot(),
        }
        if optimizer is not None and optimizer.opt_state is not None:
            payload["optimizer"] = optimizer.state_dict()

        def job():
            with open(os.path.join(path, tag), "wb") as fh:
                pickle.dump(payload, fh, protocol=4)
            _finish_checkpoint(path, tag, partial, num_kept_partial_checkpoints)

    if blocking:
        # The calling thread is parked for the whole serialize+IO+commit:
        # badput, attributed ckpt_save by the goodput ledger. The async
        # path deliberately records nothing — the saver thread's work
        # overlaps training, which is the point of blocking=False.
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        with goodput.scope("ckpt_save"):
            if _SAVER is not None:
                # Serialize behind any in-flight async saves: running
                # inline would race the writer thread on `newest` and
                # retention GC.
                _saver_executor().submit(job).result()
            else:
                job()
    else:
        _PENDING_SAVES.append(_saver_executor().submit(job))


def _process_index():
    import jax

    return jax.process_index()


def _process_count():
    import jax

    return jax.process_count()


_SAVE_SEQ = 0


def _commit_timeout():
    """Commit rendezvous wait bound; read per call so tests (and operators
    mid-run) can override the env after the module imported."""
    return float(os.environ.get("SMP_CKPT_COMMIT_TIMEOUT", "600"))


def _write_atomic(path, text):
    # pid-qualified tmp name: several processes write SOME of these paths
    # concurrently into a shared checkpoint dir (the .inflight stamp, most
    # directly) — with a fixed tmp name, one rank's os.replace deletes the
    # tmp another rank is about to rename and the second rename raises.
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)


def _inflight_seqs(ckpt_dir):
    """Map of in-flight marker filename -> save ordinal for `ckpt_dir`.
    Seq-named markers (``.inflight_s{seq}``) are immutable facts a
    concurrent commit can reason about without read-then-delete races; a
    legacy literal ``.inflight`` (earlier protocol, hand-built test dirs)
    counts with its numeric content, or 0."""
    out = {}
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return out
    for n in names:
        if n.startswith(".inflight_s"):
            try:
                out[n] = int(n[len(".inflight_s"):])
            except ValueError:
                out[n] = 0
        elif n == ".inflight":
            try:
                with open(os.path.join(ckpt_dir, n)) as fh:
                    out[n] = int(fh.read().strip() or 0)
            except (OSError, ValueError):
                out[n] = 0
    return out


def _commit_checkpoint(path, ckpt_dir, tag, num_kept, seq):
    """Single-commit semantics for multi-process partial saves (reference
    ``torch/checkpoint.py:180-298``: one consistent checkpoint per commit).

    Every process atomically writes a ``.done_p{me}`` marker carrying the
    save ordinal once its shard files are on disk; process 0 ALONE waits
    for every peer's marker to reach this ordinal and then publishes
    ``newest`` and runs retention GC. A reader following ``newest`` can no
    longer observe a checkpoint that is missing a peer's shard file, and
    concurrent GC from many processes is gone.
    """
    import time

    import jax

    world = jax.process_count()
    me = _process_index()
    if world > 1:
        _write_atomic(os.path.join(ckpt_dir, f".done_p{me}"), str(seq))
        if me != 0:
            logger.info("Wrote partial checkpoint shards for '%s' (p%d).",
                        tag, me)
            return
        timeout = _commit_timeout()
        deadline = time.monotonic() + timeout
        for p in range(1, world):
            marker = os.path.join(ckpt_dir, f".done_p{p}")
            while True:
                try:
                    # Freshness gate: a dead incarnation's .done (its seq
                    # counter ran higher than this run's) would satisfy
                    # the ordinal check instantly, committing before the
                    # peer's shards of THIS save actually landed.
                    with open(marker) as fh:
                        if (
                            int(fh.read().strip() or 0) >= seq
                            and _fresh(marker)
                        ):
                            break
                except (FileNotFoundError, ValueError):
                    pass
                if time.monotonic() > deadline:
                    raise SMPRuntimeError(
                        f"checkpoint commit timed out waiting for process "
                        f"{p}'s shards under {ckpt_dir} (> {timeout}s)."
                    )
                time.sleep(0.05)
    _finish_checkpoint(path, tag, True, num_kept, seq=seq)


def _finish_checkpoint(path, tag, partial, num_kept, seq=None):
    if partial:
        # Commit marker INSIDE the dir, before `newest` moves: GC (and the
        # resilience probe) can tell a completed checkpoint from the debris
        # of a rank killed mid-save without consulting `newest` history.
        # EXCEPT when a NEWER save of the same tag has already stamped its
        # in-flight marker (back-to-back async re-saves: a non-committer
        # rank can start save N+1's job while the committer is still in
        # save N's commit): its job is overwriting the shard files in
        # place, so publishing .committed now would bless half-written
        # files if the process died before the newer commit. The markers
        # are seq-NAMED and immutable, so this commit can only ever skip
        # or unlink stamps of its own save or older — never a newer one —
        # and the post-write re-check below repairs the one interleaving
        # the pre-check cannot see (newer stamp landing between the check
        # and the .committed write; the newer save's own .committed unlink
        # covers stamps landing after the re-check).
        ckpt_dir = os.path.join(path, f"{tag}_partial")
        my_seq = float("inf") if seq is None else seq

        def newer_live(stamps):
            # Only stamps THIS run wrote can outrank this commit: ordinals
            # restart every incarnation, so a dead run's high-seq stamp
            # must not block .committed forever (see _RUN_START).
            return any(
                s > my_seq and _fresh(os.path.join(ckpt_dir, n))
                for n, s in stamps.items()
            )

        marker = os.path.join(ckpt_dir, ".committed")
        if not newer_live(_inflight_seqs(ckpt_dir)):
            _write_atomic(marker, tag)
            stamps = _inflight_seqs(ckpt_dir)
            if newer_live(stamps):
                try:
                    os.unlink(marker)
                except OSError:
                    pass
            else:
                # Clear this save's stamps AND any dead incarnation's:
                # once committed, the dir's contents are exactly this
                # save's output — stale stamps are no longer evidence.
                for name in stamps:
                    try:
                        os.unlink(os.path.join(ckpt_dir, name))
                    except OSError:
                        pass
    _write_atomic(os.path.join(path, "newest"), tag)
    logger.info("Saved %s checkpoint '%s' under %s.",
                "partial" if partial else "full", tag, path)
    if partial and num_kept is not None:
        _gc_partial_checkpoints(path, num_kept)


_SAVER = None
_PENDING_SAVES = []


def _saver_executor():
    global _SAVER
    if _SAVER is None:
        import atexit
        from concurrent.futures import ThreadPoolExecutor

        # ONE worker: saves execute in submission order, so the `newest`
        # pointer always converges to the latest submitted tag.
        _SAVER = ThreadPoolExecutor(max_workers=1, thread_name_prefix="smp-ckpt")
        atexit.register(wait_for_checkpoints)
    return _SAVER


def wait_for_checkpoints():
    """Drain pending non-blocking saves; re-raises the first failure.
    Registered atexit so fire-and-forget saves still complete."""
    global _PENDING_SAVES
    pending, _PENDING_SAVES = _PENDING_SAVES, []
    first_err = None
    for fut in pending:
        try:
            fut.result()
        except Exception as e:  # noqa: BLE001 - surfaced to the caller
            logger.error("async checkpoint save failed: %s", e)
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err


def _gc_partial_checkpoints(path, keep):
    """Parity: reference retention GC (``torch/checkpoint.py:270-298``),
    plus crash hygiene: a rank killed mid-save leaves an uncommitted
    ``{tag}_partial/`` dir that the retention pass used to count (and keep)
    forever. A dir is swept as an orphan only on POSITIVE evidence of an
    interrupted save — the ``.inflight`` marker (stamped at save start,
    removed at commit) without ``.committed`` — and only once older than
    the commit timeout (younger ones may be a peer's in-flight save).
    Dirs with neither marker predate the marker protocol and count as
    committed, so an upgrade can never sweep previously valid
    checkpoints."""
    import time

    if keep <= 0:
        return
    dirs = [
        d for d in os.listdir(path)
        if d.endswith("_partial") and os.path.isdir(os.path.join(path, d))
    ]
    committed, orphans = [], []
    now = time.time()
    stale_after = _commit_timeout()
    for d in dirs:
        full = os.path.join(path, d)
        if os.path.exists(os.path.join(full, ".committed")):
            committed.append(d)
            continue
        # Positive interruption evidence only: an in-flight stamp without
        # .committed. (.done_p* is NOT evidence — committed pre-marker
        # multi-process dirs retain theirs.)
        if not _inflight_seqs(full):
            committed.append(d)  # legacy (pre-marker) dir
            continue
        try:
            age = now - os.path.getmtime(full)
        except OSError:
            continue  # swept by a concurrent GC
        if age > stale_after:
            orphans.append(d)
    for d in orphans:
        shutil.rmtree(os.path.join(path, d), ignore_errors=True)
        logger.warning(
            "Swept orphaned (uncommitted, > %.0fs old) checkpoint dir %s — "
            "debris of an interrupted save.", stale_after, d,
        )
    committed.sort(key=lambda d: os.path.getmtime(os.path.join(path, d)))
    for d in committed[:-keep]:
        shutil.rmtree(os.path.join(path, d), ignore_errors=True)
        logger.info("Removed old partial checkpoint %s.", d)


def resume_from_checkpoint(path, tag=None, partial=True, strict=True,
                           load_optimizer=True, load_sharded_optimizer_state=True,
                           elastic=True):
    """Load a checkpoint; defer application until model/optimizer exist.

    Parity: reference ``smp.resume_from_checkpoint``
    (``torch/checkpoint.py:381+``), EXCEPT that a parallelism-layout
    mismatch is no longer fatal by default: with ``elastic=True`` a
    checkpoint saved under a different (pp, tp, rdp) degree layout is
    resharded on load — each leaf reassembles from its logical shard
    bounds and re-slices per the resuming mesh's shardings
    (``resilience/elastic.py``; the reference's ``verify_smp_config``
    hard-fail is restored with ``elastic=False``).
    Returns the saved user_content.
    """
    from smdistributed_modelparallel_tpu.utils.goodput import goodput

    # The restore blocks training end to end: badput (ckpt_restore) in
    # the goodput ledger. One attribute test while disarmed.
    with goodput.scope("ckpt_restore"):
        return _resume_from_checkpoint(
            path, tag=tag, partial=partial, strict=strict,
            load_optimizer=load_optimizer,
            load_sharded_optimizer_state=load_sharded_optimizer_state,
            elastic=elastic,
        )


def _resume_from_checkpoint(path, tag=None, partial=True, strict=True,
                            load_optimizer=True,
                            load_sharded_optimizer_state=True,
                            elastic=True):
    if tag is None:
        newest = os.path.join(path, "newest")
        if not os.path.exists(newest):
            raise SMPRuntimeError(f"No 'newest' pointer file under {path}.")
        with open(newest) as fh:
            tag = fh.read().strip()

    if elastic:
        # Warm-start consult (smp.exec_cache): an elastic resume at a new
        # topology is exactly the cold start the persistent executable
        # cache exists for — count the candidate entries before the first
        # step pays (or skips) the recompile. One env test when the cache
        # is off. A supervisor-driven recovery already consulted under
        # the "recovery" label; don't double-count it here.
        from smdistributed_modelparallel_tpu.resilience.supervisor import (
            supervisor,
        )
        from smdistributed_modelparallel_tpu.utils import exec_cache

        if not supervisor._recovering:
            exec_cache.note_warm_start("elastic_resume")

    def _verify(saved_cfg, shard_format, what):
        try:
            verify_smp_config(saved_cfg)
        except SMPValidationError:
            # Elastic downgrades topology mismatches only — resuming
            # before smp.init stays an error either way.
            if not elastic or state.cfg is None:
                raise
            from smdistributed_modelparallel_tpu.resilience.elastic import (
                begin_elastic_resume,
            )

            begin_elastic_resume(
                saved_cfg, _smp_config_snapshot(), shard_format, what=what
            )

    if partial:
        import glob as _glob

        from smdistributed_modelparallel_tpu.shard_io import ShardCatalog

        ckpt_dir = os.path.join(path, f"{tag}_partial")
        if not os.path.isdir(ckpt_dir):
            raise SMPRuntimeError(f"Partial checkpoint dir not found: {ckpt_dir}")
        if (
            not os.path.exists(os.path.join(ckpt_dir, ".committed"))
            and _inflight_seqs(ckpt_dir)
        ):
            # An in-flight stamp without the commit marker means a save
            # (possibly an in-place RE-save of a previously good tag) was
            # interrupted: the shard files may be half-overwritten, and
            # every per-file check would still pass — bounds and census
            # don't change when only the tensor BYTES are torn. Refuse
            # rather than resume from silently inconsistent state.
            raise SMPRuntimeError(
                f"Checkpoint '{tag}' under {path} was interrupted mid-save "
                "(in-flight markers present, no commit marker): its shard "
                "files may be half-written. Resume an older committed tag "
                "(scripts/resilience_probe.py lists them), or remove the "
                "in-flight markers only if you are certain every rank's "
                "save completed."
            )
        with open(os.path.join(ckpt_dir, "smp_config.pt"), "rb") as fh:
            saved_cfg = pickle.load(fh)
        shard_format = bool(
            _glob.glob(os.path.join(ckpt_dir, "model_shards_p*.npz"))
        )
        _verify(saved_cfg, shard_format, what=f"of '{tag}'")
        if shard_format:
            model_sd = ShardCatalog(ckpt_dir, "model")
            # Coverage pre-flight: gaps (a peer's file missing from this
            # filesystem) must fail HERE, not inside the deferred apply at
            # the first training step. The writer census catches what
            # bounds coverage cannot: a missing TAIL shard file.
            model_sd.verify_complete(
                what=f"model checkpoint '{tag}'",
                expected_files=saved_cfg.get("num_processes"),
            )
        else:  # legacy gathered-pickle layout
            model_sd = load(os.path.join(ckpt_dir, "model.pt"))
        opt_sd = None
        if load_optimizer:
            if _glob.glob(os.path.join(ckpt_dir, "optimizer_shards_p*.npz")):
                opt_sd = ShardCatalog(ckpt_dir, "optimizer")
                opt_sd.verify_complete(
                    what=f"optimizer checkpoint '{tag}'",
                    expected_files=saved_cfg.get("num_processes"),
                )
            else:
                try:
                    opt_sd = load(os.path.join(ckpt_dir, "optimizer.pt"))
                except SMPRuntimeError:
                    opt_sd = None
        if state.loss_scaler is not None:
            fp16_path = os.path.join(ckpt_dir, "fp16_states.pt")
            if os.path.exists(_partial_name(fp16_path)):
                state.loss_scaler.load_state_dict(load(fp16_path))
            else:
                # Elastic resume: the saved rank coordinates differ from
                # ours, so the exact per-coord name misses. Scaler state is
                # one replicated scalar struct — any saved copy is THE copy.
                stem, ext = os.path.splitext(fp16_path)
                any_fp16 = sorted(_glob.glob(f"{stem}_*{ext}"))
                if any_fp16:
                    with open(any_fp16[0], "rb") as fh:
                        state.loss_scaler.load_state_dict(pickle.load(fh))
        if getattr(state, "quant_state", None) is not None:
            quant_path = os.path.join(ckpt_dir, "quant_states.pt")
            if os.path.exists(_partial_name(quant_path)):
                state.quant_state.load_state_dict(load(quant_path))
            else:
                # Elastic resume for the replicated fp8 amax/scale struct:
                # any saved coordinate's copy is THE copy.
                stem, ext = os.path.splitext(quant_path)
                any_quant = sorted(_glob.glob(f"{stem}_*{ext}"))
                if any_quant:
                    with open(any_quant[0], "rb") as fh:
                        state.quant_state.load_state_dict(pickle.load(fh))
        with open(os.path.join(ckpt_dir, "user_content.pt"), "rb") as fh:
            user_content = pickle.load(fh)
    else:
        with open(os.path.join(path, tag), "rb") as fh:
            payload = pickle.load(fh)
        # A full checkpoint is a gathered logical state dict — always
        # reshardable, so elastic resume only needs the record/log.
        _verify(payload.get("smp_config", {}), True, what=f"of full '{tag}'")
        model_sd = payload.get("model")
        opt_sd = payload.get("optimizer") if load_optimizer else None
        user_content = payload.get("user_content")

    _stash_or_apply(model_sd, opt_sd)
    logger.info("Resumed from checkpoint '%s' under %s.", tag, path)
    return user_content


def _stash_or_apply(model_sd, opt_sd):
    from smdistributed_modelparallel_tpu.shard_io import ShardCatalog

    model = state.model
    if model is not None and model.params is not None:
        if isinstance(model_sd, ShardCatalog):
            model.load_sharded(model_sd)
        else:
            model.load_state_dict(model_sd)
    else:
        # Applied by DistributedModel once params materialize (parity:
        # reference state.loaded_model_state, torch/model.py:245-251).
        state.loaded_model_state = model_sd
    opt = state.optimizer
    if opt_sd is None:
        return
    if opt is not None and opt.opt_state is not None:
        if isinstance(opt_sd, ShardCatalog):
            opt.load_sharded(opt_sd)
        else:
            opt.load_state_dict(opt_sd)
    else:
        state.loaded_optimizer_state = opt_sd


def _maybe_translate_to_hf(model, sd):
    """Translate a gathered state dict to the original (HF) layout when the
    root module has registered translate functions (parity: reference
    ``translate_if_full``, ``torch/nn/predefined_hooks.py:82-151``)."""
    if model is None:
        return sd
    fns = getattr(model, "_translate_functions", None)
    if fns is None and state.tp_registry is not None:
        from smdistributed_modelparallel_tpu.nn.auto_distribute import (
            HookedModule,
        )

        mod = model.module
        if isinstance(mod, HookedModule):
            mod = mod.inner
        fns = state.tp_registry.translate_functions(type(mod))
    if fns is None:
        return sd
    to_hf = fns[0] if isinstance(fns, (tuple, list)) else fns
    try:
        return to_hf(sd)
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("HF state-dict translation failed (%s); saving raw.", e)
        return sd
