"""DistributedOptimizer: optax-backed optimizer with smp semantics.

Parity target: reference ``torch/optimizers/optimizer.py:437-549``
(``DistributedOptimizer``): wraps the user optimizer, makes ``step()``
distribution-aware (sharded update + allgather under
``shard_optimizer_state``), and provides TP/shard-aware state_dicts. Here
the user optimizer is an ``optax.GradientTransformation``; ``step()``
consumes the gradients stashed by the last ``@smp.step`` call and applies a
jit-compiled donated update. Under ``shard_optimizer_state`` (M4) the
optimizer state carries rdp-sharded PartitionSpecs — the reference's
contiguous-buffer/virtual-parameter machinery (``torch/model.py:1237-1340``)
reduces to sharding annotations, and XLA emits the reduce-scatter/allgather
pair of a sharded update.
"""

import numpy as np

import jax
import jax.numpy as jnp
import optax

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.module_manager import path_key
from smdistributed_modelparallel_tpu.utils import health
from smdistributed_modelparallel_tpu.utils import profiling
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPValidationError,
    StepUsageError,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


_OPTIMIZER_SERIAL = [0]


class DistributedOptimizer:
    def __init__(self, tx, model=None, grad_clip_norm=None):
        # Monotonic serial for step-cache keys: id() can be reused by the
        # allocator after a replaced optimizer is collected, which would let
        # a new optimizer silently hit the old optimizer's cached fused
        # update.
        _OPTIMIZER_SERIAL[0] += 1
        self._serial = _OPTIMIZER_SERIAL[0]
        if not isinstance(tx, optax.GradientTransformation):
            raise SMPValidationError(
                "DistributedOptimizer expects an optax.GradientTransformation "
                f"(got {type(tx).__name__})."
            )
        self.tx = tx
        self.model = model if model is not None else state.model
        if self.model is None:
            raise SMPValidationError("Create smp.DistributedModel before the optimizer.")
        self.grad_clip_norm = grad_clip_norm
        self._opt_state = None
        self._update = None
        state.optimizer = self

    # ------------------------------------------------------------------

    def _ensure_state(self):
        if self._opt_state is not None:
            return
        if self.model.params is None:
            raise StepUsageError(
                "Optimizer state is created lazily from model parameters; run a "
                "step (or initialize the model) before optimizer.step()."
            )
        from smdistributed_modelparallel_tpu.parallel.zero import opt_state_shardings

        self._opt_state = jax.jit(self.tx.init)(self.model.params)
        opt_shardings = opt_state_shardings(self._opt_state, self.model)
        if opt_shardings is not None:
            self._opt_state = jax.device_put(self._opt_state, opt_shardings)
        if state.loaded_optimizer_state is not None:
            # Deferred resume payload (parity: reference
            # torch/optimizers/optimizer.py:545-547).
            from smdistributed_modelparallel_tpu.shard_io import ShardCatalog

            logger.info("Applying deferred checkpoint state to optimizer.")
            payload = state.loaded_optimizer_state
            state.loaded_optimizer_state = None
            if isinstance(payload, ShardCatalog):
                self.load_sharded(payload)
            else:
                self.load_state_dict(payload)

        update = self.build_update_fn()

        # Pin output shardings: without them GSPMD may return params
        # resharded to whatever layout the update program preferred (e.g. a
        # tp-sharded embedding coming back from tp-sharded grads), after
        # which the step's AOT executable rejects its inputs and every
        # subsequent step pays jit-dispatch. Parity: the reference's
        # post-step param allgather restores the canonical placement
        # (torch/optimizers/optimizer.py:355-391); here the canonical
        # placement is the partitioner's _param_shardings.
        param_pin = self.model._param_shardings
        opt_pin = opt_shardings if opt_shardings is not None else (
            jax.tree_util.tree_map(lambda l: l.sharding, self._opt_state)
        )
        out_shardings = None
        if param_pin is not None:
            out_shardings = (param_pin, opt_pin)
        self._update = jax.jit(
            update, donate_argnums=(0, 1), out_shardings=out_shardings
        )

    def build_update_fn(self):
        """Pure (params, opt_state, grads) -> (new_params, new_opt_state)
        update, shared between the standalone jitted update and the fused
        in-step update (``fused_optimizer_step``)."""
        clip = self.grad_clip_norm
        tx = self.tx

        def update(params, opt_state, grads):
            # In-graph profiler region: the optimizer's ops carry this
            # scope in HLO op metadata, so an XLA trace of the fused step
            # shows where the update ends and the model compute begins.
            with profiling.named_region("smp/optimizer/update"):
                if clip is not None:
                    gnorm = optax.global_norm(grads)
                    scale = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                    grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
                updates, new_opt_state = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
            return new_params, new_opt_state

        return update

    # ------------------------------------------------------------------

    def step(self):
        """Apply the gradients stashed by the last @smp.step call.

        Parity: reference patched ``step()``
        (``torch/optimizers/optimizer.py:355-391``) — sharded update then
        param allgather; under XLA both emerge from the sharding specs.
        """
        with profiling.region("optimizer/step"):
            self._step_impl()

    def _step_impl(self):
        if self.model._grads_store is None:
            raise StepUsageError(
                "No gradients available: run an @smp.step function with "
                "model.backward(loss) before optimizer.step()."
            )
        # Fused path (``fused_optimizer_step``): the step program already
        # computed (new_params, new_opt_state) in the same launch; installing
        # them is a host-side pointer swap. Guarded by grads identity so a
        # user who replaced model._grads (custom grad processing) falls back
        # to the real update below. The identity check deliberately avoids
        # reading model._grads (that would force the lazy average).
        pending = getattr(self.model, "_pending_update", None)
        self.model._dropped_updates = 0  # the loop does call optimizer.step()
        if pending is not None:
            self.model._pending_update = None
            if (
                pending[0] is not None
                and self.model._grads_token_is(pending[0])
                and self.model._params is pending[3]
                and self._opt_state is pending[4]
            ):
                self.model.params = pending[1]
                self._opt_state = pending[2]
                if health.enabled():
                    # Grad-norm / update-ratio gauges (before the grads
                    # store is cleared). Under fused_step_donation the
                    # pending tuple is self-referential (old params gone)
                    # — the ratio is skipped there.
                    old = pending[3] if pending[3] is not pending[1] else None
                    health.record_update_stats(self.model, old, pending[1])
                self.model._grads = None
                self.model._grads_finite = None
                return
        grads = self.model._grads
        self._ensure_state()
        scaler = state.loss_scaler
        finite = self.model._grads_finite
        if finite is not None and not bool(finite):
            # Overflow under fp16 loss scaling: skip the update, back the
            # scale off (reference Bit16_Optimizer skip path; agreement
            # across ranks is implicit — the flag is one SPMD value).
            if scaler is not None:
                scaler.update(True)
            self.model._grads = None
            self.model._grads_finite = None
            return
        with jax.set_mesh(state.mesh):
            new_params, self._opt_state = self._update(
                self.model.params, self._opt_state, grads
            )
        self.model.params = new_params
        if health.enabled():
            # The pre-update params were donated into _update, so only the
            # grad/param norms are recorded here; the update ratio comes
            # from the fused path, which retains the old tree.
            health.record_update_stats(self.model, None, new_params)
        self.model._grads = None
        self.model._grads_finite = None
        if scaler is not None:
            scaler.update(False)

    def zero_grad(self):
        self.model._grads = None

    # ------------------------------------------------------------------

    @property
    def opt_state(self):
        return self._opt_state

    def state_dict(self):
        """Gathered optimizer state as numpy arrays keyed by pytree path."""
        self._ensure_state()
        flat = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(self._opt_state)[0]:
            key = path_key(path)
            flat[key] = np.asarray(jax.device_get(leaf)) if isinstance(
                leaf, jax.Array
            ) else leaf
        return flat

    def local_state_dict(self):
        """Per-process shard payload (parity: reference ``local_state_dict``;
        r2 weak item: this used to gather the full state). Round-trips
        through ``load_state_dict``."""
        from smdistributed_modelparallel_tpu.shard_io import shard_payload

        self._ensure_state()
        return shard_payload(self._opt_state, dedupe_global=False)

    def load_sharded(self, catalog):
        """Load a sharded optimizer checkpoint (``shard_io`` catalog)."""
        self._ensure_state()
        shardings = jax.tree_util.tree_map(
            lambda l: l.sharding if isinstance(l, jax.Array) else None,
            self._opt_state,
        )
        try:
            self._opt_state = catalog.load_tree(self._opt_state, shardings)
        finally:
            catalog.close()

    def load_state_dict(self, flat_dict):
        from smdistributed_modelparallel_tpu.shard_io import (
            InMemoryCatalog,
            is_shard_payload,
        )

        if is_shard_payload(flat_dict):
            self.load_sharded(InMemoryCatalog(flat_dict))
            return
        self._ensure_state()
        leaves, _ = jax.tree_util.tree_flatten_with_path(self._opt_state)
        new = []
        for path, old in leaves:
            key = path_key(path)
            if key in flat_dict and isinstance(old, jax.Array):
                arr = jnp.asarray(flat_dict[key], dtype=old.dtype)
                new.append(jax.device_put(arr, old.sharding))
            else:
                new.append(old)
        self._opt_state = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self._opt_state), new
        )

