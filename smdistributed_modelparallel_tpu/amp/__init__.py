"""AMP-style grad scaler.

Parity target: reference ``torch/amp/scaler.py:22-194`` — a
``torch.cuda.amp.GradScaler`` subclass whose found_inf flag is allgathered
across the PP group so all pp_ranks skip steps together. Under SPMD the
flag is computed once inside the compiled step; this class adapts the
torch-style scale/step/update API onto the framework's scaler.
"""

from smdistributed_modelparallel_tpu.fp16.loss_scaler import DynamicLossScaler


class GradScaler(DynamicLossScaler):
    """torch.cuda.amp.GradScaler-shaped surface over DynamicLossScaler."""

    def __init__(self, init_scale=2.0 ** 16, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000, enabled=True):
        super().__init__(
            init_scale=init_scale,
            scale_factor=growth_factor,
            scale_window=growth_interval,
            backoff_factor=backoff_factor,
        )
        self.enabled = enabled

    def scale(self, loss):
        return loss * self.loss_scale if self.enabled else loss

    def get_scale(self):
        return self.loss_scale

    def step(self, optimizer):
        # The framework's DistributedOptimizer.step already consults the
        # step's finite flag; delegate.
        optimizer.step()

    def unscale_(self, optimizer):
        # Grad unscaling happens inside the compiled step; kept for API
        # parity with the reference's torch surface.
        pass
