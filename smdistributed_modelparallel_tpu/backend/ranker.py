"""Rank / group arithmetic under a placement strategy.

Parity target: reference ``backend/core.py:26-162`` (``Ranker``). The
reference derives (pp, tp, rdp) coordinates from a global rank via
stride arithmetic over the 3-letter placement permutation; here the same
mapping is realized as a numpy rank grid — ``grid[coords] == rank`` — which
is also exactly the device array handed to ``jax.sharding.Mesh`` (see
``topology.py``), so rank arithmetic and mesh construction cannot drift
apart.

Conventions (same as reference):
- placement string is a permutation of "P" (pipeline), "D" (reduced data
  parallel), "T" (tensor); the right-most letter varies fastest across
  neighboring ranks. "cluster" == "DPT", "spread" == "TPD".
- dp is the composite of T and D; mp is the composite of P and T. In a
  composite, the letter appearing later in the placement string is the
  minor (fast-varying) component.
"""

import numpy as np

PLACEMENT_ALIASES = {"cluster": "DPT", "spread": "TPD"}


def normalize_placement(ps):
    return PLACEMENT_ALIASES.get(ps, ps)


class Ranker:
    def __init__(self, placement_strategy, rdp_size, pp_size, tp_size):
        self.ps = normalize_placement(placement_strategy)
        assert sorted(self.ps) == ["D", "P", "T"], f"bad placement {placement_strategy}"
        self.sizes = {"P": pp_size, "D": rdp_size, "T": tp_size}
        self.size = pp_size * rdp_size * tp_size
        shape = tuple(self.sizes[d] for d in self.ps)
        self._grid = np.arange(self.size).reshape(shape)
        self._coords = np.empty((self.size, 3), dtype=np.int64)  # columns follow self.ps
        for idx, rank in np.ndenumerate(self._grid):
            self._coords[int(rank)] = idx

    # -- single-dim ranks ----------------------------------------------

    def _coord(self, rank, dim):
        return int(self._coords[rank][self.ps.index(dim)])

    def get_pp_rank(self, rank):
        return self._coord(rank, "P")

    def get_tp_rank(self, rank):
        return self._coord(rank, "T")

    def get_rdp_rank(self, rank):
        return self._coord(rank, "D")

    # -- composite ranks -----------------------------------------------

    def _major_minor(self, a, b):
        """Of two dims, the one earlier in the placement string is major."""
        return (a, b) if self.ps.index(a) < self.ps.index(b) else (b, a)

    def _composite_rank(self, rank, a, b):
        major, minor = self._major_minor(a, b)
        return self._coord(rank, minor) + self.sizes[minor] * self._coord(rank, major)

    def get_dp_rank(self, rank):
        return self._composite_rank(rank, "T", "D")

    def get_mp_rank(self, rank):
        return self._composite_rank(rank, "P", "T")

    # -- groups ---------------------------------------------------------

    def _group(self, rank, dims):
        """All ranks sharing this rank's coordinates outside `dims`, in
        placement order (earlier letters outer)."""
        index = tuple(
            slice(None) if d in dims else self._coord(rank, d) for d in self.ps
        )
        return [int(r) for r in self._grid[index].ravel()]

    def get_pp_group(self, rank):
        return self._group(rank, "P")

    def get_tp_group(self, rank):
        return self._group(rank, "T")

    def get_rdp_group(self, rank):
        return self._group(rank, "D")

    def get_dp_group(self, rank):
        return self._group(rank, "TD")

    def get_mp_group(self, rank):
        return self._group(rank, "PT")

    def get_world_group(self):
        return list(range(self.size))

    # -- translations ---------------------------------------------------

    def translate(self, pp_rank, tp_rank, rdp_rank):
        coords = {"P": pp_rank, "T": tp_rank, "D": rdp_rank}
        return int(self._grid[tuple(coords[d] for d in self.ps)])

    def _decompose(self, comp_rank, a, b):
        major, minor = self._major_minor(a, b)
        return {minor: comp_rank % self.sizes[minor], major: comp_rank // self.sizes[minor]}

    def get_rdp_rank_from_dp_rank(self, dp_rank):
        return self._decompose(dp_rank, "T", "D")["D"]

    def get_tp_rank_from_dp_rank(self, dp_rank):
        return self._decompose(dp_rank, "T", "D")["T"]

    def get_pp_rank_from_mp_rank(self, mp_rank):
        return self._decompose(mp_rank, "P", "T")["P"]

    def get_tp_rank_from_mp_rank(self, mp_rank):
        return self._decompose(mp_rank, "P", "T")["T"]

    # -- grid access (used by topology.build_mesh) ----------------------

    @property
    def grid(self):
        """(sizes in placement order) ndarray with grid[coords] == rank."""
        return self._grid
