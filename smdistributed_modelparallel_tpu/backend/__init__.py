"""Framework-independent backend core (config, topology, state, splitting).

Parity target: reference ``smdistributed/modelparallel/backend/`` (SURVEY §2.2).
"""
