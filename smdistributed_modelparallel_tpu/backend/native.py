"""Loader + ctypes wrappers for the native host runtime (``libsmptpu.so``).

Parity target: the reference loads its C++ backend ``smplib`` via ctypes at
init (reference ``backend/core.py:234-290``, symbol list in SURVEY §5.8).
The TPU build's device data plane is compiled XLA — collectives ride ICI
inside the step program — so the native layer here is deliberately smaller:

- **message bus** (``smp_async_send`` / ``smp_wait_recv`` /
  ``smp_poll_recv`` / ``smp_retrieve_object`` / ``smp_clean_recv_resources``
  — N2 parity): TCP mesh between host processes for control-plane object
  P2P and real subgroup barriers;
- **timeline recorder** (``smp_create_timeline`` family — N5 parity).

The library is built on demand from ``native/`` with the in-image g++
toolchain; every caller must tolerate ``load() is None`` (no toolchain, or
``SMP_DISABLE_NATIVE=1``) and fall back to pure Python.
"""

import ctypes
import os
import subprocess
import threading
import time

from smdistributed_modelparallel_tpu.resilience.chaos import chaos
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPPeerLost,
    SMPWatchdogTimeout,
)
from smdistributed_modelparallel_tpu.utils.flight_recorder import flight_recorder
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import watchdog

logger = get_logger()

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsmptpu.so")

_lock = threading.Lock()
_lib = None
_load_attempted = False


def _stale():
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    src_dir = os.path.join(_NATIVE_DIR, "src")
    try:
        return any(
            os.path.getmtime(os.path.join(src_dir, f)) > lib_mtime
            for f in os.listdir(src_dir)
            if f.endswith(".cc")
        )
    except OSError:
        return False


def _build():
    """Build libsmptpu.so under an inter-process file lock, into a temp
    name, installed by atomic rename — N processes hit smp.init (and so
    this builder) simultaneously on one host, and an unlocked in-place make
    can hand a half-written .so to a peer's dlopen (worse: the corrupt file
    ends up newer than the sources, so _stale() never rebuilds it)."""
    import fcntl

    lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
    tmp_name = f"libsmptpu.build.{os.getpid()}.so"
    try:
        with open(lock_path, "w") as lock_fh:
            fcntl.flock(lock_fh, fcntl.LOCK_EX)
            if not _stale():  # a peer built it while we waited
                return True
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, f"LIB={tmp_name}"],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(os.path.join(_NATIVE_DIR, tmp_name), _LIB_PATH)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native build failed (%s); using pure-Python fallbacks.", e)
        try:
            os.unlink(os.path.join(_NATIVE_DIR, tmp_name))
        except OSError:
            pass
        return False


def _declare(lib):
    c = ctypes
    lib.smp_bus_listen.argtypes = [c.c_int]
    lib.smp_bus_listen.restype = c.c_int
    lib.smp_bus_connect.argtypes = [c.c_int, c.c_int, c.c_char_p]
    lib.smp_bus_connect.restype = c.c_int
    lib.smp_async_send.argtypes = [c.c_int, c.c_char_p, c.c_int64, c.c_int64]
    lib.smp_async_send.restype = c.c_int
    lib.smp_poll_recv.argtypes = [c.c_int, c.c_int64]
    lib.smp_poll_recv.restype = c.c_int
    lib.smp_wait_recv.argtypes = [c.c_int, c.c_int64, c.c_int]
    lib.smp_wait_recv.restype = c.c_int64
    lib.smp_retrieve_object.argtypes = [
        c.c_int, c.c_int64, c.POINTER(c.c_uint8), c.c_int64,
    ]
    lib.smp_retrieve_object.restype = c.c_int64
    lib.smp_clean_recv_resources.argtypes = [c.c_int, c.c_int64]
    lib.smp_clean_recv_resources.restype = None
    lib.smp_bus_barrier.argtypes = [c.POINTER(c.c_int), c.c_int, c.c_int]
    lib.smp_bus_barrier.restype = c.c_int
    lib.smp_peer_down.argtypes = [c.c_int]
    lib.smp_peer_down.restype = c.c_int
    lib.smp_bus_shutdown.argtypes = []
    lib.smp_bus_shutdown.restype = None

    lib.smp_create_timeline.argtypes = [c.c_char_p]
    lib.smp_create_timeline.restype = c.c_void_p
    lib.smp_destroy_timeline.argtypes = [c.c_void_p]
    lib.smp_destroy_timeline.restype = None
    lib.smp_timeline_start_step.argtypes = [c.c_void_p, c.c_int64]
    lib.smp_timeline_start_step.restype = None
    lib.smp_timeline_end_step.argtypes = [c.c_void_p, c.c_int64]
    lib.smp_timeline_end_step.restype = c.c_int64
    lib.smp_timeline_record_pipeline_event.argtypes = [
        c.c_void_p, c.c_char_p, c.c_double, c.c_double, c.c_int, c.c_char_p,
    ]
    lib.smp_timeline_record_pipeline_event.restype = None
    lib.smp_timeline_record_instant.argtypes = [
        c.c_void_p, c.c_char_p, c.c_double, c.c_char_p,
    ]
    lib.smp_timeline_record_instant.restype = None
    lib.smp_timeline_flush.argtypes = [c.c_void_p, c.c_int]
    lib.smp_timeline_flush.restype = c.c_int
    lib.smp_timeline_event_count.argtypes = [c.c_void_p]
    lib.smp_timeline_event_count.restype = c.c_int64
    return lib


def load():
    """Return the loaded native library, building it if needed; None when
    unavailable (caller falls back to pure Python)."""
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("SMP_DISABLE_NATIVE", "0") == "1":
            return None
        if _stale() and not _build():
            return None
        try:
            _lib = _declare(ctypes.CDLL(_LIB_PATH))
        except OSError as e:
            logger.warning("could not load %s: %s", _LIB_PATH, e)
            _lib = None
        return _lib


def available():
    return load() is not None


class MessageBus:
    """Python face of the native bus; one per process.

    Transaction ids follow the reference's ``TransactionIdentifier``
    convention (2*id + is_user_api, reference ``backend/collectives.py:61-66``)
    — the bus itself only sees opaque int64 keys.
    """

    def __init__(self, lib):
        self._lib = lib
        self.rank = 0
        self.world = 1
        self.port = None
        self._connected = False

    def listen(self, port=0):
        self.port = self._lib.smp_bus_listen(port)
        if self.port < 0:
            raise OSError("smp_bus_listen failed")
        return self.port

    def connect(self, rank, world, endpoints):
        """endpoints: list of "host:port" strings indexed by process."""
        joined = ",".join(endpoints).encode()
        if self._lib.smp_bus_connect(rank, world, joined) != 0:
            raise OSError("smp_bus_connect failed")
        self.rank, self.world = rank, world
        self._connected = True

    def send_bytes(self, dest, payload, tx):
        """Enqueue one message, with dead-link detection + bounded retry.

        The C side reports two failures: ``-1`` (bus not connected / bad
        destination — caller misuse, raised as OSError immediately, as
        before) and ``-2`` (the sender thread for this link gave up:
        connect budget exhausted or the peer died mid-stream —
        ``message_bus.cc`` ``SendQueue.dead``). A dead link retries
        ``SMP_BUS_SEND_RETRIES`` times (default 3) with exponential
        backoff, then raises a structured ``SMPPeerLost`` carrying the
        peer index: a typed, attributable failure instead of frames
        silently queueing forever while the receiver hangs until the
        watchdog fires. The C side keeps a dead link marked for a ~2s
        cool-down — longer than the default backoff burst, so one send's
        retries fail typed and fast — and then revives it (fresh sender
        thread, fresh connect budget) on the next attempt, which is what
        lets a send to a RESTARTED peer eventually go through.
        """
        injected = chaos.on_bus_send(dest)
        if injected == "drop":
            flight_recorder.record_wait("bus_send", dest, tx, "chaos_drop", 0.0)
            return
        try:
            retries = max(int(os.environ.get("SMP_BUS_SEND_RETRIES", "3")), 0)
        except ValueError:
            logger.warning(
                "ignoring non-integer SMP_BUS_SEND_RETRIES=%r; using 3.",
                os.environ.get("SMP_BUS_SEND_RETRIES"),
            )
            retries = 3
        delay = 0.05
        for attempt in range(retries + 1):
            rc = (
                -2 if injected == "error" and attempt == 0
                else self._lib.smp_async_send(dest, payload, len(payload), tx)
            )
            if rc == 0:
                if attempt:
                    logger.warning(
                        "bus send to process %d succeeded after %d retr%s.",
                        dest, attempt, "y" if attempt == 1 else "ies",
                    )
                return
            if rc == -1:
                raise OSError(f"smp_async_send to {dest} failed ({rc})")
            if attempt < retries:
                flight_recorder.record_wait(
                    "bus_send", dest, tx, "retry", delay
                )
                time.sleep(delay)
                delay *= 2
        flight_recorder.record_wait("bus_send", dest, tx, "peer_lost", 0.0)
        raise SMPPeerLost(
            dest,
            f"native-bus link to process {dest} is dead (sender gave up "
            f"delivering; rc={rc}) after {retries} "
            f"retr{'y' if retries == 1 else 'ies'}.",
        )

    def poll(self, src, tx):
        return bool(self._lib.smp_poll_recv(src, tx))

    def peer_down(self, peer):
        """True when the link to `peer` is marked dead in either direction
        (sender thread gave up, or the peer's incoming connection hit EOF
        while the bus was running — its process died)."""
        return bool(self._lib.smp_peer_down(peer))

    def _wait_recv(self, src, tx, timeout_ms):
        """Blocking C wait, sliced for two early exits: an armed watchdog
        turns an unbounded wait into a diagnostics dump + raise instead of
        a silent wedge, and a peer whose link the bus has marked DEAD (in
        either direction) raises ``SMPPeerLost`` immediately — a wait on a
        frame that can never arrive must not burn the full watchdog/caller
        timeout. Frames already delivered before the death still drain
        first (the probe only fires when nothing is pending)."""
        if timeout_ms == 0:
            return self._lib.smp_wait_recv(src, tx, 0)
        now = time.monotonic()
        deadline = None if timeout_ms < 0 else now + timeout_ms / 1000.0
        # The watchdog guards UNBOUNDED waits only — a caller that chose
        # an explicit timeout keeps it (and its TimeoutError), even when
        # the watchdog window is shorter.
        wd = watchdog.timeout() if timeout_ms < 0 else None
        wd_deadline = None if wd is None else now + wd
        while True:
            if (
                src != self.rank
                and not self._lib.smp_poll_recv(src, tx)
                and self.peer_down(src)
            ):
                raise SMPPeerLost(
                    src,
                    f"bus recv from process {src} (tx={tx}): the link is "
                    "marked dead (peer process unreachable or exited).",
                )
            now = time.monotonic()
            slice_ms = 1000  # peer-death probe cadence
            if deadline is not None:
                left_ms = int((deadline - now) * 1000)
                if left_ms <= 0:
                    return -1  # caller's timeout
                slice_ms = min(slice_ms, max(left_ms, 1))
            if wd_deadline is not None:
                wd_left = int((wd_deadline - now) * 1000)
                if wd_left <= 0:
                    watchdog.dump(
                        f"bus recv from process {src} (tx={tx}) stalled >{wd}s"
                    )
                    raise SMPWatchdogTimeout(
                        f"watchdog: bus recv from process {src} stalled for "
                        f"more than {wd}s (diagnostics dumped)."
                    )
                slice_ms = min(slice_ms, max(wd_left, 1))
            n = self._lib.smp_wait_recv(src, tx, slice_ms)
            if n != -1:  # -1 = slice timed out; keep waiting
                return n

    def recv_bytes(self, src, tx, timeout_ms=-1):
        # Flight-record both edges of the wait: the begin event is what a
        # post-mortem ring shows when this rank wedged INSIDE the wait
        # (the end event never arrives), the end event carries the
        # measured wait latency and outcome.
        flight_recorder.record_wait("bus_recv", src, tx, "begin", 0.0)
        t0 = time.monotonic()
        try:
            n = self._wait_recv(src, tx, timeout_ms)
        except SMPWatchdogTimeout:
            flight_recorder.record_wait(
                "bus_recv", src, tx, "watchdog", time.monotonic() - t0
            )
            raise
        except SMPPeerLost:
            flight_recorder.record_wait(
                "bus_recv", src, tx, "peer_lost", time.monotonic() - t0
            )
            raise
        elapsed = time.monotonic() - t0
        if n == -1:
            flight_recorder.record_wait("bus_recv", src, tx, "timeout", elapsed)
            raise TimeoutError(f"recv from {src} (tx={tx}) timed out")
        if n < 0:
            flight_recorder.record_wait("bus_recv", src, tx, "error", elapsed)
            raise OSError(f"smp_wait_recv failed ({n})")
        flight_recorder.record_wait("bus_recv", src, tx, "ok", elapsed)
        buf = (ctypes.c_uint8 * int(n))()
        got = self._lib.smp_retrieve_object(src, tx, buf, n)
        if got != n:
            raise OSError(f"smp_retrieve_object failed ({got})")
        return bytes(buf)

    def clean(self, src, tx):
        self._lib.smp_clean_recv_resources(src, tx)

    def send_raw(self, dest, payload, tx):
        """Single unadorned enqueue: no chaos seam, no retries, no flight
        recording. Returns the C return code (0 ok, -1 misuse, -2 link
        dead). The heartbeat (tx -4) and fleet metric snapshot (tx -7)
        paths use this — a periodic beat must not consume chaos bus-send
        ordinals or flood the flight ring, and a dead-link result is
        itself the detection signal, not an error."""
        return self._lib.smp_async_send(dest, payload, len(payload), tx)

    def drain_bytes(self, src, tx, limit=256):
        """Drain every already-delivered frame for (src, tx) without
        blocking or flight-recording. Heartbeat receive path: beats arrive
        faster than the detector scans, and each scan wants *all* pending
        beats (the freshest carries the peer's current step edge). The
        fleet aggregator (tx -7) drains the same way — the freshest
        snapshot per peer wins."""
        out = []
        while len(out) < limit and self._lib.smp_poll_recv(src, tx):
            n = self._lib.smp_wait_recv(src, tx, 0)
            if n < 0:
                break
            buf = (ctypes.c_uint8 * int(n))()
            got = self._lib.smp_retrieve_object(src, tx, buf, n)
            if got != n:
                break
            out.append(bytes(buf))
        return out

    def barrier(self, ranks, timeout_ms=600000):
        # An armed watchdog tightens the C-side timeout so a wedged peer
        # produces the dump within the configured window, not after 10 min.
        wd = watchdog.timeout()
        if wd is not None:
            timeout_ms = min(timeout_ms, max(int(wd * 1000), 1))
        arr = (ctypes.c_int * len(ranks))(*sorted(ranks))
        flight_recorder.record_wait("bus_barrier", -1, len(ranks), "begin", 0.0)
        t0 = time.monotonic()
        rc = self._lib.smp_bus_barrier(arr, len(ranks), timeout_ms)
        if rc <= -100:
            # The C side identified a member whose link is marked dead:
            # typed and immediate, not a full-timeout stall.
            peer = -(rc + 100)
            flight_recorder.record_wait(
                "bus_barrier", peer, len(ranks), "peer_lost",
                time.monotonic() - t0,
            )
            raise SMPPeerLost(
                peer,
                f"bus barrier over {sorted(ranks)}: the link to process "
                f"{peer} is marked dead (peer unreachable or exited).",
            )
        if rc != 0:
            # The C side returns -1 for timeouts AND for immediate failures
            # (bus already shut down, dead peer): only a wait that actually
            # consumed the window is a stall — instant failures keep the
            # plain OSError their callers handle.
            elapsed_ms = (time.monotonic() - t0) * 1000
            if wd is not None and elapsed_ms >= 0.9 * timeout_ms:
                flight_recorder.record_wait(
                    "bus_barrier", -1, len(ranks), "watchdog", elapsed_ms / 1e3
                )
                watchdog.dump(
                    f"bus barrier over {sorted(ranks)} stalled >{timeout_ms}ms"
                )
                raise SMPWatchdogTimeout(
                    f"watchdog: bus barrier over {sorted(ranks)} stalled "
                    f"(diagnostics dumped)."
                )
            flight_recorder.record_wait(
                "bus_barrier", -1, len(ranks), "error", elapsed_ms / 1e3
            )
            raise OSError(f"bus barrier over {sorted(ranks)} failed")
        flight_recorder.record_wait(
            "bus_barrier", -1, len(ranks), "ok", time.monotonic() - t0
        )

    def shutdown(self):
        self._lib.smp_bus_shutdown()
        self._connected = False


class NativeTimeline:
    """ctypes face of the native timeline recorder (N5)."""

    def __init__(self, lib, path):
        self._lib = lib
        self._handle = lib.smp_create_timeline(path.encode())

    def start_step(self, step):
        self._lib.smp_timeline_start_step(self._handle, step)

    def end_step(self, step):
        return self._lib.smp_timeline_end_step(self._handle, step)

    def record_event(self, name, begin_us, end_us, microbatch=None, track="pipeline"):
        self._lib.smp_timeline_record_pipeline_event(
            self._handle, name.encode(), begin_us, end_us,
            -1 if microbatch is None else microbatch, track.encode(),
        )

    def record_instant(self, name, ts_us, track="pipeline"):
        self._lib.smp_timeline_record_instant(
            self._handle, name.encode(), ts_us, track.encode()
        )

    def flush(self, pid=0):
        return self._lib.smp_timeline_flush(self._handle, pid)

    def event_count(self):
        return self._lib.smp_timeline_event_count(self._handle)

    def close(self):
        if self._handle:
            self._lib.smp_destroy_timeline(self._handle)
            self._handle = None
