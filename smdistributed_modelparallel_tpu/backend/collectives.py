"""Host-side control-plane collectives over pickled Python objects.

Parity target: reference ``backend/collectives.py:69-348``
(``CollectiveCommunicator`` / ``CommGroup`` / ``RankType``), which rides the
C++ async object P2P layer (SURVEY §2.1 N2). The TPU build's control plane
needs far less: under SPMD there is one program, so the reference's
trace-result broadcast / request routing vanish. What remains is host-level
coordination between *processes* (config agreement, partition-result
broadcast under multi-host, checkpoint rendezvous), implemented over
``jax.experimental.multihost_utils`` — pickled objects ride a uint8 device
array broadcast. Single-process runs short-circuit to local no-ops.
"""

import pickle
from enum import Enum

import numpy as np

import jax

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.exceptions import SMPRuntimeError


class CommGroup(Enum):
    """Parity: reference ``backend/collectives.py:15-58``."""

    WORLD = 0
    PP_GROUP = 1
    TP_GROUP = 2
    DP_GROUP = 3
    RDP_GROUP = 4
    MP_GROUP = 5
    CP_GROUP = 6  # TPU extension


class RankType(Enum):
    WORLD_RANK = 0
    PP_RANK = 1
    TP_RANK = 2
    DP_RANK = 3
    RDP_RANK = 4
    MP_RANK = 5


class CollectiveCommunicator:
    """Object broadcast/allgather across *host processes*.

    Note: reference collectives address per-GPU ranks; here device-level
    data movement happens inside compiled programs (psum/all_gather/...),
    and this class only coordinates host processes.
    """

    def __init__(self):
        self._tx_counter = 0

    def _multi(self):
        return jax.process_count() > 1

    def broadcast(self, obj, group=CommGroup.WORLD, src=0):
        """Broadcast a picklable object from process `src` to all processes."""
        if not self._multi():
            return obj
        from jax.experimental import multihost_utils

        payload = pickle.dumps(obj) if jax.process_index() == src else b""
        # Length-prefix exchange, then the payload as a uint8 array.
        n = multihost_utils.broadcast_one_to_all(
            np.array([len(payload)], dtype=np.int64), is_source=jax.process_index() == src
        )
        buf = np.frombuffer(payload.ljust(int(n[0]), b"\0"), dtype=np.uint8)
        out = multihost_utils.broadcast_one_to_all(
            buf, is_source=jax.process_index() == src
        )
        return pickle.loads(np.asarray(out).tobytes()[: int(n[0])])

    def allgather(self, obj, group=CommGroup.WORLD):
        """Gather a picklable object from every process; returns a list
        indexed by process_index."""
        if not self._multi():
            return [obj]
        from jax.experimental import multihost_utils

        gathered = []
        for src in range(jax.process_count()):
            gathered.append(self.broadcast(obj, group=group, src=src))
        return gathered

    def barrier(self, name="smp_ccl_barrier"):
        state.core.barrier(name)

    def send(self, obj, dest, group=CommGroup.WORLD):
        raise SMPRuntimeError(
            "Point-to-point host messaging has no SPMD counterpart; use "
            "broadcast/allgather, or lax collectives inside the compiled step."
        )

    recv_from = send
