"""Host-side control-plane collectives over pickled Python objects.

Parity target: reference ``backend/collectives.py:69-348``
(``CollectiveCommunicator`` / ``CommGroup`` / ``RankType``), which rides the
C++ async object P2P layer (SURVEY §2.1 N2). The TPU build's control plane
needs far less: under SPMD there is one program, so the reference's
trace-result broadcast / request routing vanish. What remains is host-level
coordination between *processes* (config agreement, partition-result
broadcast under multi-host, checkpoint rendezvous, user-level object
send/recv), carried two ways:

- broadcast/allgather ride ``jax.experimental.multihost_utils`` (pickled
  objects as uint8 device arrays) — always available;
- point-to-point ``send``/``recv_from`` and *subgroup* barriers ride the
  native TCP message bus (``native/src/message_bus.cc``, loaded through
  ``backend/native.py``) — the reference's N2 layer rebuilt for hosts
  without MPI. Transaction ids follow the reference's
  ``TransactionIdentifier`` convention (2*id + is_user_api,
  ``backend/collectives.py:61-66``): user sends use a per-peer-pair
  monotonic sequence so ``recv_from(src)`` is in-order, like the reference's
  user API.

Single-process runs short-circuit: broadcast/allgather are local no-ops and
P2P self-sends are delivered through the bus's local inbox.
"""

import atexit
import os
import pickle
import socket
import time
from enum import Enum

import numpy as np

import jax

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.resilience.chaos import chaos
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPCollectiveTimeout,
    SMPRuntimeError,
    SMPWatchdogTimeout,
)
from smdistributed_modelparallel_tpu.utils import profiling
from smdistributed_modelparallel_tpu.utils.flight_recorder import flight_recorder
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    record_comm,
    record_sync_mark,
    telemetry,
    watchdog,
)

logger = get_logger()

COLLECTIVE_TIMEOUT_ENV = "SMP_COLLECTIVE_TIMEOUT"


def _collective_timeout():
    """Per-operation deadline (seconds) for host-bus-backed collectives,
    or None (unbounded — the global watchdog remains the only limit).
    Read per call so tests and operators can change it mid-run. Unlike
    the watchdog, exceeding this raises a typed ``SMPCollectiveTimeout``
    carrying group + phase + the group's last flight-recorder collective
    seq — enough structure for the recovery supervisor to tell "slow"
    from "gone". Device-side collectives (full-world broadcast/allgather,
    WORLD barriers) are not host-interruptible and stay watchdog-only."""
    raw = os.environ.get(COLLECTIVE_TIMEOUT_ENV, "")
    if not raw:
        return None
    try:
        t = float(raw)
    except ValueError:
        logger.warning(
            "ignoring non-numeric %s=%r.", COLLECTIVE_TIMEOUT_ENV, raw
        )
        return None
    return t if t > 0 else None


def _payload_size(obj):
    """Approximate payload size for the comm-volume counters on the
    short-circuit paths (which never pickle). Raw buffers/arrays are sized
    cheaply — pickling a multi-GB array tree just to count bytes would cost
    seconds and 2x transient host memory; everything else (small
    control-plane objects) pays one pickle. Best-effort: an unpicklable
    object must not start failing just to be counted."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    leaves = jax.tree_util.tree_leaves(obj)
    if leaves and all(hasattr(l, "nbytes") for l in leaves):
        return int(sum(l.nbytes for l in leaves))
    try:
        return len(pickle.dumps(obj))
    except Exception:
        return 0


class CommGroup(Enum):
    """Parity: reference ``backend/collectives.py:15-58``."""

    WORLD = 0
    PP_GROUP = 1
    TP_GROUP = 2
    DP_GROUP = 3
    RDP_GROUP = 4
    MP_GROUP = 5
    CP_GROUP = 6  # TPU extension


class RankType(Enum):
    WORLD_RANK = 0
    PP_RANK = 1
    TP_RANK = 2
    DP_RANK = 3
    RDP_RANK = 4
    MP_RANK = 5


def _local_ip():
    """Best-effort routable address of this host for peer connections."""
    override = os.environ.get("SMP_BUS_HOST")
    if override:
        return override
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


class CollectiveCommunicator:
    """Object broadcast/allgather/P2P across *host processes*.

    Note: reference collectives address per-GPU ranks; here device-level
    data movement happens inside compiled programs (psum/all_gather/...),
    and this class only coordinates host processes. ``dest``/``src`` for
    P2P are therefore ranks within the *process set* of the given group.
    """

    def __init__(self):
        self._bus = None
        self._bus_failed = False
        self._send_seq = {}
        self._recv_seq = {}
        # Per-group barrier counter for the sync marks: deliberately NOT
        # the flight recorder's collective seq (which goes away when the
        # ring is disabled) — sync-mark identity across ranks must never
        # depend on an observability knob, or trace_fuse would match
        # DIFFERENT physical barriers and compute wrong clock offsets.
        self._barrier_seq = {}
        # Internal (framework) P2P streams, kept separate from the user
        # API's: internal tx ids are even (is_user_api=0), user odd.
        self._int_send_seq = {}
        self._int_recv_seq = {}

    def _multi(self):
        return jax.process_count() > 1

    # -- bus lifecycle --------------------------------------------------

    def initialize_bus(self):
        """Bring the native message bus up. Multi-process endpoint exchange
        is a GLOBAL collective, so this must run at ``smp.init`` time (every
        process participates there); bringing it up lazily from a subgroup
        operation would deadlock the processes that never touch the bus.
        Single-process bring-up involves no collective and stays lazy.
        Returns the bus, or None when the native library is unavailable."""
        if self._bus is not None:
            return self._bus
        if self._bus_failed:
            return None
        from smdistributed_modelparallel_tpu.backend import native

        lib = native.load()
        world = jax.process_count()
        # Local bring-up first (library load + listener bind), then ONE
        # collective endpoint exchange that every process enters no matter
        # what happened locally — heterogeneous failures (missing .so, bind
        # error) must disable the bus consistently everywhere rather than
        # strand the healthy processes inside the collective.
        bus, endpoint = None, None
        if lib is not None:
            bus = native.MessageBus(lib)
            try:
                port = bus.listen(0)
                endpoint = f"{_local_ip()}:{port}"
            except OSError as e:
                logger.warning("native bus listener failed: %s", e)
                bus.shutdown()
                bus = None
        if world == 1:
            if bus is None:
                self._bus_failed = True
                return None
            bus.connect(0, 1, [endpoint])
        else:
            # Two-collective object allgather; None (local bring-up failed)
            # travels as a pickled value, keeping the exchange all-or-nothing.
            endpoints = self.allgather(endpoint)
            if any(e is None for e in endpoints):
                if bus is not None:
                    bus.shutdown()
                logger.warning(
                    "native message bus disabled: unavailable on at least "
                    "one peer process."
                )
                self._bus_failed = True
                return None
            # The gathered list is identical on every process, so a connect
            # failure (malformed endpoint) is deterministic — raise rather
            # than leave processes in divergent states.
            bus.connect(jax.process_index(), world, endpoints)
        self._bus = bus
        if world == 1:
            # Multi-process teardown is owned by core.shutdown (which must
            # relay the exit status over the bus FIRST — an atexit handler
            # here would run before core's in LIFO order and close the bus
            # under it). Single-process runs have no relay; close at exit.
            atexit.register(self.shutdown)
        logger.debug("native message bus up at %s", endpoint)
        return bus

    def _get_bus(self, required_by):
        if self._bus is not None:
            return self._bus
        if jax.process_count() == 1 and not self._bus_failed:
            bus = self.initialize_bus()
            if bus is not None:
                return bus
        raise SMPRuntimeError(
            f"{required_by} needs the native message bus "
            "(native/libsmptpu.so), which is not up — it failed to build/"
            "load, or smp.init ran before the library was available; build "
            "it with `make -C native` and unset SMP_DISABLE_NATIVE."
        )

    def shutdown(self):
        if self._bus is not None:
            self._bus.shutdown()
            self._bus = None

    # -- group -> process-set resolution --------------------------------

    def group_processes(self, group=CommGroup.WORLD):
        """Process indices participating in `group`, for this process's
        default device. WORLD (and single-process runs) -> all processes."""
        world = list(range(jax.process_count()))
        if group in (None, CommGroup.WORLD) or not self._multi():
            return world
        if not state.initialized:
            # Without topology, subgroup membership is unknowable; widening
            # to WORLD would deadlock the members (non-members never join
            # the collective) — refuse instead.
            raise SMPRuntimeError(
                f"collective over {group} requires smp.init first "
                "(group membership comes from the device topology)."
            )
        core = state.core
        getter = {
            CommGroup.PP_GROUP: core.get_pp_group,
            CommGroup.TP_GROUP: core.get_tp_group,
            CommGroup.DP_GROUP: core.get_dp_group,
            CommGroup.RDP_GROUP: core.get_rdp_group,
            CommGroup.MP_GROUP: core.get_mp_group,
            CommGroup.CP_GROUP: core.get_cp_group,
        }.get(group)
        if getter is None:
            return world
        devices = list(core.topology.mesh.devices.flat)
        procs = sorted({devices[d].process_index for d in getter()})
        return procs or world

    # -- collectives ----------------------------------------------------
    # `src` is group-relative throughout (for WORLD the group list is the
    # identity, so it coincides with the process index) — consistent with
    # send/recv_from's peer addressing.

    def broadcast(self, obj, group=CommGroup.WORLD, src=0):
        """Broadcast a picklable object from member `src` of `group` to the
        group's processes. Full-world broadcasts ride multihost_utils;
        proper subgroups ride the native bus (only members may call)."""
        chaos.on_collective("broadcast", getattr(group, "name", group))
        if not self._multi():
            record_comm("broadcast", group, _payload_size(obj), 1)
            return obj
        procs = self.group_processes(group)
        if len(procs) < jax.process_count():
            out, nbytes = self._subgroup_broadcast(obj, procs, src, group)
            record_comm("broadcast", group, nbytes, len(procs))
            return out
        from jax.experimental import multihost_utils

        payload = pickle.dumps(obj) if jax.process_index() == src else b""
        # Begin-edge into the flight recorder BEFORE the blocking device
        # collective (record_comm below fires only on completion): a rank
        # wedged inside the broadcast must leave this as its ring's last
        # word, same as the native bus waits do.
        flight_recorder.record_wait("broadcast", -1, 0, "begin", 0.0)
        with watchdog.guard(f"broadcast/{getattr(group, 'name', group)}"), \
                profiling.region("collective/broadcast", track="host"):
            # Length-prefix exchange, then the payload as a uint8 array.
            n = multihost_utils.broadcast_one_to_all(
                np.array([len(payload)], dtype=np.int64), is_source=jax.process_index() == src
            )
            buf = np.frombuffer(payload.ljust(int(n[0]), b"\0"), dtype=np.uint8)
            out = multihost_utils.broadcast_one_to_all(
                buf, is_source=jax.process_index() == src
            )
        record_comm("broadcast", group, int(n[0]), len(procs))
        # astype: psum-based broadcast_one_to_all widens uint8 to uint32
        # under the gloo CPU collectives (values preserved) — tobytes() on
        # the widened array would interleave three zero bytes per real one.
        return pickle.loads(
            np.asarray(out).astype(np.uint8, copy=False).tobytes()[: int(n[0])]
        )

    def allgather(self, obj, group=CommGroup.WORLD):
        """Gather a picklable object from every process of `group`; returns
        a list indexed by group-relative rank (process_index for WORLD).

        Full-world gathers are TWO collectives (max-length exchange, then
        one padded uint8 process_allgather) — not P sequential broadcasts.
        """
        chaos.on_collective("allgather", getattr(group, "name", group))
        if not self._multi():
            record_comm("allgather", group, _payload_size(obj), 1)
            return [obj]
        procs = self.group_processes(group)
        if len(procs) < jax.process_count():
            out, nbytes = self._subgroup_allgather(obj, procs, group)
            record_comm("allgather", group, nbytes, len(procs))
            return out
        from jax.experimental import multihost_utils

        payload = pickle.dumps(obj)
        # Begin-edge before the blocking collective; see broadcast.
        flight_recorder.record_wait("allgather", -1, 0, "begin", 0.0)
        with watchdog.guard(f"allgather/{getattr(group, 'name', group)}"), \
                profiling.region("collective/allgather", track="host"):
            lens = np.asarray(
                multihost_utils.process_allgather(
                    np.asarray([len(payload)], np.int64)
                )
            ).reshape(-1)
            row = np.zeros(int(lens.max()), np.uint8)
            row[: len(payload)] = np.frombuffer(payload, np.uint8)
            rows = np.asarray(multihost_utils.process_allgather(row))
        record_comm("allgather", group, int(lens.sum()), len(procs))
        return [
            pickle.loads(bytes(rows[i])[: int(lens[i])])
            for i in range(jax.process_count())
        ]

    def _subgroup_broadcast(self, obj, procs, src, group):
        me = jax.process_index()
        if me not in procs:
            raise SMPRuntimeError(
                f"broadcast over {group} called from process {me}, which is "
                "not a member of that group."
            )
        if src < 0 or src >= len(procs):
            raise SMPRuntimeError(
                f"broadcast src {src} out of range for group {group} "
                f"({len(procs)} processes)."
            )
        root = procs[src]
        if me == root:
            # Pickle ONCE for both the per-peer sends and the byte counter.
            payload = pickle.dumps(obj)
            for p in procs:
                if p != me:
                    self._int_send_bytes(p, payload)
            return obj, len(payload)
        return self._int_recv(root, group=group, phase="broadcast")

    def _subgroup_allgather(self, obj, procs, group):
        me = jax.process_index()
        if me not in procs:
            raise SMPRuntimeError(
                f"allgather over {group} called from process {me}, which is "
                "not a member of that group."
            )
        root = procs[0]
        if me == root:
            gathered, nbytes = [], 0
            for p in procs:
                if p == me:
                    gathered.append(obj)
                else:
                    o, n = self._int_recv(p, group=group, phase="allgather")
                    gathered.append(o)
                    nbytes += n
            payload = pickle.dumps(gathered)
            for p in procs:
                if p != me:
                    self._int_send_bytes(p, payload)
            return gathered, nbytes + len(payload)
        self._int_send(root, obj)
        return self._int_recv(root, group=group, phase="allgather")

    # _int_send/_int_recv return the wire payload size so the comm-volume
    # counters ride the serialization the bus already pays for (no
    # re-pickling just to count bytes).

    def _int_send(self, gdest, obj):
        return self._int_send_bytes(gdest, pickle.dumps(obj))

    def _int_send_bytes(self, gdest, payload):
        bus = self._get_bus("framework collective")
        seq = self._int_send_seq.get(gdest, 0)
        bus.send_bytes(gdest, payload, 2 * seq)
        self._int_send_seq[gdest] = seq + 1
        return len(payload)

    def _int_recv(self, gsrc, timeout_ms=-1, group=None, phase="recv"):
        bus = self._get_bus("framework collective")
        seq = self._int_recv_seq.get(gsrc, 0)
        ct = _collective_timeout()
        if timeout_ms < 0 and ct is not None:
            timeout_ms = max(int(ct * 1000), 1)
        try:
            payload = bus.recv_bytes(gsrc, 2 * seq, timeout_ms)
        except TimeoutError:
            # Typed deadline (SMP_COLLECTIVE_TIMEOUT): the supervisor can
            # treat it as "peer slow/stuck at THIS coordinate" rather
            # than the watchdog's undifferentiated stall.
            g = getattr(group, "name", None) or str(group)
            raise SMPCollectiveTimeout(
                g, phase, flight_recorder.last_seq(g)
            ) from None
        self._int_recv_seq[gsrc] = seq + 1
        return pickle.loads(payload), len(payload)

    def barrier(self, name="smp_ccl_barrier", group=CommGroup.WORLD):
        """Barrier over the processes of `group`. WORLD barriers are a
        global device sync; proper subgroups require the native bus — a
        global sync is NOT a safe substitute there (it waits on non-member
        processes that may never call barrier, deadlocking the members), so
        subgroup barriers raise when the bus is down rather than silently
        widening."""
        procs = self.group_processes(group)
        gname = getattr(group, "name", None) or str(group)
        chaos.on_collective("barrier", gname)
        record_comm("barrier", group, 0, len(procs))
        seq = self._barrier_seq.get(gname, 0)
        self._barrier_seq[gname] = seq + 1
        if len(procs) > 1:
            with profiling.region(f"collective/barrier/{gname}", track="host"):
                if len(procs) < jax.process_count():
                    ct = _collective_timeout()
                    t0 = time.monotonic()
                    with watchdog.guard(f"barrier/{gname}"):
                        try:
                            if ct is None:
                                self._get_bus(
                                    f"smp.barrier({group})"
                                ).barrier(procs)
                            else:
                                self._get_bus(
                                    f"smp.barrier({group})"
                                ).barrier(
                                    procs,
                                    timeout_ms=max(int(ct * 1000), 1),
                                )
                        except (OSError, SMPWatchdogTimeout) as e:
                            # Only a wait that consumed the configured
                            # deadline is a typed collective timeout;
                            # instant failures (bus down) stay OSError.
                            # An armed watchdog tightens the bus-level
                            # timeout and raises its OWN type first —
                            # when the ct deadline is what elapsed, the
                            # typed SMPCollectiveTimeout wins (the dump,
                            # if any, already happened).
                            if (
                                ct is not None
                                and time.monotonic() - t0 >= 0.9 * ct
                            ):
                                raise SMPCollectiveTimeout(
                                    gname, "barrier",
                                    flight_recorder.last_seq(gname),
                                ) from e
                            raise
                else:
                    state.core.barrier(name)
        # Sync mark AFTER the barrier: every member leaves it within
        # network jitter of the others, so this rank's wall clock at this
        # point is the cross-rank alignment signal trace_fuse uses to
        # correct per-rank clock offsets (and the skew gauges measure).
        # `seq` is this group's barrier ordinal — identical on every
        # member that executes the same barrier sequence, which is what
        # lets trace_fuse match the SAME physical barrier across ranks.
        self._record_sync(name, gname, seq)

    def _record_sync(self, name, gname, seq):
        record_sync_mark(name, gname, seq)
        tl = state.timeline
        if tl is not None and tl.enabled:
            tl.sync_mark(name, gname, seq)

    # -- point-to-point (native bus; reference N2 user API) -------------

    def send(self, obj, dest, group=CommGroup.WORLD):
        """Async-send a picklable object to process `dest` of `group`.

        Parity: reference ``CollectiveCommunicator.send``
        (``backend/collectives.py:233-260``) — returns immediately; delivery
        is handled by the bus's sender thread.
        """
        gdest = self._resolve_peer(dest, group, "send dest")
        bus = self._get_bus("smp.send")
        seq = self._send_seq.get(gdest, 0)
        # TransactionIdentifier parity: 2*seq + is_user_api(=1). The counter
        # advances only after a successful enqueue so a failed send can be
        # retried without desynchronizing the per-peer stream.
        payload = pickle.dumps(obj)
        bus.send_bytes(gdest, payload, 2 * seq + 1)
        self._send_seq[gdest] = seq + 1
        record_comm("send", group, len(payload), 2)

    def recv_from(self, src, group=CommGroup.WORLD, timeout_ms=-1):
        """Receive the next in-order object sent by process `src` of `group`."""
        gsrc = self._resolve_peer(src, group, "recv_from src")
        bus = self._get_bus("smp.recv_from")
        seq = self._recv_seq.get(gsrc, 0)
        telemetry.set_phase(f"recv_from/{gsrc}")
        payload = bus.recv_bytes(gsrc, 2 * seq + 1, timeout_ms)
        self._recv_seq[gsrc] = seq + 1
        record_comm("recv_from", group, len(payload), 2)
        return pickle.loads(payload)

    def poll(self, src, group=CommGroup.WORLD):
        """True when the next in-order object from `src` has arrived."""
        gsrc = self._resolve_peer(src, group, "poll src")
        bus = self._get_bus("smp.poll")
        return bus.poll(gsrc, 2 * self._recv_seq.get(gsrc, 0) + 1)

    def _resolve_peer(self, idx, group, what):
        procs = self.group_processes(group)
        if idx < 0 or idx >= len(procs):
            raise SMPRuntimeError(
                f"{what} {idx} out of range for group {group} "
                f"({len(procs)} processes)."
            )
        return procs[idx]
