"""Device topology: placement strategy -> jax.sharding.Mesh.

TPU-native replacement for the reference's process-group construction
(``torch/state_mod.py:83-166`` creates torch.distributed groups for
dp/mp/pp/tp/rdp; ``backend/core.py:286`` registers pp groups with the C++
backend). Here the whole topology is one ``jax.sharding.Mesh`` whose axis
order is the placement permutation, so XLA lays collectives for the
fastest-varying axis onto neighboring devices (ICI) exactly as the
reference lays them onto neighboring GPUs.

Mesh axes: the "D" letter of the placement string expands into the
sub-axes ("rdp", "ep", "cp") — expert and context parallelism are carved
out of the data-parallel dimension (TPU extensions; reference has only
pp/tp/rdp). With ep == cp == 1 these are degenerate size-1 axes and the
mesh is exactly the reference 3-axis topology.

Axis name constants are the single source of truth for PartitionSpecs
throughout the framework.
"""

import numpy as np

import jax
from jax.sharding import Mesh

from smdistributed_modelparallel_tpu.backend.ranker import Ranker, normalize_placement
from smdistributed_modelparallel_tpu.utils.exceptions import DeviceCountError

# Canonical mesh axis names.
PP_AXIS = "pp"
TP_AXIS = "tp"
RDP_AXIS = "rdp"
EP_AXIS = "ep"
CP_AXIS = "cp"

# Axes across which a (non-prescaled) batch is sharded: every rank that holds
# a distinct slice of data. Matches the reference's dp = tp x rdp composite
# (``backend/core.py:49-55``) plus the TPU-only ep/cp sub-axes.
DATA_AXES = (RDP_AXIS, EP_AXIS, CP_AXIS)


def _letter_axes(letter):
    if letter == "P":
        return [PP_AXIS]
    if letter == "T":
        return [TP_AXIS]
    return [RDP_AXIS, EP_AXIS, CP_AXIS]


class DeviceTopology:
    """Owns the Ranker, the device mesh, and degree bookkeeping."""

    def __init__(self, cfg, devices=None):
        self.cfg = cfg
        if devices is None:
            devices = jax.devices()
        n = cfg._device_count_override or len(devices)
        self.pp_size = cfg.pipeline_parallel_degree
        self.tp_size = cfg.tensor_parallel_degree
        self.cp_size = cfg.context_parallel_degree
        self.ep_size = cfg.expert_parallel_degree
        model_degree = self.pp_size * self.tp_size * self.cp_size * self.ep_size
        if n % model_degree != 0:
            raise DeviceCountError(model_degree, n)
        self.rdp_size = n // model_degree
        self.size = n
        # Reference "D" dimension = everything that is not pp/tp.
        self.d_size = self.rdp_size * self.cp_size * self.ep_size
        self.dp_size = self.tp_size * self.d_size

        self.placement = normalize_placement(cfg.placement_strategy)
        self.ranker = Ranker(self.placement, self.d_size, self.pp_size, self.tp_size)

        axis_names, axis_sizes = [], []
        for letter in self.placement:
            for ax in _letter_axes(letter):
                axis_names.append(ax)
                axis_sizes.append(getattr(self, f"{ax}_size"))
        self.axis_names = tuple(axis_names)
        self.axis_sizes = tuple(axis_sizes)

        device_grid = np.asarray(devices[:n], dtype=object).reshape(axis_sizes)
        self.mesh = Mesh(device_grid, self.axis_names)

    # -- sub-axis coordinates for a global rank -------------------------

    def coords(self, rank):
        """Dict of mesh-axis name -> coordinate for a global rank index."""
        out = {}
        rem = rank
        # Unravel in placement (mesh) order: later axes vary fastest.
        for name, size in zip(reversed(self.axis_names), reversed(self.axis_sizes)):
            out[name] = rem % size
            rem //= size
        return out

    def cp_rank(self, rank):
        return self.coords(rank)[CP_AXIS]

    def ep_rank(self, rank):
        return self.coords(rank)[EP_AXIS]

    def axis_group(self, rank, axis):
        """Flat device indices of the devices sharing `rank`'s coordinates
        on every mesh axis except `axis` (i.e. `rank`'s group along that
        axis), in axis order."""
        mine = self.coords(rank)
        group = []
        for r in range(self.size):
            c = self.coords(r)
            if all(c[a] == mine[a] for a in self.axis_names if a != axis):
                group.append(r)
        return group

    def __repr__(self):
        dims = "x".join(
            f"{n}={s}" for n, s in zip(self.axis_names, self.axis_sizes)
        )
        return f"DeviceTopology({dims}, placement={self.placement})"
