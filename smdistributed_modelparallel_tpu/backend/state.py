"""Global framework state singleton.

Parity target: reference ``backend/state_mod.py:14-93`` (``ModelParallelState``)
and the PyTorch-side ``torch/state_mod.py:31-418`` (``PTModelParallelState``).
Under the SPMD design most of the reference's state (link-id maps, worker
bookkeeping, serialization managers) disappears; what remains is the config,
the core/topology, the current model/optimizer registrations, the module
manager, the tp registry, and RNG management.
"""

from smdistributed_modelparallel_tpu.backend.core import ModelParallelCore
from smdistributed_modelparallel_tpu.utils.exceptions import NotInitializedError


class ModelParallelState:
    def __init__(self):
        self.cfg = None
        self.core = ModelParallelCore()
        self.model = None           # current smp.DistributedModel
        self.optimizer = None       # current smp.DistributedOptimizer
        self.module_manager = None  # set by model.py on DistributedModel creation
        self.tp_registry = None     # lazily created TensorParallelismRegistry
        self.rng_manager = None
        self.loss_scaler = None     # DynamicLossScaler when cfg.fp16
        self.quant_state = None     # quant.QuantState when matmul_precision fp8
        self.timeline = None        # Timeline (SMP_TIMELINE_PATH)
        self.memory_metrics = None  # StepMemoryMetricsCollector
        self.step_count = 0
        self.step_rng = None        # device-carried RNG key advanced by the step program
        self.loaded_model_state = None      # deferred checkpoint payloads
        self.loaded_optimizer_state = None
        self.last_compile_report = None     # one_time_compile_report output
        self._comm = None                   # lazy CollectiveCommunicator
        # Bumped on every (re-)initialize: compiled-step cache keys include
        # it, so a program compiled under an old cfg/mesh can never serve a
        # re-initialized topology (the key's shapes/flags may collide).
        self.generation = 0

    @property
    def comm(self):
        """Host control-plane communicator (parity: reference
        ``state.comm``, ``backend/state_mod.py:14-93``). Lazy: collectives
        imports this module, so construction defers to first use."""
        if self._comm is None:
            from smdistributed_modelparallel_tpu.backend.collectives import (
                CollectiveCommunicator,
            )

            self._comm = CollectiveCommunicator()
        return self._comm

    @property
    def initialized(self):
        return self.core.initialized

    def initialize(self, cfg, devices=None):
        self.cfg = cfg
        self.generation += 1
        self.core.initialize(cfg, devices=devices)
        from smdistributed_modelparallel_tpu.utils.random import RngManager

        self.rng_manager = RngManager(cfg.tensor_parallel_seed)
        from smdistributed_modelparallel_tpu.nn.tp_registry import TensorParallelismRegistry

        if self.tp_registry is None:
            self.tp_registry = TensorParallelismRegistry()
        from smdistributed_modelparallel_tpu.nn.auto_distribute import (
            install_construction_hooks,
            register_builtins,
        )

        register_builtins(self.tp_registry)
        install_construction_hooks()
        from smdistributed_modelparallel_tpu.nn.huggingface import (
            register_predefined_hooks,
        )

        register_predefined_hooks(self.tp_registry)
        if cfg.fp16:
            from smdistributed_modelparallel_tpu.fp16.loss_scaler import (
                DynamicLossScaler,
            )

            self.loss_scaler = DynamicLossScaler()
        else:
            self.loss_scaler = None
        from smdistributed_modelparallel_tpu import quant

        if quant.matmul_precision_mode(cfg) == "fp8":
            # Delayed-scaling amax/scale state, threaded through the
            # step like the loss scaler and checkpointed beside it
            # (quant_states.pt).
            self.quant_state = quant.QuantState()
        else:
            self.quant_state = None
        from smdistributed_modelparallel_tpu.utils.metrics import (
            StepMemoryMetricsCollector,
        )
        from smdistributed_modelparallel_tpu.utils.timeline import Timeline

        self.timeline = Timeline()
        self.memory_metrics = StepMemoryMetricsCollector()
        import jax

        if jax.process_count() > 1:
            # Multi-process bus bring-up is a global collective (endpoint
            # allgather) and so must happen HERE, where every process is
            # known to participate — not lazily from a subgroup op.
            self.comm.initialize_bus()
        from smdistributed_modelparallel_tpu.resilience.preemption import (
            preemption,
        )

        preemption.install()
        from smdistributed_modelparallel_tpu.resilience.supervisor import (
            supervisor,
        )

        # Arm the heartbeat failure detector (SMP_SUPERVISOR=on, multi-
        # process, bus up); re-arms on a recovery's re-initialize. Off is
        # a hard no-op: no thread, no bus traffic, step path untouched.
        supervisor.start()
        from smdistributed_modelparallel_tpu.utils.fleet import fleet

        # Fleet metrics plane (SMP_FLEET_INTERVAL): needs the bus AND
        # the supervisor's liveness verdicts, so it arms after both.
        # Unset/0 constructs nothing — no thread, no traffic, no port.
        fleet.start()
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        # Wall-clock attribution ledger (SMP_GOODPUT and friends): chains
        # onto the set_phase listener, so it arms after telemetry exists.
        # Idempotent — a recovery's re-initialize keeps the same ledger.
        goodput.start()
        from smdistributed_modelparallel_tpu.utils import profiling

        # SIGUSR2 arms a one-step profiler capture on a live run
        # (utils/profiling.py); the SMP_PROFILE window is read lazily at
        # the first step edge.
        profiling.capture.install_signal()

    def _check(self):
        if not self.initialized:
            raise NotInitializedError()

    @property
    def mesh(self):
        self._check()
        return self.core.mesh

    @property
    def topology(self):
        self._check()
        return self.core.topology

    def reset(self):
        """Testing hook: drop model/optimizer registrations and counters."""
        from smdistributed_modelparallel_tpu.utils import health
        from smdistributed_modelparallel_tpu.utils.flight_recorder import (
            flight_recorder,
        )
        from smdistributed_modelparallel_tpu.utils.telemetry import telemetry

        from smdistributed_modelparallel_tpu.utils.fleet import fleet
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        from smdistributed_modelparallel_tpu.serving import (
            controller as serving_controller,
        )

        serving_controller.reset_all()
        goodput.reset()
        fleet.reset()
        telemetry.reset()
        flight_recorder.clear()
        health.reset()
        from smdistributed_modelparallel_tpu.utils import profiling

        profiling.capture.reset()
        from smdistributed_modelparallel_tpu.resilience import (
            reset as resilience_reset,
        )

        resilience_reset()
        if self._comm is not None:
            # Barrier ordinals restart with the session, like the metric
            # counters (a re-init resets them on every rank uniformly).
            self._comm._barrier_seq.clear()
        self.model = None
        self.optimizer = None
        self.module_manager = None
        self.step_count = 0
        self.step_rng = None
        self.loaded_model_state = None
        self.loaded_optimizer_state = None
        self.last_compile_report = None


state = ModelParallelState()
