"""Process/topology core.

Parity target: reference ``backend/core.py:191-562`` (``ModelParallelCore``).
The reference wraps a C++ MPI/NCCL backend (ctypes ``smp_init`` etc., SURVEY
§2.1 N1); on TPU the same responsibilities map to:

- bootstrap: ``jax.distributed.initialize`` (multi-host) — no MPI;
- rank/group queries: pure ``Ranker`` arithmetic over device indices
  (reference ranks are 1:1 with GPUs; here 1:1 with TPU chips);
- barrier: ``multihost_utils.sync_global_devices``;
- timeline: see ``utils/timeline.py`` (host-side Perfetto trace, replacing
  the C++ ``smp_create_timeline`` family, SURVEY §2.1 N5).

One deliberate semantic difference: the reference runs one process per GPU,
so ``rank()`` is both a process and a device id. A JAX process drives many
local TPU chips; device-level queries (pp_rank/tp_rank/...) answer for a
given device index (default: this process's first addressable device), while
``process_index()`` exposes the host-level id for checkpoint coordination.
"""

import atexit
import os

import jax

from smdistributed_modelparallel_tpu.backend.topology import DeviceTopology
from smdistributed_modelparallel_tpu.utils.exceptions import (
    NotInitializedError,
    SMPValidationError,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import telemetry, watchdog

logger = get_logger()


class ModelParallelCore:
    def __init__(self):
        self.cfg = None
        self.topology = None
        self._initialized = False
        self.exit_hook = None

    # -- lifecycle ------------------------------------------------------

    def initialize(self, cfg, devices=None):
        if self._initialized:
            logger.warning("smp core already initialized; re-initializing topology.")
        self.cfg = cfg
        telemetry.set_phase("init/distributed")
        self._maybe_init_distributed()
        # The first device enumeration is the probe that wedges when the
        # accelerator transport is down (BENCH_r05): guard it so an armed
        # watchdog dumps instead of hanging smp.init silently.
        telemetry.set_phase("init/topology")
        with watchdog.guard("init/topology"):
            # Rank identity first (inside the guard: process_index() itself
            # touches the backend and can wedge), so a topology stall dumps
            # rank-suffixed files instead of N ranks clobbering one path.
            telemetry.process_index = jax.process_index()
            telemetry.process_count = jax.process_count()
            self.topology = DeviceTopology(cfg, devices=devices)
        telemetry.set_phase("initialized")
        self._initialized = True
        self.attach_exit_hook()
        atexit.register(self.shutdown)
        logger.info("Initialized %r over %d device(s), %d process(es).",
                    self.topology, self.topology.size, jax.process_count())

    def attach_exit_hook(self):
        """Parity: reference ``attach_exit_hook`` (``backend/core.py:204``)."""
        if self.exit_hook is None:
            from smdistributed_modelparallel_tpu.utils.exit_hook import ExitHook

            self.exit_hook = ExitHook()
        self.exit_hook.hook()

    def exit_status(self):
        """True when this process is shutting down cleanly."""
        return self.exit_hook.success if self.exit_hook is not None else True

    def _maybe_init_distributed(self):
        """Multi-host bootstrap. Under SageMaker/launcher envs with a
        coordinator address set, bring up the JAX distributed runtime."""
        coord = os.environ.get("SMP_COORDINATOR_ADDRESS") or os.environ.get(
            "JAX_COORDINATOR_ADDRESS"
        )
        if coord and jax.process_count() == 1 and not self._initialized:
            try:
                jax.distributed.initialize()
            except Exception as e:  # already initialized or single-host
                logger.debug("jax.distributed.initialize skipped: %s", e)

    def shutdown(self):
        """Parity: reference ``shutdown`` (``backend/core.py:226-231``) —
        derive the consistent exit status from the exit hook and relay it
        (reference: ``smp_shutdown(success)``; here: best-effort status
        report to process 0 over the bus, which logs failing peers)."""
        if not self._initialized:
            return
        self._initialized = False
        # The fleet metrics plane stops FIRST: its final snapshot/window
        # flush needs the bus, which the exit-status relay below closes,
        # and its scrape server must be gone before the telemetry dump
        # becomes this process's record.
        from smdistributed_modelparallel_tpu.utils.fleet import fleet
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        # The serving controller closes its open scale events before the
        # fleet plane (its window source) goes away.
        try:
            from smdistributed_modelparallel_tpu.serving import (
                controller as serving_controller,
            )

            serving_controller.shutdown_all()
        except Exception as e:
            logger.warning("serving controller stop failed: %s", e)
        # Goodput ledger flushes BEFORE the fleet plane stops so the final
        # second-counters make the fleet's last aggregated window.
        try:
            goodput.stop()
        except Exception as e:
            logger.warning("goodput ledger stop failed: %s", e)
        try:
            fleet.stop()
        except Exception as e:
            logger.warning("fleet metrics plane stop failed: %s", e)
        success = self.exit_status()
        if not success:
            logger.error(
                "process %d shutting down after failure (exit_code=%r, "
                "exception=%r)", jax.process_index(),
                self.exit_hook.exit_code, self.exit_hook.exception,
            )
        self._relay_exit_status(success)
        # Drain pending async checkpoint saves BEFORE the shutdown dumps:
        # the dumps below are the post-mortem record of this process, and
        # on a crash-exit they must not race (or misrepresent) a
        # half-written checkpoint — once they run, every submitted save has
        # either committed or surfaced its error here.
        from smdistributed_modelparallel_tpu.checkpoint import (
            wait_for_checkpoints,
        )

        try:
            wait_for_checkpoints()
        except Exception as e:
            logger.error(
                "pending async checkpoint save failed during shutdown: %s", e
            )
        # The session timeline (state.timeline, fed by the step engine and
        # the barrier sync marks) flushes here: events recorded after the
        # last step's flush — the final barrier's sync mark above all —
        # must reach the file or trace_fuse loses its alignment signal.
        from smdistributed_modelparallel_tpu.backend.state import state

        # A profiler capture still open at shutdown (run ended inside its
        # window) is closed here so the trace file is usable.
        from smdistributed_modelparallel_tpu.utils import profiling

        profiling.capture.stop_if_active()
        if state.timeline is not None:
            state.timeline.flush()
        telemetry.set_phase("shutdown")
        telemetry.dump()  # no-op unless SMP_TELEMETRY_PATH is set
        from smdistributed_modelparallel_tpu.utils.flight_recorder import (
            flight_recorder,
        )

        flight_recorder.dump()  # no-op unless SMP_FLIGHT_RECORDER_PATH is set

    def _relay_exit_status(self, success):
        """Tell process 0 how this process ended; process 0 polls for peer
        reports against ONE shared deadline and logs failures. Best-effort:
        peers may already be gone at exit, so never block shutdown on this.
        Runs before the bus closes (this method owns closing it — atexit
        LIFO would otherwise tear the bus down under the relay)."""
        if jax.process_count() <= 1:
            return
        from smdistributed_modelparallel_tpu.backend.state import state

        comm = state._comm
        bus = comm._bus if comm is not None else None
        if bus is None:
            return
        try:
            import time

            # Reserved status tx: negative namespace distinct from barriers
            # (barrier ids are even*; -1 is never produced there).
            me = jax.process_index()
            if me != 0:
                bus.send_bytes(0, b"\x01" if success else b"\x00", -1)
            else:
                failed = [] if success else [0]
                pending = set(range(1, jax.process_count()))
                deadline = time.monotonic() + 2.0
                while pending and time.monotonic() < deadline:
                    for peer in list(pending):
                        if bus.poll(peer, -1):
                            if bus.recv_bytes(peer, -1, timeout_ms=0) == b"\x00":
                                failed.append(peer)
                            pending.discard(peer)
                    if pending:
                        time.sleep(0.01)
                if failed:
                    logger.error(
                        "shutdown status: process(es) %s reported failure.",
                        sorted(failed),
                    )
        except Exception:  # pragma: no cover - never block exit
            pass
        finally:
            comm.shutdown()

    @property
    def initialized(self):
        return self._initialized

    def _check(self):
        if not self._initialized:
            raise NotInitializedError("smp core")

    # -- process-level --------------------------------------------------

    def process_index(self):
        return jax.process_index()

    def process_count(self):
        return jax.process_count()

    def barrier(self, name="smp_barrier"):
        self._check()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            # A global device sync is not interruptible from Python; the
            # guard's timer thread dumps diagnostics if it stalls, and the
            # sync itself keeps waiting (see utils/telemetry.py).
            telemetry.set_phase(f"barrier/{name}")
            with watchdog.guard(f"barrier/{name}"):
                multihost_utils.sync_global_devices(name)

    # -- device-level rank queries (reference API parity) ---------------

    def _default_rank(self):
        """Device index answering rank queries: first local addressable device."""
        self._check()
        local = self.topology.mesh.local_devices
        if local:
            flat = list(self.topology.mesh.devices.flat)
            return flat.index(local[0])
        return 0

    def rank(self, device_index=None):
        self._check()
        return self._default_rank() if device_index is None else device_index

    def size(self):
        self._check()
        return self.topology.size

    def local_rank(self):
        self._check()
        return 0

    def local_size(self):
        return jax.local_device_count()

    def _flat_devices(self):
        """Cached rank -> device list (per topology: large pods shouldn't
        rebuild an O(devices) list per instance query)."""
        cached = getattr(self, "_flat_devices_cache", None)
        if cached is None or cached[0] is not self.topology:
            cached = (self.topology, list(self.topology.mesh.devices.flat))
            self._flat_devices_cache = cached
        return cached[1]

    def instance_id(self, rank=None):
        """Host id of the given device rank (default: this process's
        rank). Ranks index ``mesh.devices.flat``; each device belongs to
        exactly one jax process, and a process is host-bound — so the
        reference's "instance" (machine) maps to ``device.process_index``
        on a TPU pod. Parity: reference ``backend/core.py:486-489``."""
        self._check()
        r = self._default_rank() if rank is None else rank
        flat = self._flat_devices()
        if not 0 <= r < len(flat):
            raise SMPValidationError(
                f"rank {r} out of range [0, {len(flat)})."
            )
        return flat[r].process_index

    def is_in_same_instance(self, rank):
        """Whether device ``rank`` lives on the same host as this
        process. Parity: reference ``backend/core.py:479-481``."""
        return self.instance_id(rank) == self.instance_id()

    def is_multi_node(self):
        """Parity: reference ``backend/core.py:483-485``."""
        self._check()
        return jax.process_count() > 1

    def pp_rank(self, device_index=None):
        return self.topology.ranker.get_pp_rank(self.rank(device_index))

    def tp_rank(self, device_index=None):
        return self.topology.ranker.get_tp_rank(self.rank(device_index))

    def rdp_rank(self, device_index=None):
        return self.topology.ranker.get_rdp_rank(self.rank(device_index))

    def dp_rank(self, device_index=None):
        return self.topology.ranker.get_dp_rank(self.rank(device_index))

    def mp_rank(self, device_index=None):
        return self.topology.ranker.get_mp_rank(self.rank(device_index))

    def cp_rank(self, device_index=None):
        return self.topology.cp_rank(self.rank(device_index))

    def pp_size(self):
        self._check()
        return self.topology.pp_size

    def tp_size(self):
        self._check()
        return self.topology.tp_size

    def rdp_size(self):
        self._check()
        return self.topology.d_size

    def dp_size(self):
        self._check()
        return self.topology.dp_size

    def mp_size(self):
        self._check()
        return self.topology.pp_size * self.topology.tp_size

    def cp_size(self):
        self._check()
        return self.topology.cp_size

    def ep_size(self):
        self._check()
        return self.topology.ep_size

    # -- rank conversions (parity: reference backend/core.py:439-477) ---
    # Each converts a per-axis rank into the WORLD rank of the peer
    # holding that coordinate within this process's other-axis groups.

    @staticmethod
    def _axis_rank_in_range(value, size, name):
        """Numpy indexing would silently wrap negatives (pp_rank_to_rank(-1)
        -> last stage) — an off-by-one would target the wrong peer in a
        collective, so validate like instance_id does."""
        if not 0 <= value < size:
            raise SMPValidationError(
                f"{name} {value} out of range [0, {size})."
            )

    def pp_rank_to_rank(self, pp_rank):
        """World rank of pipeline stage ``pp_rank`` within this rank's
        tp x rdp group."""
        self._axis_rank_in_range(pp_rank, self.pp_size(), "pp_rank")
        rk = self.topology.ranker
        me = self._default_rank()
        return rk.translate(pp_rank=pp_rank, tp_rank=rk.get_tp_rank(me),
                            rdp_rank=rk.get_rdp_rank(me))

    def tp_rank_to_rank(self, tp_rank):
        self._axis_rank_in_range(tp_rank, self.tp_size(), "tp_rank")
        rk = self.topology.ranker
        me = self._default_rank()
        return rk.translate(pp_rank=rk.get_pp_rank(me), tp_rank=tp_rank,
                            rdp_rank=rk.get_rdp_rank(me))

    def rdp_rank_to_rank(self, rdp_rank):
        self._axis_rank_in_range(rdp_rank, self.rdp_size(), "rdp_rank")
        rk = self.topology.ranker
        me = self._default_rank()
        return rk.translate(pp_rank=rk.get_pp_rank(me),
                            tp_rank=rk.get_tp_rank(me), rdp_rank=rdp_rank)

    def dp_rank_to_rank(self, dp_rank):
        """World rank of composite-dp rank ``dp_rank`` in this rank's
        pp group (dp folds tp x rdp, reference composite order)."""
        self._axis_rank_in_range(dp_rank, self.dp_size(), "dp_rank")
        rk = self.topology.ranker
        me = self._default_rank()
        return rk.translate(
            pp_rank=rk.get_pp_rank(me),
            tp_rank=rk.get_tp_rank_from_dp_rank(dp_rank),
            rdp_rank=rk.get_rdp_rank_from_dp_rank(dp_rank),
        )

    def mp_rank_to_rank(self, mp_rank):
        """World rank of composite-mp rank ``mp_rank`` in this rank's
        rdp group (mp folds pp x tp)."""
        self._axis_rank_in_range(mp_rank, self.mp_size(), "mp_rank")
        rk = self.topology.ranker
        me = self._default_rank()
        return rk.translate(
            pp_rank=rk.get_pp_rank_from_mp_rank(mp_rank),
            tp_rank=rk.get_tp_rank_from_mp_rank(mp_rank),
            rdp_rank=rk.get_rdp_rank(me),
        )

    def get_pp_group(self, device_index=None):
        return self.topology.ranker.get_pp_group(self.rank(device_index))

    def get_tp_group(self, device_index=None):
        return self.topology.ranker.get_tp_group(self.rank(device_index))

    def get_rdp_group(self, device_index=None):
        return self.topology.ranker.get_rdp_group(self.rank(device_index))

    def get_dp_group(self, device_index=None):
        return self.topology.ranker.get_dp_group(self.rank(device_index))

    def get_mp_group(self, device_index=None):
        return self.topology.ranker.get_mp_group(self.rank(device_index))

    def get_cp_group(self, device_index=None):
        from smdistributed_modelparallel_tpu.backend.topology import CP_AXIS

        return self.topology.axis_group(self.rank(device_index), CP_AXIS)

    def get_world_group(self):
        self._check()
        return self.topology.ranker.get_world_group()

    @property
    def mesh(self):
        self._check()
        return self.topology.mesh

