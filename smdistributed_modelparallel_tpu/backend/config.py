"""Configuration engine.

Parity target: reference ``backend/config.py:181-306`` — dependency-ordered
evaluation of a declarative schema (``DependencyIterator``), type/options/
bounds checks, aliases, cross-parameter ``requires`` / ``requires_not`` /
``requires_either`` constraints, arithmetic default formulas, and SageMaker
environment injection via ``SM_HP_MP_PARAMETERS``.
"""

import json
import os
import re

from smdistributed_modelparallel_tpu.backend.schema import SCHEMA
from smdistributed_modelparallel_tpu.utils.exceptions import ConfigError
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

_FORMULA_REF = re.compile(r"\(([A-Za-z_][A-Za-z0-9_]*)\)")


class DependencyIterator:
    """Yield schema keys so every key appears after its declared dependencies.

    Parity: reference ``backend/config.py:181-200``.
    """

    def __init__(self, schema):
        self.schema = schema

    def __iter__(self):
        emitted = set()
        pending = list(self.schema.keys())
        while pending:
            progressed = False
            remaining = []
            for key in pending:
                deps = self.schema[key].get("dependencies", [])
                if all(d in emitted for d in deps):
                    emitted.add(key)
                    progressed = True
                    yield key
                else:
                    remaining.append(key)
            if not progressed:
                raise ConfigError(f"Circular dependency among config keys: {remaining}")
            pending = remaining


def _eval_formula(expr, values):
    """Evaluate an arithmetic default/bound like ``(pipeline_parallel_degree) + 2``."""

    def sub(m):
        name = m.group(1)
        if name not in values:
            raise ConfigError(f"Formula references unknown/unevaluated key '{name}': {expr}")
        return repr(values[name])

    py = _FORMULA_REF.sub(sub, expr)
    if not re.fullmatch(r"[0-9eE\.\+\-\*/\(\) ]+", py):
        raise ConfigError(f"Unsafe formula: {expr!r}")
    return eval(py)  # noqa: S307 - validated to arithmetic-only above


def _coerce(key, value, types):
    if isinstance(types, type):
        types = (types,)
    if bool in types and not isinstance(value, bool) and value in (0, 1):
        # Schema bools accept 0/1 from JSON/env configs.
        return bool(value)
    if isinstance(value, bool) and bool not in types:
        raise ConfigError(f"Config '{key}': expected {types}, got bool {value}")
    if isinstance(value, tuple(t for t in types if t is not type(None))):
        return value
    # ints are acceptable where floats are required; floats with integral value
    # are acceptable where ints are required (matches 5e8-style YAML defaults).
    if float in types and isinstance(value, int):
        return float(value)
    if int in types and isinstance(value, float) and value == int(value):
        return int(value)
    if type(None) in types and value is None:
        return None
    raise ConfigError(f"Config '{key}': expected {types}, got {type(value).__name__} {value!r}")


class ModelParallelConfig:
    """Validated, attribute-accessible configuration.

    Parity: reference ``backend/config.py:203-306``.
    """

    def __init__(self, user_config=None):
        user_config = dict(user_config or {})
        env_cfg = os.environ.get("SM_HP_MP_PARAMETERS")
        if env_cfg and not user_config:
            try:
                user_config = json.loads(env_cfg)
            except json.JSONDecodeError as e:
                raise ConfigError(f"SM_HP_MP_PARAMETERS is not valid JSON: {e}")

        # Environment aliases for the ZeRO-3 knobs (SMP_ZERO3 /
        # SMP_ZERO3_BUCKET_MB): applied only when the user config does not
        # set the canonical key, so an explicit config always wins.
        env_zero3 = os.environ.get("SMP_ZERO3")
        if env_zero3 is not None and "sharded_params" not in user_config:
            if env_zero3.lower() in ("1", "on", "true", "zero3"):
                user_config["sharded_params"] = "zero3"
            elif env_zero3.lower() in ("0", "off", "false", "none"):
                user_config["sharded_params"] = "none"
            else:
                raise ConfigError(
                    f"SMP_ZERO3={env_zero3!r}: expected 1/on/true/zero3 "
                    "or 0/off/false/none"
                )
        env_bucket = os.environ.get("SMP_ZERO3_BUCKET_MB")
        if env_bucket is not None and "zero3_bucket_mb" not in user_config:
            try:
                user_config["zero3_bucket_mb"] = int(env_bucket)
            except ValueError:
                raise ConfigError(
                    f"SMP_ZERO3_BUCKET_MB={env_bucket!r} is not an integer"
                )

        # Environment aliases for the recompute planner (SMP_RECOMPUTE /
        # SMP_RECOMPUTE_BUDGET_MB), same precedence rule as the ZeRO ones.
        env_recompute = os.environ.get("SMP_RECOMPUTE")
        if env_recompute is not None and "recompute" not in user_config:
            val = env_recompute.strip().lower()
            if val in ("full", "stash_weight", "stash_all", "auto"):
                user_config["recompute"] = val
            else:
                raise ConfigError(
                    f"SMP_RECOMPUTE={env_recompute!r}: expected "
                    "full/stash_weight/stash_all/auto"
                )
        env_rbudget = os.environ.get("SMP_RECOMPUTE_BUDGET_MB")
        if env_rbudget is not None and "recompute_budget_mb" not in user_config:
            try:
                user_config["recompute_budget_mb"] = int(env_rbudget)
            except ValueError:
                raise ConfigError(
                    f"SMP_RECOMPUTE_BUDGET_MB={env_rbudget!r} is not an "
                    "integer"
                )

        # Environment alias for overlapped tensor parallelism
        # (SMP_TP_OVERLAP), same precedence rule: explicit config wins.
        env_tp_overlap = os.environ.get("SMP_TP_OVERLAP")
        if env_tp_overlap is not None and "tp_overlap" not in user_config:
            val = env_tp_overlap.strip().lower()
            if val in ("ring",):
                user_config["tp_overlap"] = "ring"
            elif val in ("0", "off", "false", "none"):
                user_config["tp_overlap"] = "off"
            else:
                raise ConfigError(
                    f"SMP_TP_OVERLAP={env_tp_overlap!r}: expected "
                    "ring or 0/off/false/none"
                )

        # Environment alias for the training matmul precision
        # (SMP_MATMUL_PRECISION), same precedence rule.
        env_matmul_prec = os.environ.get("SMP_MATMUL_PRECISION")
        if (env_matmul_prec is not None
                and "matmul_precision" not in user_config):
            val = env_matmul_prec.strip().lower()
            if val in ("fp8", "float8"):
                user_config["matmul_precision"] = "fp8"
            elif val in ("0", "off", "false", "none", "bf16", "bfloat16"):
                user_config["matmul_precision"] = "bf16"
            else:
                raise ConfigError(
                    f"SMP_MATMUL_PRECISION={env_matmul_prec!r}: expected "
                    "fp8 or bf16/0/off/none"
                )

        # Resolve aliases (e.g. partitions -> pipeline_parallel_degree).
        alias_map = {
            spec["alias"]: key for key, spec in SCHEMA.items() if "alias" in spec
        }
        resolved = {}
        for key, value in user_config.items():
            canonical = alias_map.get(key, key)
            if canonical not in SCHEMA:
                raise ConfigError(f"Unknown config key '{key}'")
            if canonical in resolved:
                raise ConfigError(f"Config key '{canonical}' specified twice (via alias '{key}')")
            resolved[canonical] = value

        values = {}
        for key in DependencyIterator(SCHEMA):
            spec = SCHEMA[key]
            if key in resolved:
                value = _coerce(key, resolved[key], spec["type"])
                if spec.get("deprecated"):
                    logger.warning(
                        "Config '%s' is deprecated; use '%s'.", key, spec.get("replacement")
                    )
                if spec.get("advisory") and value != spec["default"]:
                    logger.warning(
                        "Config '%s' is advisory on TPU (%s); accepted for "
                        "reference compatibility but has no effect.",
                        key, spec["advisory"],
                    )
            else:
                value = spec["default"]
                if isinstance(value, str) and _FORMULA_REF.search(value) and spec["type"] is int:
                    value = int(_eval_formula(value, values))
                    # Computed defaults are clamped into bounds rather than
                    # rejected (e.g. active_microbatches = pp+2 > microbatches).
                    value = self._clamp(spec, value, values)
            if value is not None:
                self._check_bounds(key, spec, value, values)
                self._check_options(key, spec, value)
                self._check_multiple(key, spec, value)
            values[key] = value

        # The ZeRO-2D JSON overrides land BEFORE constraint checking so the
        # keys it sets go through the same requires/cross validation as
        # directly-specified values.
        if values.get("_sharded_data_parallelism_config") is not None:
            self._apply_sdp_json(values)

        for key, spec in SCHEMA.items():
            self._check_requires(key, spec, values)

        self._values = values
        self._validate_cross(values)

    @staticmethod
    def _clamp(spec, value, values):
        lo, hi = spec.get("lower_bound"), spec.get("upper_bound")
        if isinstance(lo, str):
            lo = _eval_formula(lo, values)
        if isinstance(hi, str):
            hi = _eval_formula(hi, values)
        if lo is not None:
            value = max(value, lo)
        if hi is not None:
            value = min(value, hi)
        return value

    @staticmethod
    def _check_bounds(key, spec, value, values):
        for bound_name, op in (("lower_bound", "<"), ("upper_bound", ">")):
            bound = spec.get(bound_name)
            if bound is None:
                continue
            if isinstance(bound, str):
                bound = _eval_formula(bound, values)
            if (op == "<" and value < bound) or (op == ">" and value > bound):
                raise ConfigError(
                    f"Config '{key}'={value} violates {bound_name}={bound}"
                )

    @staticmethod
    def _check_options(key, spec, value):
        options = spec.get("options")
        if options is not None and value not in options:
            raise ConfigError(f"Config '{key}'={value!r} not in allowed options {options}")

    @staticmethod
    def _check_multiple(key, spec, value):
        mult = spec.get("multiple_of")
        if mult is not None and isinstance(value, int) and value % mult:
            raise ConfigError(
                f"Config '{key}'={value} must be a multiple of {mult}"
            )

    @staticmethod
    def _check_requires(key, spec, values):
        value = values[key]
        default = spec["default"]
        is_non_default = value != default or (isinstance(default, str) and _FORMULA_REF.search(str(default)))
        if not is_non_default:
            return
        for dep, required in spec.get("requires", {}).items():
            # A list/tuple of required values means "any of these".
            if isinstance(required, (list, tuple)):
                if values[dep] not in required:
                    raise ConfigError(
                        f"Config '{key}'={value} requires '{dep}' in "
                        f"{list(required)}, got {values[dep]}"
                    )
            elif values[dep] != required:
                raise ConfigError(
                    f"Config '{key}'={value} requires '{dep}'={required}, got {values[dep]}"
                )
        for dep, forbidden in spec.get("requires_not", {}).items():
            if values[dep] == forbidden:
                raise ConfigError(
                    f"Config '{key}'={value} requires '{dep}' != {forbidden!r}"
                )
        req_either = spec.get("requires_either")
        if req_either and not any(values[d] == v for d, v in req_either.items()):
            raise ConfigError(
                f"Config '{key}'={value} requires one of {req_either}"
            )

    def _validate_cross(self, v):
        if v["ddp_dist_backend"] == "nccl":
            logger.info("ddp_dist_backend=nccl accepted for compatibility; using XLA collectives.")
            v["ddp_dist_backend"] = "xla"
        if v["sharded_data_parallel_degree"] > 1 and not v["ddp"]:
            # Reference enables ZeRO-2D only under ddp; mirror that requirement.
            raise ConfigError("sharded_data_parallel_degree > 1 requires ddp: True")
        if (v["sharded_params"] == "zero3"
                and v["_sharded_data_parallelism_config"] is not None):
            raise ConfigError(
                "sharded_params: zero3 and _sharded_data_parallelism_config "
                "(zero2d) are mutually exclusive ZeRO modes."
            )
        if v["offload_activations"] and v["activation_loading_horizon"] < 1:
            logger.warning("activation_loading_horizon=0 disables offload prefetch pipelining.")

    def _apply_sdp_json(self, v):
        """Parse ``_sharded_data_parallelism_config`` (a DeepSpeed-style
        JSON file path or dict) onto the ``sdp_*`` knobs.

        Parity: reference ``backend/zero_config.py:13-131`` — the custom
        JSON recursively overrides the defaults built from the sdp_*
        params; stage must be 3. Keys with no TPU counterpart (DeepSpeed
        scheduler/engine options) are accepted as advisory with a warning.
        """
        import json
        import os

        raw = v["_sharded_data_parallelism_config"]
        if isinstance(raw, str):
            if not os.path.exists(raw):
                raise ConfigError(
                    f"_sharded_data_parallelism_config file not found: {raw}"
                )
            with open(raw, encoding="utf-8") as fh:
                raw = json.load(fh)
        if not isinstance(raw, dict):
            raise ConfigError(
                "_sharded_data_parallelism_config must be a dict or a JSON "
                f"file path (got {type(raw).__name__})."
            )
        zo = raw.get("zero_optimization", {})
        if not isinstance(zo, dict):
            raise ConfigError("zero_optimization must be a dict.")
        if zo.get("stage", 3) != 3:
            raise ConfigError(
                "Only ZeRO stage 3 is supported in "
                "_sharded_data_parallelism_config (reference parity)."
            )
        if zo.get("offload_optimizer") or zo.get("offload_param"):
            raise ConfigError(
                "cpu offload in _sharded_data_parallelism_config is not "
                "supported (reference parity)."
            )
        mapping = {
            "reduce_bucket_size": "sdp_reduce_bucket_size",
            "stage3_param_persistence_threshold": "sdp_param_persistence_threshold",
            "stage3_max_live_parameters": "sdp_max_live_parameters",
            "zero2d_hierarchy_allgather": "sdp_hierarchical_allgather",
            "zero2d_shard_size": "sharded_data_parallel_degree",
        }
        consumed = {"stage", "offload_optimizer", "offload_param"}
        for src, dst in mapping.items():
            if src in zo:
                v[dst] = _coerce(dst, zo[src], SCHEMA[dst]["type"])
                self._check_bounds(dst, SCHEMA[dst], v[dst], v)
                consumed.add(src)
        if "gradient_clipping" in raw:
            v["sdp_gradient_clipping"] = _coerce(
                "sdp_gradient_clipping", raw["gradient_clipping"],
                SCHEMA["sdp_gradient_clipping"]["type"],
            )
        advisory = sorted(set(zo) - consumed) + sorted(
            set(raw) - {"zero_optimization", "gradient_clipping"}
        )
        if advisory:
            logger.warning(
                "_sharded_data_parallelism_config keys with no TPU "
                "counterpart (advisory, ignored): %s", advisory,
            )

    # -- accessors ------------------------------------------------------

    def __getattr__(self, name):
        try:
            return self.__dict__["_values"][name]
        except KeyError:
            raise AttributeError(name)

    def __contains__(self, name):
        return name in self._values

    def as_dict(self):
        return dict(self._values)

    def __repr__(self):
        non_default = {
            k: v for k, v in self._values.items() if v != SCHEMA[k]["default"]
        }
        return f"ModelParallelConfig({non_default})"

    # Convenience composite sizes -------------------------------------

    @property
    def zero2d_enabled(self):
        return (
            self._values["sharded_data_parallel_degree"] > 1
            or self._values["_sharded_data_parallelism_config"] is not None
        )

    @property
    def zero3_enabled(self):
        return self._values["sharded_params"] == "zero3"

    @property
    def half_dtype(self):
        if self._values["bf16"]:
            return "bfloat16"
        if self._values["fp16"] or self._values["fp16_params"]:
            return "float16"
        return None
