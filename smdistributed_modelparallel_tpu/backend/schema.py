"""Declarative configuration schema.

Parity target: reference ``backend/config.yaml:1-315``. Same key set and
semantics (types, defaults, bounds, aliases, cross-parameter ``requires`` /
``requires_not`` / ``requires_either`` constraints, arithmetic default
formulas such as ``(pipeline_parallel_degree) + 2``), expressed as Python
data instead of YAML, with TPU-specific re-interpretations noted per key and
a handful of new TPU-native keys (context parallelism, sequence parallelism)
per SURVEY.md §5.7/§7-M6.

A formula default/bound is a string containing ``(other_param)`` references;
it is evaluated after its dependencies (see ``DependencyIterator`` in
``config.py``).
"""

# Each entry: type (a python type, a tuple of types, or 'none-able' via tuple
# containing type(None)), default, optional lower_bound/upper_bound (number or
# formula str), options list, alias str, requires / requires_not /
# requires_either dicts, dependencies list, internal / deprecated flags.

SCHEMA = {
    "pipeline_parallel_degree": {
        "type": int,
        "default": 1,
        "lower_bound": 1,
        "alias": "partitions",
        "description": "Pipeline parallelism degree.",
    },
    "tensor_parallel_degree": {
        "type": int,
        "default": 1,
        "lower_bound": 1,
        "requires": {"ddp": True},
        "dependencies": ["ddp"],
        "description": "Tensor parallelism degree.",
    },
    "microbatches": {
        "type": int,
        "default": 1,
        "lower_bound": 1,
        "description": "Number of microbatches the incoming batch is split into; "
        "batch size must be divisible by this value.",
    },
    "pipeline": {
        "type": str,
        "default": "interleaved",
        "options": ["simple", "interleaved", "zero_bubble", "_only_forward"],
        "description": "Pipelining schedule. 'interleaved' lowers to a 1F1B "
        "schedule in the compiled microbatch loop; 'simple' to all-forward-"
        "then-all-backward; 'zero_bubble' to the ZB-H1 split-backward "
        "schedule (input-grad pass on the critical path, weight-grad pass "
        "deferred into the cooldown bubble — bound "
        "2(pp-1)/(3*v*mb+2(pp-1)), below the interleaved floor at the same "
        "activation memory; composes with virtual_pipeline_degree).",
    },
    "virtual_pipeline_degree": {
        "type": int,
        "default": 1,
        "lower_bound": 1,
        "alias": "virtual_pipeline_parallel_degree",
        "requires": {"pipeline": ["interleaved", "zero_bubble"]},
        "dependencies": ["pipeline"],
        "description": "Megatron-style interleaved virtual pipeline stages: "
        "each pipeline rank owns this many non-contiguous model chunks "
        "(chunk c runs on stage c mod pp), shrinking the 1F1B bubble floor "
        "from (pp-1)/(mb+pp-1) to (pp-1)/(v*mb+pp-1) at the cost of v x "
        "more stage-boundary collective-permutes per microbatch. Requires "
        "the 1F1B ('interleaved') schedule; no effect at "
        "pipeline_parallel_degree 1.",
    },
    "horovod": {
        "advisory": "SPMD collectives replace horovod",
        "type": bool,
        "default": False,
        "description": "Reference-compat flag (TF/Horovod DP). Accepted, unused on TPU.",
    },
    "ddp": {
        "type": bool,
        "default": False,
        "requires": {"horovod": False},
        "dependencies": ["horovod"],
        "description": "Enable data parallelism (reference: PyTorch DDP). Required "
        "for data and tensor parallelism; on TPU this toggles the dp/rdp mesh axes.",
    },
    "sharded_data_parallel_degree": {
        "type": int,
        "default": 1,
        "lower_bound": 1,
        "requires": {
            "tensor_parallel_degree": 1,
            "pipeline_parallel_degree": 1,
            "shard_optimizer_state": False,
        },
        "dependencies": [
            "tensor_parallel_degree",
            "pipeline_parallel_degree",
            "shard_optimizer_state",
        ],
        "description": "Sharded data parallelism (reference: ZeRO-2D / DeepSpeed "
        "stage 3). On TPU this lowers to fully-sharded parameter PartitionSpecs "
        "over the dp axis.",
    },
    "sdp_reduce_bucket_size": {
        "type": int,
        "default": int(5e8),
        "description": "Gradient-reduction bucket size in elements. Advisory on TPU "
        "(XLA fuses reductions); kept for config compatibility.",
    },
    "sdp_param_persistence_threshold": {
        "type": int,
        "default": int(1e6),
        "description": "Parameters smaller than this many elements are kept "
        "replicated rather than sharded under sharded data parallelism.",
    },
    "sdp_max_live_parameters": {
        "type": int,
        "default": int(1e9),
        "description": "Max number of parameters simultaneously in recombined "
        "(allgathered) state. Advisory on TPU; XLA schedules allgathers.",
    },
    "sdp_hierarchical_allgather": {
        "type": bool,
        "default": True,
        "description": "Hierarchical (intra- then inter-host) parameter allgather. "
        "On TPU, ICI/DCN hierarchy is chosen by XLA from the mesh layout.",
    },
    "sdp_gradient_clipping": {
        "type": float,
        "default": 1.0,
        "description": "Global grad-norm clip value applied under sharded data parallelism.",
    },
    "sharded_params": {
        "type": str,
        "default": "none",
        "options": ["none", "zero3"],
        "requires": {
            "ddp": True,
            "sharded_data_parallel_degree": 1,
            "horovod": False,
        },
        "dependencies": [
            "ddp", "sharded_data_parallel_degree", "horovod",
        ],
        "description": "Fully-sharded parameters (ZeRO-3 / FSDP over the rdp "
        "mesh axis): 'zero3' stores every parameter >= "
        "sdp_param_persistence_threshold elements sharded over rdp, "
        "all-gathers each layer's params just-in-time in forward (and "
        "regathers in backward), and reduce-scatters gradients in "
        "zero3_bucket_mb buckets overlapped with the backward. Env alias: "
        "SMP_ZERO3=1. Mutually exclusive with the legacy zero2d knob "
        "(sharded_data_parallel_degree).",
    },
    "zero3_bucket_mb": {
        "type": int,
        "default": 25,
        "lower_bound": 1,
        "description": "Gradient reduce-scatter bucket size in MiB under "
        "sharded_params: zero3 (reference: DeepSpeed reduce_bucket_size). "
        "Env alias: SMP_ZERO3_BUCKET_MB.",
    },
    "_sharded_data_parallelism_config": {
        "type": (str, dict, type(None)),
        "default": None,
        "internal": True,
        "description": "DeepSpeed-style sharded-DP overrides: a JSON file "
        "path or an inline dict (zero_optimization.* keys map onto sdp_*).",
    },
    "ddp_port": {
        "advisory": "no TCP rendezvous under the JAX runtime",
        "type": (int, type(None)),
        "default": None,
        "lower_bound": 0,
        "requires": {"ddp": True},
        "dependencies": ["ddp"],
        "description": "Reference-compat; coordination port for jax.distributed.",
    },
    "ddp_dist_backend": {
        "type": str,
        "default": "xla",
        "options": ["xla", "nccl"],
        "description": "Collective backend. On TPU always 'xla' (ICI collectives); "
        "'nccl' is accepted for config compatibility and treated as 'xla'.",
    },
    "contiguous": {
        "advisory": "TF-runtime key; the single JAX runtime has no graph split",
        "type": bool,
        "default": True,
        "description": "Force pipeline stages to be contiguous layer ranges "
        "(reference: TF subgraph contiguity). The TPU pipeline is always "
        "contiguous-per-stage; False is accepted and ignored.",
    },
    "placement_strategy": {
        "type": str,
        "default": "cluster",
        "options": ["cluster", "spread", "PDT", "PTD", "DPT", "DTP", "TPD", "TDP"],
        "description": "Mapping of (pp, rdp, tp) onto physical devices; the "
        "right-most letter varies fastest over neighboring devices. 'cluster'="
        "'DPT', 'spread'='TPD'. Lowers directly to jax.sharding.Mesh axis order.",
    },
    "optimize": {
        "type": str,
        "default": "speed",
        "options": ["speed", "memory"],
        "description": "DistributedTransformer layout: 'speed' = head-partitioned "
        "(Megatron-style allgather/reduce), 'memory' = input-partitioned "
        "(all-to-all scatter-merge).",
    },
    "auto_partition": {
        "type": bool,
        "default": True,
        "requires_not": {"default_partition": None},
        "dependencies": ["default_partition"],
        "description": "Enable auto-partitioning of modules across pipeline stages.",
    },
    "default_partition": {
        "type": (int, type(None)),
        "default": None,
        "lower_bound": 0,
        "upper_bound": "(pipeline_parallel_degree) - 1",
        "dependencies": ["pipeline_parallel_degree"],
        "description": "Partition for modules not explicitly assigned when "
        "auto_partition is disabled.",
    },
    "prescaled_batch": {
        "type": bool,
        "default": False,
        "requires": {"optimize": "speed"},
        "dependencies": ["optimize"],
        "description": "DistributedTransformerLMHead expects the same batch on "
        "every tp_rank (batch defined per TP group).",
    },
    "memory_weight": {
        "type": float,
        "default": 0.8,
        "lower_bound": 0.0,
        "upper_bound": 1.0,
        "description": "Relative weight of memory (vs compute time) in the "
        "auto-partitioner cost model.",
    },
    "active_microbatches": {
        "type": int,
        "default": "(pipeline_parallel_degree) + 2",
        "lower_bound": 1,
        "upper_bound": "(microbatches)",
        "dependencies": ["microbatches", "pipeline_parallel_degree"],
        "description": "Max microbatches simultaneously in flight; bounds "
        "activation memory of the pipeline schedule.",
    },
    "fast_mode": {
        "advisory": "no MPMD message passing to shortcut",
        "type": bool,
        "default": False,
        "internal": True,
        "description": "Reference-compat. The compiled TPU pipeline always does "
        "direct stage-to-stage transfers; accepted and ignored.",
    },
    "static_mode": {
        "advisory": "the compiled step IS static",
        "type": bool,
        "default": False,
        "internal": True,
        "description": "Reference-compat. The TPU schedule is always static "
        "(baked into the compiled program); accepted and ignored.",
    },
    "fp16": {
        "type": bool,
        "default": False,
        "description": "Train in float16 with dynamic loss scaling.",
    },
    "bf16": {
        "type": bool,
        "default": False,
        "requires": {"fp16": False, "fp16_params": False},
        "dependencies": ["fp16", "fp16_params"],
        "description": "Train in bfloat16 (the native TPU half precision).",
    },
    "fp16_params": {
        "type": bool,
        "default": False,
        "deprecated": True,
        "replacement": "fp16",
        "description": "Deprecated; use fp16.",
    },
    "tensor_parallel_seed": {
        "type": int,
        "default": 0,
        "lower_bound": 0,
        "description": "Seed for random ops inside tensor-parallel distributed modules.",
    },
    "offload_activations": {
        "type": bool,
        "default": False,
        "description": "Offload checkpointed activations to host memory during "
        "forward, reload during backward. Only functional with activation "
        "checkpointing.",
    },
    "_shard_offloaded_activations": {
        "advisory": "XLA manages offload buffers",
        "type": bool,
        "default": True,
        "internal": True,
        "description": "Shard offloaded activations across the TP group instead "
        "of offloading replicas from every tp_rank.",
    },
    "shard_optimizer_state": {
        "type": bool,
        "default": False,
        "description": "Shard optimizer state across (reduced-)data-parallel ranks "
        "(reference: virtual-parameter contiguous buffer; TPU: opt-state "
        "PartitionSpecs over the rdp axis).",
    },
    "delayed_parameter_initialization": {
        "type": bool,
        "default": False,
        "description": "Initialize parameters lazily/abstractly and materialize "
        "them directly sharded on device (TPU: jax.eval_shape + sharded init).",
    },
    "skip_tracing": {
        "type": bool,
        "default": False,
        "description": "Skip the cost-tracing pass; the auto-partitioner falls "
        "back to parameter-count costs from jax.eval_shape.",
    },
    "activation_loading_horizon": {
        "type": int,
        "default": 4,
        "lower_bound": 0,
        "description": "How many offloaded layer activations may simultaneously "
        "be resident on device awaiting consumption.",
    },
    "task_level_activation_loading_horizon": {
        "advisory": "XLA schedules host offload",
        "type": int,
        "default": 4,
        "lower_bound": 1,
        "internal": True,
        "description": "Reference-compat scheduling knob; advisory on TPU.",
    },
    "herring": {
        "advisory": "SPMD collectives replace herring",
        "type": bool,
        "default": False,
        "requires": {"ddp": False, "horovod": False},
        "dependencies": ["ddp", "horovod"],
        "internal": True,
        "description": "Reference-compat; not functional.",
    },
    "_match_weights": {
        "type": bool,
        "default": False,
        "internal": True,
        "description": "Debug: verify distributed weights match the source "
                       "module at distribution time (here: the HF "
                       "translation round-trips against the source state "
                       "dict, logged per key).",
    },
    "_fp32_grad_accumulation": {
        "type": bool,
        "default": False,
        "internal": True,
        "requires_either": {"fp16": True, "fp16_params": True},
        "dependencies": ["fp16", "fp16_params"],
        "description": "Accumulate microbatch gradients in float32.",
    },
    "checkpoint_attentions": {
        "advisory": "use activation-checkpointing configs (smp.set_activation_checkpointing) — remat granularity is the layer",
        "type": bool,
        "default": False,
        "internal": True,
        "description": "Activation-checkpoint the attention score computation in "
        "DistributedTransformer.",
    },
    "load_partition": {
        "type": bool,
        "default": False,
        "internal": True,
        "description": "Load a saved partition assignment instead of repartitioning.",
    },
    "partition_file": {
        "type": (str, type(None)),
        "default": None,
        "internal": True,
        "description": "Path for saving/loading partition assignments.",
    },
    # ------------------------------------------------------------------
    # TPU-native extensions (no reference counterpart; SURVEY.md §5.7, §7-M6)
    # ------------------------------------------------------------------
    "context_parallel_degree": {
        "type": int,
        "default": 1,
        "lower_bound": 1,
        "description": "TPU extension: context (sequence) parallelism degree for "
        "long sequences; shards the sequence dimension across a 'cp' mesh axis.",
    },
    "context_parallel_impl": {
        "type": str,
        "default": "ring",
        "options": ["ring", "ulysses", "allgather"],
        "description": "TPU extension: ring attention (ppermute KV rotation), "
        "Ulysses (all_to_all head/sequence exchange), or allgather-KV.",
    },
    "expert_parallel_degree": {
        "type": int,
        "default": 1,
        "lower_bound": 1,
        "description": "TPU extension: expert parallelism degree for MoE layers.",
    },
    "moe_aux_loss_weight": {
        "type": float,
        "default": 1.0,
        "lower_bound": 0.0,
        "description": "TPU extension: global multiplier on the MoE router "
        "load-balancing auxiliary loss folded into the differentiated step "
        "loss (each DistributedMoE layer's own aux_loss_coef still applies). "
        "0 disables the aux term.",
    },
    "use_pallas_kernels": {
        "type": bool,
        "default": True,
        "description": "TPU extension: dispatch attention/softmax to Pallas "
        "kernels on TPU (jnp fallback elsewhere or when shapes don't tile).",
    },
    "fused_optimizer_step": {
        "type": bool,
        "default": True,
        "description": "TPU extension: compile the optimizer update into the "
        "step program (one device launch per training iteration). The update "
        "is installed only when optimizer.step() is called; disabled "
        "automatically under fp16 loss scaling. Memory note: because the "
        "step may legally run without a following optimizer.step(), the "
        "fused program cannot donate params/opt_state by default, so peak "
        "memory holds one extra params+opt_state copy vs the donated "
        "standalone update; enable fused_step_donation (steady-state "
        "training) or set False to restore the donated memory profile on "
        "tight-HBM configs.",
    },
    "fused_step_donation": {
        "type": bool,
        "default": False,
        "requires": {"fused_optimizer_step": True},
        "dependencies": ["fused_optimizer_step"],
        "description": "TPU extension: donate the params and optimizer-state "
        "buffers through the fused step program, removing the extra "
        "params+opt_state copy from peak HBM. The update is installed at "
        "step return (the input buffers are gone), so every training step "
        "behaves as if followed by optimizer.step() — calling step() is "
        "still fine and becomes a no-op confirmation. Do not enable if the "
        "training loop reads PRE-update parameters after a step or "
        "intentionally skips optimizer.step().",
    },
    "fused_ce": {
        "type": (bool, str),
        "default": "auto",
        "options": [True, False, "auto"],
        "description": "TPU extension: LM-head cross-entropy path for "
        "model(ids, targets=...) loss mode. True: stream vocab through "
        "the blockwise Pallas kernel (logits never materialize; the "
        "backward recomputes logit blocks, ~5/3 the head matmul flops) — "
        "falls back WITH A WARNING where the kernel cannot run (off-TPU, "
        "tp-sharded vocab, no block configuration fits VMEM). False: "
        "always materialize logits (fastest when they fit). 'auto' "
        "(default): use the kernel only when the per-microbatch logits "
        "(at the activation dtype) would exceed fused_ce_auto_threshold_mb "
        "— at that size the HBM capacity win outweighs the recompute; "
        "below it the logits path is faster on every measured shape.",
    },
    "pallas_attn_block_q": {
        "type": (int, type(None)),
        "default": None,
        "lower_bound": 128,
        "multiple_of": 128,
        "description": "TPU extension: flash-attention q-tile rows "
        "(default 256; Mosaic lane alignment requires multiples of 128). "
        "Tune per TPU generation with the bench's breakdown mode.",
    },
    "pallas_attn_block_k": {
        "type": (int, type(None)),
        "default": None,
        "lower_bound": 128,
        "multiple_of": 128,
        "description": "TPU extension: flash-attention kv-tile rows "
        "(default 512; 256 inside context-parallel regions). Multiples "
        "of 128 only.",
    },
    "fused_ce_auto_threshold_mb": {
        "type": int,
        "default": 2048,
        "lower_bound": 1,
        "description": "TPU extension: logits-size threshold (MB, at the "
        "activation dtype — bf16 logits count 2 bytes/element, fp32 count "
        "4) above which fused_ce: auto switches to the no-materialize "
        "Pallas CE kernel.",
    },
    "tp_overlap": {
        "type": str,
        "default": "off",
        "options": ["off", "ring"],
        "description": "TPU extension: overlapped tensor parallelism "
        "(env alias SMP_TP_OVERLAP). 'off' (default): the GSPMD path — "
        "synchronous tp all-gather/reduce-scatter/all-reduce around the "
        "tp matmuls, byte-identical programs to older builds. 'ring': "
        "the column-parallel input all-gather and row-parallel output "
        "reduce-scatter of the tp attention/MLP blocks decompose into "
        "tp-many ppermute hops, each hidden under the partial matmul on "
        "the block already in hand (ops/collective_matmul.py; "
        "double-buffered, custom_vjp mirrored backward ring). Implies "
        "the sequence-parallel (optimize: memory) residual layout over "
        "tp. Inert at tensor_parallel_degree 1; does not compose with "
        "context_parallel_degree > 1 (the ring owns the sequence axis).",
    },
    "matmul_precision": {
        "type": str,
        "default": "bf16",
        "options": ["bf16", "fp8"],
        "description": "TPU extension: training matmul precision (env "
        "alias SMP_MATMUL_PRECISION). 'bf16' (default): byte-identical "
        "programs to older builds — the knob contributes nothing to "
        "step keys, exec-cache facts, or X-ray fingerprints. 'fp8': "
        "the matmul seams (tp ring chunk matmuls, fused-QKV Pallas "
        "kernel, transformer/linear einsum paths, bias+GELU epilogue "
        "input, attention score operands) quantize to fp8 — e4m3 "
        "forward operands, e5m2 gradients — with delayed scaling: "
        "per-slot amax history threaded through the step like the "
        "fp16 loss scaler (smp.quant.QuantState; checkpointed beside "
        "it as quant_states.pt). Canonicalizes back to bf16 under "
        "pipeline_parallel_degree > 1 or sharded_params: zero3 (warn "
        "once). On CPU/interpret XLA upcasts the f8 dots — CPU runs "
        "prove parity, not speed (BENCH_NOTES Round 20).",
    },
    "fused_qkv": {
        "type": bool,
        "default": False,
        "description": "TPU extension: dispatch the attention QKV "
        "projection to the Pallas fused matmul+bias kernel "
        "(ops/pallas_qkv.py) — one kernel against the concatenated, "
        "tp-sharded [in, 3*head] weight, bias folded into the epilogue. "
        "Engages at tensor_parallel_degree 1 directly, and under "
        "tp_overlap: ring inside the ring's partial matmuls; the "
        "GSPMD tp path keeps the einsum (the sharded kernel cannot "
        "enter a plain pallas_call without a gather).",
    },
    "recompute": {
        "type": str,
        "default": "full",
        "options": ["full", "stash_weight", "stash_all", "auto"],
        "description": "TPU extension: memory-budgeted recompute planner "
        "(env alias SMP_RECOMPUTE). 'full' (default): every backward pass "
        "re-runs its chunk forward (activation recomputation; the compiled "
        "program is byte-identical to older builds). 'stash_weight': the "
        "zero-bubble executor's B pass captures per-layer jax.vjp "
        "residuals so the deferred W pass consumes them instead of "
        "re-running the forward — a single forward per microbatch. "
        "'stash_all': residuals are captured at the forward pass itself, "
        "removing the B recompute too (also applies to the "
        "interleaved/1F1B executors). 'auto': the schedule's default "
        "stash (stash_weight on zero_bubble — memory-conservative, its "
        "rings cost only the existing W-queue depth; stash_all on "
        "interleaved/1F1B) budgeted against recompute_budget_mb and "
        "degraded per-(stage, chunk) by the planner "
        "(parallel/remat_plan.py). Non-pipeline paths map the knob onto "
        "jax.checkpoint policies (dots_with_no_batch_dims_saveable "
        "family).",
    },
    "recompute_budget_mb": {
        "type": (int, type(None)),
        "default": None,
        "lower_bound": 0,
        "description": "TPU extension: stash budget in MiB for "
        "recompute: auto (env alias SMP_RECOMPUTE_BUDGET_MB). Unset: the "
        "XLA memory-breakdown temp bytes of the last audited program, "
        "else the planner's own ring bound (stash everything).",
    },
    "_device_count_override": {
        "type": (int, type(None)),
        "default": None,
        "internal": True,
        "description": "TPU extension: build the mesh over this many devices "
        "instead of len(jax.devices()) (testing / dry-run).",
    },
}
