"""Microbatch splitting of step arguments and per-microbatch outputs.

Parity target: reference ``backend/split.py:13-228`` (``TensorSplitter``,
``StepOutput``). Semantics preserved: nested structures are traversed, named
arguments can be exempted (``non_split_inputs``) or split along a custom axis
(``input_split_axes``), and any object may implement the ``smp_slice``
protocol (``smp_slice(num_mb, mb, axis) -> piece``,
reference ``backend/split.py:154-175``).

TPU-native difference: instead of producing a Python list of per-microbatch
slices consumed by a dynamic server loop, splitting *stacks* microbatches
along a new leading axis — ``[B, ...] -> [num_mb, B // num_mb, ...]`` — so
the compiled step can ``lax.scan`` over them. ``StepOutput`` holds the
stacked per-microbatch results and implements the reference reduction API.
"""

import numpy as np

import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.utils.exceptions import MicrobatchError
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


def _is_array(x):
    return isinstance(x, (jnp.ndarray, np.ndarray, jax.Array))


class TensorSplitter:
    def __init__(self, num_microbatches, non_split_inputs=None, input_split_axes=None):
        self.num_microbatches = num_microbatches
        self.non_split_inputs = set(non_split_inputs or [])
        self.input_split_axes = dict(input_split_axes or {})

    def stack_microbatches(self, args, kwargs, arg_names=None):
        """Return (args, kwargs) with every splittable array reshaped to
        [num_mb, B/num_mb, ...] along its split axis.

        `arg_names` gives the positional-parameter names of the user step
        function so `non_split_inputs` / `input_split_axes` can refer to
        positional args by name, as in the reference.
        """
        arg_names = arg_names or []
        new_args = []
        for i, a in enumerate(args):
            name = arg_names[i] if i < len(arg_names) else None
            new_args.append(self._split_value(a, name))
        new_kwargs = {k: self._split_value(v, k) for k, v in kwargs.items()}
        return tuple(new_args), new_kwargs

    def _split_value(self, value, name):
        if name is not None and name in self.non_split_inputs:
            return NonSplit(value)
        axis = self.input_split_axes.get(name, 0)
        return jax.tree_util.tree_map(
            lambda leaf: self._split_leaf(leaf, axis, name),
            value,
            is_leaf=lambda x: hasattr(x, "smp_slice"),
        )

    def _split_leaf(self, leaf, axis, name):
        if hasattr(leaf, "smp_slice"):
            pieces = [
                leaf.smp_slice(self.num_microbatches, mb, axis)
                for mb in range(self.num_microbatches)
            ]
            stacked = jnp.stack([jnp.asarray(p) for p in pieces], axis=0)
            return DeferredSplit(stacked, 0, self.num_microbatches, stacked=True)
        if not _is_array(leaf):
            if self.num_microbatches > 1 and leaf is not None and not isinstance(
                leaf, (bool, int, float, str, bytes)
            ):
                logger.debug("Argument %s of type %s is not splittable; broadcasting.",
                             name, type(leaf).__name__)
            return NonSplit(leaf)
        if leaf.ndim <= axis:
            return NonSplit(leaf)
        dim = leaf.shape[axis]
        if dim % self.num_microbatches != 0:
            raise MicrobatchError(
                f"Axis {axis} of argument '{name}' has size {dim}, not divisible by "
                f"microbatches={self.num_microbatches}."
            )
        # Defer the actual [B, ...] -> [num_mb, B/num_mb, ...] restack to the
        # compiled step program: an eager per-leaf reshape dispatch per step
        # is pure launch overhead on a remote accelerator.
        return DeferredSplit(leaf, axis, self.num_microbatches, stacked=False)


class NonSplit:
    """Marks a value broadcast to all microbatches (not scanned over)."""

    def __init__(self, value):
        self.value = value


def stack_leaf(leaf, axis, num_mb, stacked=False):
    """[B, ...] -> [num_mb, B/num_mb, ...] restack along ``axis``; the single
    implementation shared by eager helpers and the traced step prologue."""
    if stacked:
        return leaf
    leaf = jnp.asarray(leaf)
    mb_dim = leaf.shape[axis] // num_mb
    new_shape = leaf.shape[:axis] + (num_mb, mb_dim) + leaf.shape[axis + 1:]
    return jnp.moveaxis(leaf.reshape(new_shape), axis, 0)


class DeferredSplit:
    """A splittable leaf whose microbatch restack is deferred to trace time.

    ``stack()`` produces the [num_mb, ...] view (called inside the compiled
    program); ``slice(mb)`` eagerly extracts one microbatch (init/trace-time
    helper).
    """

    __slots__ = ("value", "axis", "num_mb", "stacked")

    def __init__(self, value, axis, num_mb, stacked=False):
        self.value = value
        self.axis = axis
        self.num_mb = num_mb
        self.stacked = stacked

    def stack(self, value=None):
        leaf = self.value if value is None else value
        return stack_leaf(leaf, self.axis, self.num_mb, self.stacked)

    def slice(self, mb):
        leaf = jnp.asarray(self.value)
        if self.stacked:
            return leaf[mb]
        mb_dim = leaf.shape[self.axis] // self.num_mb
        start = mb * mb_dim
        return jax.lax.slice_in_dim(leaf, start, start + mb_dim, axis=self.axis)


def microbatch_slice(stacked_tree, mb):
    """Select microbatch `mb` from a stacked/deferred tree (outside-scan
    helper)."""

    def pick(x):
        if isinstance(x, NonSplit):
            return x.value
        if isinstance(x, DeferredSplit):
            return x.slice(mb)
        return x[mb]

    return jax.tree_util.tree_map(
        pick,
        stacked_tree,
        is_leaf=lambda x: isinstance(x, (NonSplit, DeferredSplit)),
    )


class StepOutput:
    """Per-microbatch outputs of an @smp.step function.

    Parity: reference ``backend/split.py:178-228`` — the reference collects a
    Python list of per-microbatch outputs; here outputs arrive stacked along
    a leading [num_mb] axis straight out of the compiled scan.
    """

    def __init__(self, stacked):
        self._stacked = stacked

    @property
    def outputs(self):
        """List of per-microbatch values (reference-compat accessor)."""
        n = jax.tree_util.tree_leaves(self._stacked)[0].shape[0]
        return [jax.tree_util.tree_map(lambda x: x[i], self._stacked) for i in range(n)]

    def reduce_mean(self):
        return jax.tree_util.tree_map(lambda x: jnp.mean(x, axis=0), self._stacked)

    def reduce_sum(self):
        return jax.tree_util.tree_map(lambda x: jnp.sum(x, axis=0), self._stacked)

    def concat(self):
        return jax.tree_util.tree_map(
            lambda x: jnp.reshape(x, (-1,) + x.shape[2:]) if x.ndim >= 2 else x.reshape(-1),
            self._stacked,
        )

    def stack(self):
        return self._stacked

    def __repr__(self):
        shapes = jax.tree_util.tree_map(lambda x: tuple(x.shape), self._stacked)
        return f"StepOutput(num_microbatches-stacked, shapes={shapes})"
