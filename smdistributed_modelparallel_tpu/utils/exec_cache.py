"""Persistent AOT executable cache (``smp.exec_cache``): fingerprint-
verified warm starts + shape bucketing.

The suite is XLA compile-bound (~10-12 s per step-program compile on
XLA:CPU), and since the in-job recovery supervisor landed, compile time
directly bounds availability: every shrink-to-survivors recovery and
every elastic resume pays a full world recompile inside the
``reshard_load``/``first_step`` MTTR phases. The reference SMP ships
pre-built executables to avoid exactly this class of cost (SURVEY §L0);
the pjit/TPUv4 line of work treats compilation as an offline, cacheable
artifact rather than a per-boot tax. This module makes the step engine's
compiled programs that artifact:

**Disk cache.** After each ``lowered.compile()`` the step engine
(``step.py::_make_runner``) serializes the executable with
``jax.experimental.serialize_executable`` into ``SMP_EXEC_CACHE_DIR``,
keyed by the step-cache key hash (generation-stripped, address-scrubbed —
the same digest family as ``hlo_audit.cache_key_hash``) joined with the
topology (pp/tp/rdp, mesh shape, process index/count, platform,
device_kind). The entry's ``meta.json`` additionally records the jax and
jaxlib versions, donation/health/pipeline knobs, the payload's sha256,
and the program's PR-9 X-ray fingerprint. On the next cold start — same
process restart, elastic resume, or supervisor recovery — the engine
deserializes instead of recompiling.

**Verified, not trusted.** A hit is accepted only after (1) the version/
knob facts in ``meta.json`` match the live environment
(``reject_version`` otherwise), (2) the payload hashes clean
(``corrupt`` otherwise — the entry is deleted and the fresh compile
overwrites it), and (3), when the X-ray is enabled, a fresh
``hlo_audit`` of the *deserialized* executable diffs clean against the
entry's stored fingerprint on the semantic subset (config / collectives
/ replication / remat) — ``reject_fingerprint`` otherwise. Verified hits
re-publish the ``smp_hlo_*`` gauges and the flight-recorder compile
event from that audit, so a cache hit never silently bypasses the PR-9
drift gates. ``SMP_HLO_AUDIT=off`` + cache on still works: the audit leg
is skipped and the hit rests on the integrity + version checks.

**Shape bucketing.** ``SMP_SHAPE_BUCKETS`` (e.g.
``"batch:16,32,64;seq:128,256;seq_pad=0"``) makes variable-shaped
batches map onto a small set of cached executables instead of retracing
per shape: the step engine pads the batch dim up to the next bucket
boundary and masks the padding at *microbatch granularity* — padded rows
fill whole trailing microbatches whose gradient/loss contributions are
multiplied by a 0/1 weight vector (a device input, so one executable
serves every occupancy), and the gradient mean divides by the number of
active microbatches. That makes batch bucketing exact, not approximate:
padded-run losses/grads equal the exact-shape run's. Sequence-dim
bucketing right-pads with ``seq_pad`` (default 0); masking those
positions is the model's contract (causal attention + ignore-index
losses are unaffected by appended positions). Bucketed keys land in the
same disk cache.

Everything is **off by default** (``SMP_EXEC_CACHE=off``): the compile
path is byte-identical to a build without this module until the knob is
turned on. ``SMP_EXEC_CACHE_MAX_BYTES`` bounds the cache directory with
LRU eviction (meta-file mtime, touched on every verified hit).

Observability: ``smp_exec_cache_total{result=hit|miss|reject_fingerprint
|reject_version|corrupt}`` counters, a ``source=fresh|disk_cache`` label
on ``smp_step_compile_seconds``, ``smp_exec_cache_entries`` (candidate
entries seen by the last warm-start consult), and a module-level compile
event ledger the recovery supervisor reads to split the ``first_step``
MTTR phase into ``compile_from_cache`` vs ``compile_fresh``.

Import-hygiene contract: importing this module must never initialize an
accelerator backend (jax device queries happen only inside the runtime
entry points).
"""

import hashlib
import json
import os
import pickle
import re
import shutil
import time

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    record_exec_cache,
    telemetry,
)

logger = get_logger()

ENV = "SMP_EXEC_CACHE"
DIR_ENV = "SMP_EXEC_CACHE_DIR"
MAX_BYTES_ENV = "SMP_EXEC_CACHE_MAX_BYTES"
BUCKETS_ENV = "SMP_SHAPE_BUCKETS"

_META_NAME = "meta.json"
_PAYLOAD_NAME = "payload.bin"
_META_VERSION = 1

# Object reprs embed heap addresses ("<... object at 0x7f...>"); the step
# cache key may contain such objects, and the disk key must be stable
# across processes.
_ADDR_RE = re.compile(r"0x[0-9a-f]+")


def enabled():
    """Cache gate: default OFF — ``SMP_EXEC_CACHE=on``/``1`` enables."""
    return os.environ.get(ENV, "off").lower() in ("on", "1", "true")


def cache_dir():
    return os.environ.get(DIR_ENV) or os.path.join(
        os.path.expanduser("~"), ".cache", "smp_exec_cache"
    )


def max_bytes():
    try:
        return int(os.environ.get(MAX_BYTES_ENV, "0") or "0")
    except ValueError:
        return 0


# ----------------------------------------------------------------------
# Keys
# ----------------------------------------------------------------------


def stable_key_hash(key):
    """Digest of a step compile-cache key that survives process restarts:
    heap addresses in object reprs are scrubbed before hashing. Callers
    pass the key WITHOUT its generation component (``key[1:]``) — the
    generation counts re-inits within one process and can never match
    across a restart."""
    return hashlib.sha256(
        _ADDR_RE.sub("0x", repr(tuple(key))).encode()
    ).hexdigest()[:16]


def module_hash(lowered):
    """Content hash of a lowered (pre-optimization) step module. The
    shape-derived disk key cannot see program CONTENT — edited user step
    code, a changed optimizer learning rate (a baked-in constant under
    ``fused_optimizer_step``) — so every load is verified against the
    entry's stored module hash: tracing+lowering always runs, only the
    expensive XLA compile is skipped on a hit. Falls back to None (cache
    bypassed) if the text form is unavailable."""
    try:
        return hashlib.sha256(lowered.as_text().encode()).hexdigest()
    except Exception as e:  # pragma: no cover - backend-specific
        logger.debug("[exec_cache] lowered module text unavailable: %s", e)
        return None


def _env_facts():
    import jax
    import jaxlib

    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__}


def _topology_facts():
    """The placement facts an executable is welded to: degrees, mesh
    shape, process coordinates, platform/device_kind. Part of the entry
    id — executables for different topologies must coexist in one cache
    directory (the elastic/recovery story shrinks worlds)."""
    import jax

    from smdistributed_modelparallel_tpu.backend.state import state

    try:
        cfg = state.cfg
        mesh = state.mesh
    except Exception:  # uninitialized framework (direct/offline callers)
        cfg = mesh = None
    dev = jax.devices()[0]
    return {
        "pp": int(getattr(cfg, "pipeline_parallel_degree", 1) or 1) if cfg else 1,
        "tp": int(getattr(cfg, "tensor_parallel_degree", 1) or 1) if cfg else 1,
        "rdp": int(getattr(cfg, "sharded_data_parallel_degree", 1) or 1)
        if cfg else 1,
        "mesh": [[a, int(s)] for a, s in mesh.shape.items()]
        if mesh is not None else [],
        "devices": len(jax.devices()),
        "process_index": int(jax.process_index()),
        "process_count": int(jax.process_count()),
        "platform": dev.platform,
        "device_kind": str(dev.device_kind),
    }


def _knob_facts():
    """Knobs that change program semantics without necessarily moving the
    step key's shape components; version-checked at load (belt and
    braces — most are also folded into the step key itself)."""
    from smdistributed_modelparallel_tpu.backend.state import state
    from smdistributed_modelparallel_tpu.utils import health

    try:
        cfg = state.cfg
    except Exception:  # uninitialized framework (direct/offline callers)
        cfg = None
    return {
        "pipeline": getattr(cfg, "pipeline", None) if cfg else None,
        "virtual": int(getattr(cfg, "virtual_pipeline_degree", 1) or 1)
        if cfg else 1,
        "microbatches": int(getattr(cfg, "microbatches", 1) or 1) if cfg else 1,
        "fused_optimizer_step": bool(getattr(cfg, "fused_optimizer_step", False))
        if cfg else False,
        "fused_step_donation": bool(getattr(cfg, "fused_step_donation", False))
        if cfg else False,
        "health": health.mode(),
        # ZeRO-3 knobs: mode/bucket/threshold reshape the compiled program
        # (param sharding layout, slice-grad restructuring, reduce-scatter
        # bucket boundaries) at identical input shapes — a knob flip must
        # version-mismatch, never warm-hit a stale executable. Sub-knobs
        # idle under the current mode are canonicalized (0 / "-") so a
        # stray env var never spuriously rejects entries of byte-identical
        # programs; mirrors the step engine's zero_key.
        **_zero_knob_facts(cfg),
        # Recompute-planner knobs, same canonicalization contract: the
        # default mode omits both facts entirely (entries stored before
        # the knob existed keep verifying), and the budget is recorded
        # only under "auto" — the one mode whose program reads it — so a
        # stray SMP_RECOMPUTE_BUDGET_MB never invalidates anything.
        **_recompute_knob_facts(cfg),
        # Overlapped-tp knobs, same contract: defaults omit the facts
        # (pre-knob disk entries keep verifying); a knob flip is a
        # version mismatch, never a warm hit of the other program.
        **_tp_overlap_knob_facts(cfg),
        # Quantization knobs (matmul_precision / SMP_KV_QUANT /
        # SMP_DECODE_WEIGHTS), same contract: bf16/none contribute no
        # facts at all.
        **_quant_knob_facts(cfg),
    }


def _quant_knob_facts(cfg):
    from smdistributed_modelparallel_tpu import quant

    facts = {}
    mode = quant.matmul_precision_mode(cfg)
    if mode != "bf16":
        facts["matmul_precision"] = mode
    if quant.kv_quant_mode() != "none":
        facts["kv_quant"] = quant.kv_quant_mode()
    if quant.decode_weights_mode() != "none":
        facts["decode_weights"] = quant.decode_weights_mode()
    return facts


def _tp_overlap_knob_facts(cfg):
    from smdistributed_modelparallel_tpu.ops.collective_matmul import (
        fused_qkv_effective,
        tp_overlap_mode,
    )

    mode = tp_overlap_mode(cfg)
    fused = fused_qkv_effective(cfg)
    facts = {}
    if mode != "off":
        facts["tp_overlap"] = mode
    if fused:
        facts["fused_qkv"] = True
    return facts


def _recompute_knob_facts(cfg):
    from smdistributed_modelparallel_tpu.parallel import remat_plan

    mode = remat_plan.resolve(cfg)
    if mode == "full":
        return {}
    facts = {"recompute": mode}
    if mode == "auto":
        # Unset (-1) vs explicit 0 are different programs (the planner's
        # fallback budget vs degrade-everything); mirror the step key.
        budget = getattr(cfg, "recompute_budget_mb", None)
        facts["recompute_budget_mb"] = -1 if budget is None else int(budget)
    return facts


def _zero_knob_facts(cfg):
    zero3 = bool(getattr(cfg, "zero3_enabled", False))
    zero2d = bool(getattr(cfg, "zero2d_enabled", False))
    prefetch = "-"
    if zero3:
        from smdistributed_modelparallel_tpu.parallel.zero import (
            prefetch_knob,
        )

        prefetch = prefetch_knob()
    return {
        "sharded_params": getattr(cfg, "sharded_params", "none")
        if cfg else "none",
        "zero3_bucket_mb": int(getattr(cfg, "zero3_bucket_mb", 0) or 0)
        if zero3 else 0,
        "sdp_param_persistence_threshold": int(
            getattr(cfg, "sdp_param_persistence_threshold", 0) or 0
        ) if (zero3 or zero2d) else 0,
        "zero3_prefetch": prefetch,
    }


def _entry_dir(name, key_hash, topo):
    ident = hashlib.sha256(
        json.dumps(
            {"name": name, "key": key_hash, "topology": topo},
            sort_keys=True,
        ).encode()
    ).hexdigest()[:24]
    return os.path.join(cache_dir(), f"{name}-{ident}")


# ----------------------------------------------------------------------
# Load / store
# ----------------------------------------------------------------------


def _delete_entry(path):
    try:
        shutil.rmtree(path)
    except OSError:
        pass


def load(name, key_hash, module_sha=None, params=None,
         expected_param_shardings=None, extra_findings_fn=None,
         tp_ring_expected=None):
    """Deserialize a cached step executable, or None.

    Returns ``(compiled, audit)``; ``audit`` is the fresh post-load X-ray
    of the deserialized executable when the audit pass is enabled (its
    gauges/flight event are already re-published), else None. Every
    outcome lands in ``smp_exec_cache_total{result=}``.
    """
    if module_sha is None:
        # Without a lowered-module hash a hit cannot be content-verified;
        # treat the lookup as a miss rather than trust blindly.
        record_exec_cache("miss")
        return None, None
    path = _entry_dir(name, key_hash, _topology_facts())
    meta_path = os.path.join(path, _META_NAME)
    payload_path = os.path.join(path, _PAYLOAD_NAME)
    if not os.path.exists(meta_path) or not os.path.exists(payload_path):
        record_exec_cache("miss")
        return None, None
    try:
        with open(meta_path, encoding="utf-8") as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        logger.warning("[exec_cache] %s: unreadable meta (%s); evicting.",
                       name, e)
        _delete_entry(path)
        record_exec_cache("corrupt")
        return None, None
    skew = _version_skew(meta)
    if skew:
        logger.info("[exec_cache] %s: entry rejected (version skew: %s); "
                    "recompiling.", name, skew)
        record_exec_cache("reject_version")
        return None, None
    if meta.get("module_sha") != module_sha:
        logger.warning(
            "[exec_cache] %s: entry's lowered-module hash differs from "
            "the live program (changed step code / optimizer constants?); "
            "recompiling.", name,
        )
        record_exec_cache("reject_fingerprint")
        return None, None
    t0 = time.perf_counter()
    try:
        with open(payload_path, "rb") as f:
            raw = f.read()
        if hashlib.sha256(raw).hexdigest() != meta.get("payload_sha256"):
            raise ValueError("payload sha256 mismatch")
        payload, in_tree, out_tree = pickle.loads(raw)
        from jax.experimental import serialize_executable

        compiled = serialize_executable.deserialize_and_load(
            payload, in_tree, out_tree
        )
    except Exception as e:  # corrupt/truncated/undeserializable entry
        logger.warning(
            "[exec_cache] %s: corrupt cache entry (%s); evicting and "
            "recompiling.", name, e,
        )
        _delete_entry(path)
        record_exec_cache("corrupt")
        return None, None
    audit = _verify_and_republish(
        name, key_hash, compiled, meta, params, expected_param_shardings,
        t0, extra_findings_fn=extra_findings_fn,
        tp_ring_expected=tp_ring_expected,
    )
    if audit is False:  # fingerprint veto
        record_exec_cache("reject_fingerprint")
        return None, None
    try:  # LRU clock: verified hits refresh the entry's eviction rank
        os.utime(meta_path, None)
    except OSError:
        pass
    dt = time.perf_counter() - t0
    record_exec_cache("hit", seconds=dt)
    logger.info(
        "[exec_cache] %s: warm start from %s in %.3fs (saved compile "
        "measured at %.1fs).", name, path, dt,
        meta.get("compile_seconds", 0.0) or 0.0,
    )
    return compiled, (audit or None)


def _version_skew(meta):
    """Human-readable mismatch description, or None when the entry's
    environment facts match the live process."""
    env = _env_facts()
    for k, v in env.items():
        if meta.get("env", {}).get(k) != v:
            return f"{k}: {meta.get('env', {}).get(k)} != {v}"
    knobs = _knob_facts()
    stored = meta.get("knobs", {})
    for k, v in knobs.items():
        if stored.get(k) != v:
            return f"knob {k}: {stored.get(k)} != {v}"
    if meta.get("version") != _META_VERSION:
        return f"entry format {meta.get('version')} != {_META_VERSION}"
    return None


def _verify_and_republish(name, key_hash, compiled, meta, params,
                          expected_param_shardings, t0,
                          extra_findings_fn=None, tp_ring_expected=None):
    """X-ray the deserialized executable and diff it against the entry's
    stored fingerprint. Returns the fresh audit on success (gauges +
    flight event re-published — cache hits do not bypass the PR-9
    gates), ``None`` when the audit pass is disabled, and ``False`` on a
    semantic mismatch (the caller rejects the hit)."""
    from smdistributed_modelparallel_tpu.utils import hlo_audit

    if not hlo_audit.enabled():
        return None
    stored_fp = meta.get("audit")
    try:
        fresh = hlo_audit.audit_compiled(
            name, compiled, key=key_hash, params=params,
            expected_param_shardings=expected_param_shardings,
            publish=False, persist=False,
            extra_findings_fn=extra_findings_fn,
            tp_ring_expected=tp_ring_expected,
        )
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("[exec_cache] %s: post-load audit failed (%s); "
                       "rejecting the cached executable.", name, e)
        return False
    if stored_fp:
        changes = hlo_audit.diff(
            stored_fp, fresh.fingerprint, fields=hlo_audit.SEMANTIC_FIELDS
        )
        if changes:
            logger.warning(
                "[exec_cache] %s: cached executable's fingerprint drifted "
                "from the entry's stored audit (%s); recompiling.",
                name, changes,
            )
            return False
    hlo_audit.republish(fresh, seconds=time.perf_counter() - t0)
    return fresh


def aot_compile(name, key_src, lowered, params=None,
                extra_findings_fn=None, tp_ring_expected=None):
    """Compile a lowered program through the full warm-start sequence the
    step engine runs — consult the disk cache (content-verified by the
    lowered-module hash, fingerprint-diffed on hit), else
    ``lowered.compile()`` + X-ray audit + store — packaged for other
    program owners (the serving engine's prefill/decode programs).

    ``key_src`` is any repr-stable tuple identifying the program family
    (shapes, knobs, topology facts the caller deems key-worthy); the
    topology itself is folded in by the entry path as usual. Returns
    ``(compiled, audit, source)`` with ``source`` in
    {"fresh", "disk_cache"}; the compile event lands in the module
    ledger either way (the supervisor's MTTR split reads it).
    """
    from smdistributed_modelparallel_tpu.utils import hlo_audit

    key_hash = stable_key_hash(key_src)
    compiled = None
    audit = None
    source = "fresh"
    module_sha = None
    t0 = time.perf_counter()
    if enabled():
        module_sha = module_hash(lowered)
        compiled, audit = load(
            name, key_hash, module_sha=module_sha, params=params,
            extra_findings_fn=extra_findings_fn,
            tp_ring_expected=tp_ring_expected,
        )
        if compiled is not None:
            source = "disk_cache"
    if compiled is None:
        compiled = lowered.compile()
        audit = hlo_audit.maybe_audit(
            name, compiled, key=key_hash, params=params,
            extra_findings_fn=extra_findings_fn,
            tp_ring_expected=tp_ring_expected,
        )
        if enabled():
            store(
                name, key_hash, compiled, module_sha=module_sha,
                audit=audit, compile_seconds=time.perf_counter() - t0,
            )
    record_compile_event(name, source, time.perf_counter() - t0)
    return compiled, audit, source


def store(name, key_hash, compiled, module_sha=None, audit=None,
          compile_seconds=None):
    """Serialize one compiled step executable into the cache. Failures
    are logged, never raised into the step path. Returns the entry dir
    or None."""
    try:
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        raw = pickle.dumps((payload, in_tree, out_tree))
    except Exception as e:
        logger.warning("[exec_cache] %s: executable not serializable on "
                       "this backend (%s); entry not written.", name, e)
        return None
    topo = _topology_facts()
    path = _entry_dir(name, key_hash, topo)
    meta = {
        "version": _META_VERSION,
        "name": name,
        "key": key_hash,
        "created_unix": time.time(),
        "env": _env_facts(),
        "topology": topo,
        "knobs": _knob_facts(),
        "payload_sha256": hashlib.sha256(raw).hexdigest(),
        "payload_bytes": len(raw),
        "module_sha": module_sha,
        "compile_seconds": compile_seconds,
        "audit": audit.fingerprint if audit is not None else None,
    }
    try:
        os.makedirs(path, exist_ok=True)
        tmp = os.path.join(path, _PAYLOAD_NAME + ".tmp")
        with open(tmp, "wb") as f:
            f.write(raw)
        os.replace(tmp, os.path.join(path, _PAYLOAD_NAME))
        tmp = os.path.join(path, _META_NAME + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(meta, f, indent=1, default=str)
        os.replace(tmp, os.path.join(path, _META_NAME))
    except OSError as e:
        logger.warning("[exec_cache] %s: cache write failed (%s).", name, e)
        return None
    logger.info("[exec_cache] %s: stored %d-byte executable at %s.",
                name, len(raw), path)
    _evict_lru(keep=path)
    return path


def _entries():
    """[(entry_dir, meta_mtime, total_bytes)] for every cache entry."""
    root = cache_dir()
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for n in names:
        path = os.path.join(root, n)
        meta = os.path.join(path, _META_NAME)
        if not os.path.isdir(path) or not os.path.exists(meta):
            continue
        size = 0
        try:
            mtime = os.path.getmtime(meta)
            for f in os.listdir(path):
                size += os.path.getsize(os.path.join(path, f))
        except OSError:
            continue
        out.append((path, mtime, size))
    return out


def _evict_lru(keep=None):
    """Drop least-recently-used entries until the directory fits
    ``SMP_EXEC_CACHE_MAX_BYTES`` (0 = unbounded). The entry named by
    ``keep`` (normally the one just written) is evicted last."""
    cap = max_bytes()
    if cap <= 0:
        return
    entries = sorted(_entries(), key=lambda e: (e[0] == keep, e[1]))
    total = sum(e[2] for e in entries)
    for path, _, size in entries:
        if total <= cap:
            break
        if path == keep and len(entries) > 1:
            continue
        _delete_entry(path)
        total -= size
        logger.info("[exec_cache] LRU-evicted %s (%d bytes; cap %d).",
                    path, size, cap)


def note_warm_start(what):
    """Recovery/elastic-resume consult hook: count the candidate entries
    in the cache directory so the availability story is measured before
    the first step compiles. One gauge + one flight-recorder event; a
    disabled cache records nothing and returns 0."""
    if not enabled():
        return 0
    n = len(_entries())
    telemetry.gauge(
        "smp_exec_cache_entries",
        "executable-cache entries present at the last warm-start consult",
    ).set(n)
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )

    flight_recorder.record_compile("exec_cache_consult", what, 0.0)
    logger.info(
        "[exec_cache] %s: consulting %s before first_step — %d cached "
        "executable(s) available.", what, cache_dir(), n,
    )
    return n


# ----------------------------------------------------------------------
# Compile-event ledger (read by the recovery supervisor to split the
# first_step MTTR phase into compile_from_cache vs compile_fresh)
# ----------------------------------------------------------------------

compile_events = []


def record_compile_event(name, source, seconds):
    compile_events.append(
        {"name": name, "source": source, "seconds": float(seconds),
         "t": time.monotonic()}
    )
    # The goodput ledger attributes compile phases compile_fresh
    # tentatively (the source is only known here, once the load/compile
    # resolved): a disk_cache event moves its seconds to compile_cache.
    try:
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        goodput.note_compile(source, seconds)
    except Exception:
        pass


def compile_event_mark():
    return len(compile_events)


def compile_events_since(mark):
    return compile_events[int(mark):]


# ----------------------------------------------------------------------
# Shape bucketing policy
# ----------------------------------------------------------------------

_policy_cache = {}


def bucket_policy():
    """Parse ``SMP_SHAPE_BUCKETS`` into ``{"batch": [...], "seq": [...],
    "seq_pad": int}`` (ascending, deduped), or None when unset/empty.
    Malformed specs log once and disable bucketing rather than raise."""
    spec = os.environ.get(BUCKETS_ENV, "").strip()
    if not spec:
        return None
    cached = _policy_cache.get(spec)
    if cached is not None:
        return cached or None
    policy = {"seq_pad": 0}
    try:
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seq_pad="):
                policy["seq_pad"] = int(part.split("=", 1)[1])
                continue
            dim, _, vals = part.partition(":")
            dim = dim.strip()
            if dim not in ("batch", "seq") or not vals:
                raise ValueError(f"unknown bucket spec part {part!r}")
            sizes = sorted({int(v) for v in vals.split(",") if v.strip()})
            if not sizes or any(s <= 0 for s in sizes):
                raise ValueError(f"bad bucket sizes in {part!r}")
            policy.setdefault(dim, [])
            policy[dim] = sorted(set(policy[dim]) | set(sizes))
    except (ValueError, TypeError) as e:
        logger.warning(
            "[exec_cache] malformed %s=%r (%s); shape bucketing disabled.",
            BUCKETS_ENV, spec, e,
        )
        _policy_cache[spec] = False
        return None
    if "batch" not in policy and "seq" not in policy:
        _policy_cache[spec] = False
        return None
    _policy_cache[spec] = policy
    return policy


def bucket_for(n, sizes):
    """Smallest bucket >= n, or None (n exceeds every bucket -> compile
    exact)."""
    for s in sizes:
        if s >= int(n):
            return int(s)
    return None


def record_bucket(result):
    telemetry.counter(
        "smp_shape_bucket_total",
        "shape-bucketing decisions by outcome "
        "(exact / padded / unbucketable)",
    ).labels(result=result).inc()
