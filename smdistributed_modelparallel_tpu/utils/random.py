"""RNG management.

Parity target: reference ``torch/random.py:8-34`` (``RngManager`` with
``consistent_rng_state`` across tp_ranks) and the RNG fork contexts of
``torch/state_mod.py:354-397``. JAX PRNG keys are explicit and splittable,
which makes the reference's state save/restore dance unnecessary: we keep a
named-stream key tree and fold axis indices in where per-rank divergence is
wanted.
"""

import zlib

import jax
import jax.numpy as jnp


def _stream_id(stream):
    # Stable across processes (Python's hash() is salted per process and
    # would desynchronize multi-host key derivation).
    return zlib.crc32(str(stream).encode())


class RngManager:
    def __init__(self, tensor_parallel_seed=0):
        self.base_seed = int(tensor_parallel_seed)
        self._root = jax.random.key(self.base_seed)
        self._counters = {}

    def next_key(self, stream="default"):
        """A fresh key on a named stream; identical across all callers with
        the same call history (the reference's 'consistent RNG across
        tp_ranks' — in SPMD, sameness is automatic because there is one
        trace)."""
        count = self._counters.get(stream, 0)
        self._counters[stream] = count + 1
        return jax.random.fold_in(jax.random.fold_in(self._root, _stream_id(stream)), count)

    def per_rank_key(self, stream, axis_name):
        """A key that differs along a mesh axis, for use inside shard_map
        (e.g. dropout under tensor parallelism)."""
        return jax.random.fold_in(self.next_key(stream), jax.lax.axis_index(axis_name))

    def init_rngs(self, streams=("params", "dropout")):
        return {s: self.next_key(s) for s in streams}

    def reset(self):
        self._counters.clear()


def dropout_keys_consistent(key, shape):
    """Helper for TP modules: dropout mask identical across tp ranks (weights
    are sharded, activations replicated on the sharded dim)."""
    return jax.random.bernoulli(key, shape=shape)
