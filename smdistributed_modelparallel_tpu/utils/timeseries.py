"""Serving metrics time-series + SLO evaluation (the autoscaler feed).

The PR-14 serving gauges are instantaneous (queue depth, occupancy) or
lifetime (request/token counters, latency means) — neither is what a
control loop wants. ``MetricsTimeSeries`` snapshots the registry at a
fixed interval (``SMP_TIMESERIES_INTERVAL`` seconds; unset/0 disables
the subsystem entirely — no ring, no thread) and turns each window into
one bounded record:

- counter DELTAS over the window (requests admitted/finished, tokens),
  and windowed rates (req/s, tok/s, tok/s/chip) — a burst shows up at
  its real rate instead of being averaged into idle history the way the
  old lifetime rates were;
- WINDOW latency percentiles: the streaming log-bucketed histograms in
  ``utils/telemetry.py`` are cumulative, so subtracting the previous
  window's bucket counts yields the distribution of just this window —
  fixed memory, no per-sample storage;
- the SLO verdict: ``SMP_SLO="ttft_p99_ms=500,itl_p99_ms=50,
  queue_depth=8"`` is evaluated against each window; violations bump
  ``smp_slo_violations_total{slo=...}`` and the running goodput fraction
  (windows with zero violations / windows) lands in
  ``smp_slo_goodput_fraction``.

Windows live in a bounded ring (``SMP_TIMESERIES_SIZE``) and are
appended live as JSONL when ``SMP_TIMESERIES_PATH`` is set (rank-
qualified like every other dump) — the exact stream
``scripts/slo_report.py`` reads and ``--check`` gates on.

Sampling is driven two ways at once: the engine polls
``maybe_sample()`` from its tick path (sharp window edges while busy)
and a daemon thread covers idle gaps — both go through one lock and the
interval gate, so a window is taken exactly once. Everything here is
host-side registry arithmetic: no jax import, no device sync.
"""

import collections
import json
import os
import threading
import time

from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPValidationError,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    SERVE_LATENCY_KINDS,
    quantile_from_counts,
    telemetry,
)

logger = get_logger()

TIMESERIES_INTERVAL_ENV = "SMP_TIMESERIES_INTERVAL"
TIMESERIES_PATH_ENV = "SMP_TIMESERIES_PATH"
TIMESERIES_SIZE_ENV = "SMP_TIMESERIES_SIZE"
SLO_ENV = "SMP_SLO"

DEFAULT_SIZE = 512

#: Keys an SMP_SLO spec may bound. ``*_ms`` keys and ``queue_depth`` are
#: upper bounds on the matching window field; ``*_min`` keys are lower
#: bounds (throughput floors).
SLO_KEYS = tuple(
    f"{kind}_{stat}_ms"
    for kind in SERVE_LATENCY_KINDS
    for stat in ("p50", "p90", "p99", "mean")
) + ("queue_depth", "tokens_per_s_min", "requests_per_s_min")


def timeseries_interval():
    """Window length in seconds; 0.0 means the subsystem is disabled."""
    raw = os.environ.get(TIMESERIES_INTERVAL_ENV, "")
    if not raw:
        return 0.0
    try:
        return max(float(raw), 0.0)
    except ValueError:
        logger.warning(
            "invalid %s=%r (want seconds); time-series disabled.",
            TIMESERIES_INTERVAL_ENV, raw,
        )
        return 0.0


def _env_size():
    raw = os.environ.get(TIMESERIES_SIZE_ENV, "")
    if not raw:
        return DEFAULT_SIZE
    try:
        return max(int(raw), 1)
    except ValueError:
        logger.warning(
            "invalid %s=%r (want an integer); using default %d.",
            TIMESERIES_SIZE_ENV, raw, DEFAULT_SIZE,
        )
        return DEFAULT_SIZE


def parse_slo(spec):
    """Parse an ``SMP_SLO`` spec ("ttft_p99_ms=500,itl_p99_ms=50,
    queue_depth=8") into ``{key: threshold}``. Unknown keys raise — a
    typo'd SLO that silently never violates is worse than failing
    fast."""
    out = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, raw = part.partition("=")
        key = key.strip()
        if not sep:
            raise SMPValidationError(
                f"SLO term {part!r} lacks '=<threshold>'."
            )
        if key not in SLO_KEYS:
            raise SMPValidationError(
                f"unknown SLO key {key!r}; supported keys: "
                f"{', '.join(SLO_KEYS)}."
            )
        try:
            out[key] = float(raw)
        except ValueError:
            raise SMPValidationError(
                f"SLO threshold {raw!r} for {key!r} is not a number."
            )
    return out


def evaluate_slo(slo, window):
    """Evaluate one parsed SLO spec against one window record. A key the
    window has no value for (no samples of that kind this window) is NOT
    a violation — an idle window meets every latency SLO."""
    violations = {}
    for key in sorted(slo):
        limit = slo[key]
        if key.endswith("_min"):
            value = window.get(key[: -len("_min")])
            bad = value is not None and value < limit
        else:
            value = window.get(key)
            bad = value is not None and value > limit
        if bad:
            violations[key] = {"limit": limit, "value": value}
    return {"ok": not violations, "violations": violations}


def _goodput_block():
    """The armed goodput ledger's per-window fold, or None. Lazy lookup:
    the ledger is optional and this module must not construct it."""
    try:
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        return goodput.window_block()
    except Exception:
        return None


def _trigger_forensics(reason, detail):
    try:
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        goodput.trigger_forensics(reason, detail=detail)
    except Exception:
        logger.warning("forensics trigger (%s) failed", reason,
                       exc_info=True)


class MetricsTimeSeries:
    """Bounded fixed-interval snapshotter of the serving metrics."""

    THREAD_NAME = "smp-timeseries"

    def __init__(self, registry=None, interval=None, size=None, path=None,
                 slo=None, chips=1, clock=None, wall=None):
        self.registry = registry if registry is not None else telemetry
        self.interval = (
            timeseries_interval() if interval is None
            else max(float(interval), 0.0)
        )
        self.enabled = self.interval > 0.0
        self.size = _env_size() if size is None else max(int(size), 1)
        self.path = (
            os.environ.get(TIMESERIES_PATH_ENV) if path is None else path
        ) or None
        if slo is None:
            raw = os.environ.get(SLO_ENV, "")
            try:
                self.slo = parse_slo(raw) if raw else {}
            except SMPValidationError as e:
                logger.warning("ignoring invalid %s: %s", SLO_ENV, e)
                self.slo = {}
        elif isinstance(slo, str):
            self.slo = parse_slo(slo)
        else:
            self.slo = dict(slo)
        self.chips = max(int(chips), 1)
        self._clock = clock or time.monotonic
        self._wall = wall or time.time
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=self.size)
        self._seq = 0
        self._ok_windows = 0
        self._slo_streak = 0
        self._t_start = self._clock()
        self._last_sample = self._t_start
        self._prev = self._read() if self.enabled else None
        self._stop_event = threading.Event()
        self._thread = None

    @classmethod
    def from_env(cls, registry=None, chips=1):
        """The env-configured snapshotter, or None when
        ``SMP_TIMESERIES_INTERVAL`` is unset/0 — in which case NOTHING is
        constructed: no ring, no baseline snapshot, no thread."""
        if timeseries_interval() <= 0.0:
            return None
        return cls(registry=registry, chips=chips)

    # -- registry reading ----------------------------------------------

    def _read(self):
        """One raw cumulative snapshot of the serving metrics (the
        subtrahend for the next window's deltas)."""
        metrics = self.registry.report().get("metrics", {})

        def series(name):
            fam = metrics.get(name)
            return fam["series"] if fam else []

        def value(name, **labels):
            for s in series(name):
                if all(s["labels"].get(k) == v for k, v in labels.items()):
                    return float(s.get("value", 0.0))
            return 0.0

        hists = {}
        for s in series("smp_serve_latency_seconds"):
            kind = s["labels"].get("kind")
            if kind:
                hists[kind] = (
                    list(s.get("buckets") or ()),
                    list(s.get("counts") or ()),
                    float(s.get("sum", 0.0)),
                    int(s.get("count", 0)),
                )
        return {
            "requests": {
                ev: value("smp_serve_requests_total", event=ev)
                for ev in ("admitted", "finished", "readmitted")
            },
            "tokens": {
                k: value("smp_serve_tokens_total", kind=k)
                for k in ("generated", "prompt")
            },
            "queue_depth": value("smp_serve_queue_depth"),
            "active_slots": value("smp_serve_slots", state="active"),
            "kv_used": value("smp_serve_kv_blocks", state="used"),
            "hists": hists,
        }

    # -- sampling -------------------------------------------------------

    def maybe_sample(self, now=None):
        """Take a window snapshot iff at least one interval has elapsed
        since the last one. Safe to call from the engine tick loop and
        the snapshotter thread concurrently."""
        if not self.enabled:
            return None
        now = self._clock() if now is None else now
        with self._lock:
            if now - self._last_sample < self.interval:
                return None
            return self._sample_locked(now)

    def sample(self, now=None):
        """Take one window snapshot unconditionally (end-of-run flushes
        and the fake-clock tests drive this directly)."""
        if not self.enabled:
            return None
        now = self._clock() if now is None else now
        with self._lock:
            return self._sample_locked(now)

    def _sample_locked(self, now):
        raw = self._read()
        prev = self._prev
        dt = max(now - self._last_sample, 1e-9)
        elapsed = max(now - self._t_start, 1e-9)
        self._seq += 1
        d_req = {
            k: raw["requests"][k] - prev["requests"].get(k, 0.0)
            for k in raw["requests"]
        }
        d_tok = {
            k: raw["tokens"][k] - prev["tokens"].get(k, 0.0)
            for k in raw["tokens"]
        }
        window = {
            "kind": "serve_window",
            "seq": self._seq,
            "t_wall": self._wall(),
            "window_s": dt,
            "queue_depth": raw["queue_depth"],
            "active_slots": raw["active_slots"],
            "kv_used_blocks": raw["kv_used"],
            "requests_admitted": d_req["admitted"],
            "requests_finished": d_req["finished"],
            "requests_readmitted": d_req["readmitted"],
            "tokens_generated": d_tok["generated"],
            "tokens_prompt": d_tok["prompt"],
            "requests_per_s": d_req["finished"] / dt,
            "tokens_per_s": d_tok["generated"] / dt,
            "tokens_per_s_chip": d_tok["generated"] / dt / self.chips,
            # Lifetime figures ride along so one JSONL line is enough to
            # see windowed-vs-lifetime divergence on a bursty trace.
            "lifetime_tokens_generated": raw["tokens"]["generated"],
            "lifetime_tokens_per_s": raw["tokens"]["generated"] / elapsed,
        }
        for kind, (buckets, counts, hsum, hcount) in raw["hists"].items():
            pb = prev["hists"].get(kind)
            if pb is not None and pb[0] == buckets:
                dcounts = [a - b for a, b in zip(counts, pb[1])]
                dsum, dn = hsum - pb[2], hcount - pb[3]
            else:
                dcounts, dsum, dn = counts, hsum, hcount
            if dn <= 0:
                continue
            window[f"{kind}_mean_ms"] = 1e3 * dsum / dn
            for stat, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
                est = quantile_from_counts(buckets, dcounts, q)
                if est is not None:
                    window[f"{kind}_{stat}_ms"] = 1e3 * est
        # Satellite fix: the throughput gauges are now WINDOWED — the old
        # engine-lifetime averages decayed toward idle history and could
        # never show a burst. Lifetime totals remain as counters.
        self.registry.gauge(
            "smp_serve_requests_per_sec",
            "completed requests per second over the last time-series "
            "window",
        ).set(window["requests_per_s"])
        g_tok = self.registry.gauge(
            "smp_serve_tokens_per_sec",
            "generated tokens per second over the last time-series window",
        )
        g_tok.labels(scope="engine").set(window["tokens_per_s"])
        g_tok.labels(scope="chip").set(window["tokens_per_s_chip"])
        self.registry.gauge(
            "smp_timeseries_windows", "time-series window snapshots taken"
        ).set(self._seq)
        if self.slo:
            verdict = evaluate_slo(self.slo, window)
            if verdict["ok"]:
                self._ok_windows += 1
            verdict["goodput"] = self._ok_windows / self._seq
            for key in verdict["violations"]:
                self.registry.counter(
                    "smp_slo_violations_total",
                    "SLO violations by key (one per violating "
                    "time-series window)",
                ).labels(slo=key).inc()
            self.registry.gauge(
                "smp_slo_goodput_fraction",
                "fraction of time-series windows with zero SLO violations",
            ).set(verdict["goodput"])
            self.registry.gauge(
                "smp_slo_ok", "1 when the last window met every SLO"
            ).set(1.0 if verdict["ok"] else 0.0)
            window["slo"] = verdict
            # An SLO violation STREAK (not one bad window) is an
            # anomaly worth evidence: three consecutive violating
            # windows trigger one auto-forensics bundle (rate-limited
            # by the engine's own cooldown; no-op when disarmed).
            if verdict["ok"]:
                self._slo_streak = 0
            else:
                self._slo_streak += 1
                if self._slo_streak == 3:
                    _trigger_forensics(
                        "slo_streak",
                        f"3 consecutive violating windows: "
                        f"{sorted(verdict['violations'])}",
                    )
        gp = _goodput_block()
        if gp is not None:
            # Fold the wall-clock attribution into the window so one
            # JSONL line answers both "is serving meeting its SLO" and
            # "where did this rank's seconds go".
            window["train_goodput"] = gp["fraction"]
            if gp["badput"]:
                window["badput_seconds"] = gp["badput"]
        self._ring.append(window)
        self._append_jsonl(window)
        self._prev = raw
        self._last_sample = now
        return window

    def _append_jsonl(self, window):
        if not self.path:
            return
        path = self.registry._rank_path(self.path)
        try:
            with open(path, "a") as f:
                f.write(json.dumps(window) + "\n")
        except OSError as e:
            logger.warning(
                "time-series append to %s failed (%s); disabling the "
                "JSONL feed.", path, e,
            )
            self.path = None

    def snapshots(self):
        """The in-memory ring, oldest first."""
        with self._lock:
            return list(self._ring)

    # -- background thread ---------------------------------------------

    def start(self):
        """Start the idle-gap snapshotter thread. No-op when disabled
        (``SMP_TIMESERIES_INTERVAL=0`` must not cost a thread) or already
        running."""
        if not self.enabled or self._thread is not None:
            return None
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.THREAD_NAME, daemon=True
        )
        self._thread.start()
        return self._thread

    def stop(self):
        """Stop the snapshotter thread. Idempotent."""
        thread = self._thread
        if thread is None:
            return
        self._stop_event.set()
        thread.join(timeout=5.0)
        self._thread = None

    def _loop(self):
        while not self._stop_event.wait(self.interval):
            try:
                self.maybe_sample()
            except Exception:  # pragma: no cover - must not die
                logger.exception("time-series sample failed")
