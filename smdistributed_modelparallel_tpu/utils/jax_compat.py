"""Version compatibility shims over the moving jax API surface.

The package targets the modern spelling of jax APIs; this module maps
them onto what the installed jax actually provides. Current shims:

- ``shard_map``: the call sites (``ops/context_parallel.py``,
  ``ops/pallas_ce.py``) use the ``jax.shard_map`` surface (jax >= 0.5):
  ``axis_names=`` names the MANUALLY-mapped mesh axes and ``check_vma=``
  toggles the varying-mesh-axes check. jax 0.4.x only has
  ``jax.experimental.shard_map.shard_map`` (``auto=`` names the
  complement set, ``check_rep=`` the flag). The obvious translation
  ``auto = mesh.axis_names - axis_names`` was verified NOT to work on
  the installed jax 0.4.37: a partial-auto region whose body contains
  collectives (ppermute/psum/axis_index) either fails SPMD partitioning
  ("PartitionId instruction is not supported") or hard-aborts XLA:CPU
  (``spmd_partitioner.cc CHECK target.IsManualSubgroup() ==
  sharding().IsManualSubgroup()``). The old-jax fallback therefore goes
  FULL manual (``auto=frozenset()``): axes the caller left automatic
  become manual-with-replicated-data (their dims are simply absent from
  the in/out specs), which is semantically equivalent — inputs sharded
  over those axes outside the region are gathered at region entry — at
  the cost of replicated compute over those axes on multi-axis meshes.
  jax >= 0.5 gets true partial-auto behavior back automatically.

- ``ensure_optimization_barrier_rules``: jax 0.4.x ships
  ``lax.optimization_barrier`` without batching (or differentiation)
  rules, so a barrier inside a ``vmap``-ed region raises
  NotImplementedError. The ZeRO-3 prefetch scan (``parallel/zero.py``)
  issues its next-layer gather behind a barrier inside the vmapped
  per-rdp-slice forward; the shim registers the trivially-correct
  identity batching rule (the barrier is semantically the identity on
  every operand). Differentiation stays unimplemented — callers wrap the
  barrier in a ``custom_vjp`` identity instead, which keeps the
  scheduling constraint out of the transpose program where it would pin
  the wrong ordering.

Keep this module import-light (jax only): it is imported at ops-module
import time, which the import-hygiene test requires to not initialize
any accelerator backend.
"""

import jax


def shard_map(f, mesh=None, in_specs=None, out_specs=None, axis_names=None,
              check_vma=True):
    """``jax.shard_map``-compatible wrapper that also runs on jax 0.4.x.

    Args follow the new-style surface: ``axis_names`` is the set of mesh
    axis names the body is manual over (None = all of them), ``check_vma``
    the varying-axes check. On new jax this forwards directly; on old jax
    it translates to ``auto=``/``check_rep=``.
    """
    if hasattr(jax, "shard_map"):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kw,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    # Full manual on old jax — see module docstring for why NOT
    # auto=mesh.axis_names - axis_names (it crashes 0.4.37's partitioner
    # as soon as the body contains a collective).
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check_vma), auto=frozenset(),
    )


def ensure_optimization_barrier_rules():
    """Register the identity batching rule for ``optimization_barrier``
    when the installed jax lacks one (jax < 0.5). Idempotent; never
    overrides a rule jax itself provides."""
    try:
        from jax._src.lax.lax import optimization_barrier_p
        from jax.interpreters import batching
    except ImportError:  # pragma: no cover - future jax reorganizations
        return False
    if optimization_barrier_p in batching.primitive_batchers:
        return True

    def _barrier_batcher(args, dims):
        return optimization_barrier_p.bind(*args), list(dims)

    batching.primitive_batchers[optimization_barrier_p] = _barrier_batcher
    return True
