"""Shared utilities: logging, exceptions, RNG, timeline."""
