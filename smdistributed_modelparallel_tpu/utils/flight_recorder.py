"""Always-on flight recorder: a bounded ring buffer of structured events.

Parity target: the reference's per-action timeline (``smp_timeline_*``
around every server action, SURVEY §2.1 N5) answers "what was this rank
doing" — but only when a timeline file was requested up front. Production
post-mortems need that answer for runs that were NOT being traced: when a
64-chip job wedges or crashes, the operator wants the last ~N things each
rank did (which collective, which schedule slot, which compile phase)
without having paid tracing overhead for the hours before.

This module is that black box. Design constraints, in priority order:

- **always on at near-zero cost**: recording is one ``time.perf_counter``
  call plus one bounded-``deque`` append of a plain tuple. No dict build,
  no string formatting, no lock on the hot path (``deque.append`` is
  atomic under CPython; the only lock guards the per-group collective
  sequence counters). Formatting happens once, at dump time.
- **bounded**: the ring holds ``SMP_FLIGHT_RECORDER_SIZE`` events
  (default 1024; ``0`` disables recording entirely — the record methods
  return before touching the clock).
- **diagnosable desyncs**: every collective event carries a per-group
  monotonic sequence number. Two ranks' rings can be diffed seq-by-seq:
  if rank 0's seq 17 on WORLD is a broadcast and rank 3's is a barrier,
  the collective streams diverged at 17 — the classic silent-hang cause
  the reference could only show as a stack dump.
- **clock-anchored**: the ring records monotonic microseconds since an
  anchor captured at construction together with the wall-clock time of
  that anchor, so ``scripts/trace_fuse.py`` can align rings (and
  timelines) from different ranks on one axis, refined by barrier sync
  marks.

Dump paths: ``dump()`` writes JSONL (one meta line, then one line per
event, oldest first) to ``SMP_FLIGHT_RECORDER_PATH`` (rank-qualified via
the telemetry registry's ``_rank_path``), automatically at exit; the
watchdog embeds ``snapshot()`` in every stall dump (see
``utils/telemetry.py``); ``smp.flight_recorder`` is the live handle.

Import-hygiene contract: stdlib + the package logger/telemetry only —
importing this module must never initialize an accelerator backend.
"""

import atexit
import itertools
import json
import os
import threading
import time
from collections import deque

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import telemetry

logger = get_logger()

FLIGHT_RECORDER_PATH_ENV = "SMP_FLIGHT_RECORDER_PATH"
FLIGHT_RECORDER_SIZE_ENV = "SMP_FLIGHT_RECORDER_SIZE"
DEFAULT_SIZE = 1024

# Event kinds (kept short: they are stored per event).
COLLECTIVE = "collective"
SYNC = "sync"
WAIT = "wait"
SLOT = "slot"
PHASE = "phase"
STEP = "step"
COMPILE = "compile"
WATCHDOG = "watchdog"
HEALTH = "health"
PREEMPT = "preempt"
CHAOS = "chaos"
SUPERVISOR = "supervisor"
SERVE = "serve"
FLEET = "fleet"
GOODPUT = "goodput"
PERF = "perf"
CONTROLLER = "controller"

# Field names per kind, applied at dump time (the ring stores bare
# tuples). Keeping the schema here — not at the record sites — is what
# keeps recording allocation-free beyond the tuple itself.
_FIELDS = {
    COLLECTIVE: ("op", "group", "nbytes", "group_size", "seq"),
    SYNC: ("name", "group", "seq", "wall_us"),
    WAIT: ("what", "peer", "tx", "outcome", "elapsed_us"),
    SLOT: ("schedule", "tick", "stage", "direction", "microbatch", "chunk",
           "pass"),
    PHASE: ("phase",),
    STEP: ("event", "step"),
    COMPILE: ("event", "name", "elapsed_us", "fingerprint"),
    WATCHDOG: ("reason",),
    HEALTH: ("event", "tag", "step", "value", "microbatch"),
    PREEMPT: ("event", "step", "detail"),
    CHAOS: ("fault", "detail"),
    SUPERVISOR: ("event", "peer", "detail", "wall_us"),
    SERVE: ("event", "rid", "trace", "slot", "pos", "detail"),
    FLEET: ("event", "rank", "detail", "wall_us"),
    GOODPUT: ("state", "prev", "elapsed_us"),
    PERF: ("event", "source", "detail", "wall_us"),
    CONTROLLER: ("event", "detail", "wall_us"),
}


def _env_size():
    raw = os.environ.get(FLIGHT_RECORDER_SIZE_ENV, "")
    if not raw:
        return DEFAULT_SIZE
    try:
        n = int(raw)
    except ValueError:
        logger.warning(
            "invalid %s=%r (want an integer); using default %d.",
            FLIGHT_RECORDER_SIZE_ENV, raw, DEFAULT_SIZE,
        )
        return DEFAULT_SIZE
    return max(n, 0)


class FlightRecorder:
    """Bounded ring of (id, t_us, kind, fields...) event tuples."""

    def __init__(self, size=None):
        size = _env_size() if size is None else max(int(size), 0)
        self.size = size
        self.enabled = size > 0
        self._ring = deque(maxlen=size) if size > 0 else None
        self._ids = itertools.count()
        self._seq_lock = threading.Lock()
        self._seq = {}
        # Wall-clock anchor: t=0 of the monotonic event clock. Captured
        # back-to-back so (anchor_unix_us + t_us) approximates the wall
        # time of any event; trace_fuse refines the residual per-rank
        # skew with barrier sync marks.
        self.anchor_unix_us = int(time.time() * 1e6)
        self._t0 = time.perf_counter()

    # -- hot path -------------------------------------------------------

    def _now_us(self):
        return (time.perf_counter() - self._t0) * 1e6

    def record(self, kind, *fields):
        """Append one event. The disabled path is a single attribute test."""
        if not self.enabled:
            return
        self._ring.append((next(self._ids), self._now_us(), kind) + fields)

    def next_seq(self, group):
        """Per-group monotonic collective sequence number. Every rank that
        executes the same collective stream gets the same numbers, so a
        cross-rank ring diff pinpoints the first diverging collective."""
        with self._seq_lock:
            seq = self._seq.get(group, 0)
            self._seq[group] = seq + 1
            return seq

    # -- typed recorders (keep the tuple layouts in _FIELDS) ------------

    def record_collective(self, op, group, nbytes, group_size,
                          sequenced=True):
        """``sequenced=False`` records the event WITHOUT consuming the
        group's sequence counter (seq -1). Point-to-point ops must use it:
        send/recv streams are rank-local by nature, and letting them bump
        the group counter would make healthy ranks' sequence streams
        diverge — false positives in the cross-rank desync diff, and
        mismatched barrier seqs that break sync-mark clock alignment."""
        if not self.enabled:
            return None
        seq = self.next_seq(group) if sequenced else -1
        self.record(COLLECTIVE, op, group, int(nbytes), int(group_size), seq)
        return seq

    def record_sync(self, name, group, seq):
        """A barrier-exit sync mark: all participating ranks record this
        within network-jitter of each other, carrying their own wall
        clock — the cross-rank clock-alignment signal."""
        if not self.enabled:
            return
        self.record(SYNC, name, group, seq, int(time.time() * 1e6))

    def record_wait(self, what, peer, tx, outcome, elapsed_s):
        if not self.enabled:
            return
        self.record(WAIT, what, int(peer), int(tx), outcome,
                    int(elapsed_s * 1e6))

    def record_slot(self, schedule, tick, stage, direction, microbatch,
                    chunk=None, pipe_pass=None):
        """``chunk`` is the virtual-pipeline chunk coordinate (interleaved
        schedules only); plain schedules omit it and their events keep the
        pre-chunk field layout. ``pipe_pass`` (dumped as ``pass``) is the
        schedule pass coordinate of split-backward schedules — "F", "B"
        (input-grad) or "W" (weight-grad); it requires ``chunk`` (the
        zero-bubble executor is always chunk-generalized)."""
        if chunk is None:
            self.record(SLOT, schedule, int(tick), int(stage), direction,
                        int(microbatch))
        elif pipe_pass is None:
            self.record(SLOT, schedule, int(tick), int(stage), direction,
                        int(microbatch), int(chunk))
        else:
            self.record(SLOT, schedule, int(tick), int(stage), direction,
                        int(microbatch), int(chunk), str(pipe_pass))

    def record_schedule(self, schedule, slots, cap=512):
        """Record a static pipeline schedule's busy slots (once, at
        build/trace time — the compiled program replays it every step).
        ``slots``: iterable of (tick, stage, direction, microbatch),
        (tick, stage, direction, microbatch, chunk) for interleaved
        virtual-stage schedules, or (tick, stage, direction, microbatch,
        chunk, pass) for zero-bubble split-backward schedules. Bounded to
        ``cap`` events so a huge schedule cannot evict the whole
        collective/wait history from the ring; truncation leaves an
        explicit marker."""
        if not self.enabled:
            return
        n = 0
        for slot in slots:
            if n >= cap:
                self.record(SLOT, schedule, -1, -1, "truncated", -1)
                break
            self.record_slot(schedule, *slot)
            n += 1

    def record_phase(self, phase):
        self.record(PHASE, phase)

    def record_step(self, event, step):
        self.record(STEP, event, int(step))

    def record_compile(self, event, name, elapsed_s=0.0, fingerprint=None):
        """``fingerprint`` ties a compile event to its program's X-ray
        fingerprint (utils/hlo_audit.py); events recorded without one
        keep the shorter pre-fingerprint tuple layout."""
        if fingerprint is None:
            self.record(COMPILE, event, name, int(elapsed_s * 1e6))
        else:
            self.record(COMPILE, event, name, int(elapsed_s * 1e6),
                        str(fingerprint))

    def record_watchdog(self, reason):
        self.record(WATCHDOG, reason)

    def record_health(self, event, tag, step=-1, value=0.0, microbatch=-1):
        """Training-health events (utils/health.py): sentinel trips, fault
        attributions, loss-scale overflow/growth, OOM post-mortems."""
        if not self.enabled:
            return
        self.record(HEALTH, event, str(tag), int(step), float(value),
                    int(microbatch))

    def record_preempt(self, event, step=-1, detail=""):
        """Resilience events (resilience/): preemption request/rendezvous/
        emergency-save edges and elastic-resume markers."""
        if not self.enabled:
            return
        self.record(PREEMPT, event, int(step), str(detail))

    def record_chaos(self, fault, detail=""):
        """An injected fault (resilience/chaos.py) — so post-mortem rings
        distinguish synthetic failures from real ones."""
        if not self.enabled:
            return
        self.record(CHAOS, str(fault), str(detail))

    def record_supervisor(self, event, peer=-1, detail=""):
        """Failure-detector / recovery-protocol events
        (resilience/supervisor.py): detections by kind, the recovery
        phase edges (rendezvous / reinit / resume / first step), aborts.
        Carries a wall-clock stamp so ``resilience_probe.py --recovery``
        can compute per-phase MTTR across dumps without ring-anchor
        arithmetic."""
        if not self.enabled:
            return
        self.record(SUPERVISOR, str(event), int(peer), str(detail),
                    int(time.time() * 1e6))

    def record_fleet(self, event, rank=-1, detail=""):
        """Fleet metrics-plane events (utils/fleet.py): aggregator
        (re-)election edges and detector transitions — straggler /
        stale_feed / kv_imbalance firing or clearing. ``rank`` is the
        subject replica (the new aggregator, the straggler), not the
        recording rank. Wall-stamped like supervisor events so
        ``trace_fuse.py`` can line detector fire-times up against the
        per-request serve spans."""
        if not self.enabled:
            return
        self.record(FLEET, str(event), int(rank), str(detail),
                    int(time.time() * 1e6))

    def record_serve(self, event, rid, trace=None, slot=-1, pos=-1,
                     detail=""):
        """Per-request serving span edges (serving/engine.py): queued /
        admitted / readmitted / prefill_chunk / first_token / finished.
        ``trace`` is the request's trace id (defaulting to the request
        id; preserved across failover re-admission via the mirror log,
        so both replicas' rings carry the same trace key) and ``slot``
        the decode-slot index — the lane ``scripts/trace_fuse.py`` draws
        the request's spans on."""
        if not self.enabled:
            return
        self.record(SERVE, str(event), str(rid), str(trace or rid),
                    int(slot), int(pos), str(detail))

    def record_goodput(self, state, prev, elapsed_s=0.0):
        """A goodput-ledger attribution transition (utils/goodput.py):
        the process left ``prev`` (after ``elapsed_s`` attributed to it)
        and entered ``state``. The stream ``trace_fuse.py`` renders as
        the per-rank badput track."""
        if not self.enabled:
            return
        self.record(GOODPUT, str(state), str(prev), int(elapsed_s * 1e6))

    def record_perf(self, event, source, detail=""):
        """Perf-regression sentinel and auto-forensics events
        (utils/goodput.py): ``regression``/``regression_clear`` edges
        (source = step_time | itl), ``goodput_min`` floor breaches, and
        ``forensics`` bundle captures. Wall-stamped like supervisor
        events so post-mortems line them up across ranks."""
        if not self.enabled:
            return
        self.record(PERF, str(event), str(source), str(detail),
                    int(time.time() * 1e6))

    def record_controller(self, event, detail=""):
        """Serving control-plane events (serving/controller.py): scale
        event edges (``scale_up`` / ``scale_down`` with phase timings),
        weight adoptions, canary verdicts, drain begin/end. Wall-stamped
        like supervisor events so ``scripts/trace_fuse.py`` and
        ``slo_report --controller`` can line a scale event up against
        the request spans that triggered it."""
        if not self.enabled:
            return
        self.record(CONTROLLER, str(event), str(detail),
                    int(time.time() * 1e6))

    def last_seq(self, group):
        """The group's current collective sequence number (the seq the
        NEXT sequenced collective would get), without consuming it: a
        typed collective-timeout error carries it as the coordinate where
        this rank's stream stopped."""
        with self._seq_lock:
            return self._seq.get(group, 0)

    # -- export ---------------------------------------------------------

    def _meta(self):
        with self._seq_lock:
            seqs = dict(self._seq)
        return {
            "kind": "meta",
            "pid": os.getpid(),
            "rank": telemetry.process_index,
            "world": telemetry.process_count,
            "size": self.size,
            "anchor_unix_us": self.anchor_unix_us,
            "collective_seq": seqs,
            "dumped_unix_us": int(time.time() * 1e6),
        }

    def snapshot(self, last=None):
        """List of event dicts, oldest first (formatting happens here, not
        at record time). ``last`` keeps only the most recent N."""
        if self._ring is None:
            return []
        events = list(self._ring)
        if last is not None:
            # last=0 must mean "no events", not the [-0:] whole-list slice.
            events = events[-last:] if last > 0 else []
        out = []
        for ev in events:
            eid, t_us, kind = ev[0], ev[1], ev[2]
            d = {"id": eid, "ts_us": round(t_us, 1), "kind": kind}
            for name, value in zip(_FIELDS.get(kind, ()), ev[3:]):
                d[name] = value
            out.append(d)
        return out

    def __len__(self):
        return 0 if self._ring is None else len(self._ring)

    def clear(self):
        """Testing hook: drop events and sequence counters."""
        if self._ring is not None:
            self._ring.clear()
        with self._seq_lock:
            self._seq.clear()

    def dump(self, path=None):
        """Write the ring as JSONL (meta line first) atomically. Explicit
        ``path`` wins; otherwise ``SMP_FLIGHT_RECORDER_PATH`` (no-op when
        neither is set). Rank-qualified under multi-process like the
        telemetry dump. Returns the path written, or None."""
        path = path or os.environ.get(FLIGHT_RECORDER_PATH_ENV)
        if not path:
            return None
        path = telemetry._rank_path(path)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps(self._meta()) + "\n")
                for d in self.snapshot():
                    f.write(json.dumps(d) + "\n")
            os.replace(tmp, path)
            return path
        except OSError as e:
            logger.warning("flight-recorder dump to %s failed: %s", path, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None


# ----------------------------------------------------------------------
# Singleton + hooks
# ----------------------------------------------------------------------

flight_recorder = FlightRecorder()

# Phase transitions flow into the ring without telemetry importing this
# module (utils/telemetry.py stays leaf; see its _phase_listener seam).
# Resolved through the module attribute at CALL time — not a bound method
# of the import-time instance — so tests (or anything else) that swap
# `flight_recorder` keep phases flowing to the live ring, same as
# telemetry's _flight() seam does for collectives.
def _phase_to_ring(phase):
    flight_recorder.record_phase(phase)


telemetry._phase_listener = _phase_to_ring


def _atexit_dump():  # pragma: no cover - exercised via subprocess test
    try:
        # The crash path too: atexit runs after sys.excepthook, so the
        # ring's tail shows what the process did right before dying. An
        # empty ring must not clobber the dump smp.shutdown already wrote
        # (state.reset clears the ring after shutdown dumps it).
        if len(flight_recorder):
            flight_recorder.dump()
    except Exception:
        pass


atexit.register(_atexit_dump)
