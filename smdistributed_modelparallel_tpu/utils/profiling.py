"""Performance observability: named profiler regions, on-demand XLA
profiler capture, and roofline/MFU attribution (``smp.profiling``).

The reference library ships profiling hooks as a first-class surface
(herring timers + the ``smp_timeline_*`` C API around every server
action); this module is the TPU build's equivalent, designed around the
fact that chip windows on this image are rare and flaky: when one opens,
a single run must capture a trace, attribute the MFU gap, and land in a
tracked trajectory (``scripts/perf_ledger.py``) without anyone re-running
ad-hoc probes. Four cooperating pieces:

1. **Named regions** — one vocabulary for every profiling surface.
   ``region(name)`` brackets a host-side phase with
   ``jax.profiler.TraceAnnotation`` (so the region shows up, by the same
   name, in an XLA profiler trace) AND a ``state.timeline`` span (so
   ``scripts/trace_fuse.py`` can align it cross-rank and report per-phase
   skew). ``named_region(name)`` is the in-graph twin: a
   ``jax.named_scope`` whose name lands in the compiled HLO's op
   metadata, tagging pipeline warmup/steady/cooldown phases, per-tick
   sub-steps — with the pass coordinate under split-backward schedules:
   ``smp/pipeline/tick_fwd``, ``tick_bwd`` (fused executors) vs
   ``tick_bwd_input`` / ``tick_bwd_weight`` (zero-bubble), plus the
   ZB-only ``cooldown_weight`` drain segment — and the optimizer update
   inside the device timeline. Wired through the step engine
   (trace/compile/dispatch/fetch), all pipeline executors, host
   collectives, and ``optimizer.step``.

2. **On-demand capture** — ``SMP_PROFILE=steps=N:M`` brackets
   ``jax.profiler.start_trace``/``stop_trace`` around exactly steps
   N..M (inclusive) into a per-rank directory under ``SMP_PROFILE_PATH``
   (default ``smp_profile/rank<i>``). ``SIGUSR2`` arms a one-step capture
   on a live run. Disarmed cost is one attribute test per step edge; the
   start/stop overhead of an actual capture is recorded in
   ``smp_profile_overhead_seconds_total`` so always-on cost stays
   measurably zero.

3. **Roofline / MFU attribution** — ``roofline(...)`` joins compiled-HLO
   ``cost_analysis``/``memory_analysis`` (FLOPs, bytes accessed) with a
   measured step wall time and the device's peak FLOP/s + HBM bandwidth
   (spec-sheet table by ``device_kind``; ``SMP_PEAK_TFLOPS`` /
   ``SMP_PEAK_GBPS`` override for unlisted backends) into MFU, achieved
   bytes/s, arithmetic intensity vs the ridge point, and a
   compute-vs-comm-vs-bubble decomposition of the step time (bubble from
   the pipeline occupancy gauges). Published as ``smp_mfu`` /
   ``smp_roofline_*`` gauges and rendered by the "performance" section of
   ``scripts/telemetry_report.py``. The step engine calls
   ``record_step_roofline`` on every dispatch, so a run on known hardware
   carries its MFU in every telemetry dump with no extra configuration.

4. **Breakdown API** — ``StepBreakdown`` collects named component
   timings and emits them in the same one-JSON-object-per-line schema
   ``bench.py`` writes to stderr (``{"component": ..., "ms": ...}``), so
   ``scripts/perf_probe.py`` / ``scripts/step_breakdown.py`` results land
   in the shape the perf ledger ingests.

Import-hygiene contract: importing this module must never initialize an
accelerator backend (``jax.profiler``/``jax.named_scope`` are pure-host
imports; ``jax.devices()`` is only touched from ``device_peaks`` at
attribution time).
"""

import atexit
import json
import os
import signal
import sys
import threading
import time

import jax

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import telemetry

logger = get_logger()

PROFILE_ENV = "SMP_PROFILE"
PROFILE_PATH_ENV = "SMP_PROFILE_PATH"
PEAK_TFLOPS_ENV = "SMP_PEAK_TFLOPS"
PEAK_GBPS_ENV = "SMP_PEAK_GBPS"

# Region names are prefixed so every surface (XLA profiler trace, our
# Perfetto timeline, trace_fuse's per-phase skew report, compiled-HLO op
# metadata) can recognize them by one convention:
#   host phases:    smp_phase/<name>   (region())
#   in-graph scopes: smp/<subsystem>/<name>  (named_region())
REGION_PREFIX = "smp_phase/"


def _timeline():
    """The live session timeline, or None. Resolved lazily: this module
    must not import backend.state at import time (state pulls in the whole
    core, and collectives/step import *us*)."""
    from smdistributed_modelparallel_tpu.backend.state import state

    return state.timeline


class _Region:
    """One named host-side profiler region (see ``region``)."""

    __slots__ = ("name", "track", "_ta", "_tl", "_begin_us")

    def __init__(self, name, track):
        self.name = name
        self.track = track
        self._ta = None
        self._tl = None
        self._begin_us = 0.0

    def __enter__(self):
        # TraceAnnotation is a TraceMe under the hood: near-free when no
        # profiler session is active, and a named host event when one is —
        # exactly the "same region names in the XLA trace" contract.
        try:
            self._ta = jax.profiler.TraceAnnotation(self.name)
            self._ta.__enter__()
        except Exception:  # pragma: no cover - profiler backend quirks
            self._ta = None
        tl = _timeline()
        if tl is not None and tl.enabled:
            self._tl = tl
            self._begin_us = tl._now_us()
        return self

    def __exit__(self, *exc):
        if self._tl is not None:
            self._tl.record_event(
                self.name, self._begin_us, self._tl._now_us(),
                track=self.track,
            )
        if self._ta is not None:
            self._ta.__exit__(*exc)
        return False


def region(name, track="phase"):
    """Context manager: one named host-side profiler region.

    Emits the region under ``smp_phase/<name>`` to BOTH observability
    surfaces at once: a ``jax.profiler.TraceAnnotation`` (visible in an
    XLA profiler capture) and a ``state.timeline`` span on the ``phase``
    track (visible in the fused Perfetto view; ``trace_fuse.py`` computes
    per-phase cross-rank skew from these). No-op-cheap when neither a
    profiler session nor the timeline is active.
    """
    return _Region(REGION_PREFIX + name, track)


def named_region(name):
    """In-graph region: a ``jax.named_scope`` wrapper. The name lands in
    the compiled HLO's op metadata (``op_name`` paths), so XLA profiler
    device timelines and HLO dumps carry the same region vocabulary as the
    host-side ``region`` spans."""
    return jax.named_scope(name)


# ----------------------------------------------------------------------
# On-demand capture (SMP_PROFILE / SIGUSR2)
# ----------------------------------------------------------------------


def _parse_profile_spec(spec):
    """``steps=N:M`` / ``steps=N`` / bare ``N:M`` -> (first, last)
    inclusive step window. Raises ValueError on anything else."""
    body = spec.strip()
    if body.startswith("steps="):
        body = body[len("steps="):]
    parts = body.split(":")
    if not body or len(parts) > 2:
        raise ValueError(f"unparseable {PROFILE_ENV} spec {spec!r}")
    first = int(parts[0])
    last = int(parts[1]) if len(parts) == 2 else first
    if first < 0 or last < first:
        raise ValueError(
            f"{PROFILE_ENV} window {spec!r} must satisfy 0 <= N <= M"
        )
    return first, last


class ProfileCapture:
    """Programmatic ``jax.profiler`` capture bracketed at step edges.

    The step engine calls ``on_step_begin(step)`` / ``on_step_end(step)``
    around every dispatch. When a window is armed (``SMP_PROFILE=
    steps=N:M`` at init, or a SIGUSR2 received on a live run — which arms
    a one-step window at the next step edge), the capture starts at the
    begin edge of step N and stops at the end edge of step M, writing the
    trace into ``<SMP_PROFILE_PATH>/rank<i>`` so multi-process runs never
    clobber each other. Disarmed, both hooks are a single attribute test.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._parsed_env = False
        self._window = None          # (first, last) inclusive, or None
        self._sig_request = False    # set by the SIGUSR2 handler
        self._installed = False
        self.active = False
        self.last_window = None      # (first, last) of the last capture
        self._started_at = None
        self._last_step = None       # most recent step edge seen
        self._forced_dir = None      # per-capture base dir override

    # -- configuration --------------------------------------------------

    def _ensure_spec(self):
        if self._parsed_env:
            return
        self._parsed_env = True
        spec = os.environ.get(PROFILE_ENV, "")
        if not spec:
            return
        try:
            self._window = _parse_profile_spec(spec)
        except ValueError as e:
            logger.warning("%s ignored: %s", PROFILE_ENV, e)

    @property
    def window(self):
        self._ensure_spec()
        return self._window

    def rank_dir(self):
        base = self._forced_dir or os.environ.get(
            PROFILE_PATH_ENV, "smp_profile"
        )
        rank = telemetry.process_index
        return os.path.join(base, f"rank{0 if rank is None else rank}")

    def request_capture(self, path=None):
        """Arm a one-step capture at the next step edge — the SIGUSR2
        path, callable in-process (auto-forensics uses it; ``path``
        overrides the SMP_PROFILE_PATH base for this capture only). Like
        the signal, it defers to a capture already running or a
        configured window still pending."""
        if path is not None and not self.active and self._window is None:
            self._forced_dir = path
        self._sig_request = True

    def install_signal(self):
        """Install the SIGUSR2 trigger (main thread only; re-entrant)."""
        if self._installed:
            return
        try:
            signal.signal(signal.SIGUSR2, self._on_sigusr2)
            self._installed = True
        except (ValueError, OSError, AttributeError) as e:
            # Non-main thread, or a platform without SIGUSR2.
            logger.debug("SIGUSR2 profile trigger unavailable: %s", e)

    def _on_sigusr2(self, signum, frame):
        # Async-signal context: only set a flag; the next step edge arms.
        self._sig_request = True

    # -- step-edge hooks (called by the step engine) --------------------

    def on_step_begin(self, step):
        self._ensure_spec()
        self._last_step = step
        if self._sig_request:
            self._sig_request = False
            if self.active or self._window is not None:
                # A capture is running or a configured window is still
                # pending — the signal must not cancel it (the armed
                # window may be the chip-window trace the run exists to
                # collect).
                logger.info(
                    "SIGUSR2 ignored: profiler capture %s.",
                    "already running" if self.active
                    else f"window {self._window} already armed",
                )
            else:
                # One-step window at the step about to run.
                self._window = (step, step)
                logger.info(
                    "SIGUSR2: profiler capture armed for step %d.", step
                )
        win = self._window
        if win is None or self.active or not (win[0] <= step <= win[1]):
            return
        with self._lock:
            if self.active:
                return
            t0 = time.perf_counter()
            path = self.rank_dir()
            try:
                os.makedirs(path, exist_ok=True)
                jax.profiler.start_trace(path)
            except Exception as e:
                logger.warning(
                    "profiler capture start failed (%s); window disarmed.", e
                )
                self._window = None
                self._forced_dir = None
                return
            self.active = True
            self._started_at = step
            self._record_overhead(time.perf_counter() - t0)
            telemetry.gauge(
                "smp_profile_active", "1 while a profiler capture is running"
            ).set(1)
            logger.info(
                "profiler capture started at step %d (window %d..%d) -> %s",
                step, win[0], win[1], path,
            )

    def on_step_end(self, step, outputs=None):
        if not self.active:
            return
        win = self._window
        if win is not None and step < win[1]:
            return
        # Make the captured window actually contain this step's device
        # execution (dispatch is async): block before stopping the trace.
        if outputs is not None:
            try:
                jax.block_until_ready(outputs)
            except Exception:  # pragma: no cover - donated/consumed buffers
                pass
        self._stop(step)

    def _stop(self, step):
        with self._lock:
            if not self.active:
                return
            t0 = time.perf_counter()
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover
                logger.warning("profiler capture stop failed: %s", e)
            self.active = False
            first = self._started_at if self._started_at is not None else step
            self.last_window = (first, step)
            self._window = None       # window consumed; SIGUSR2 can re-arm
            self._record_overhead(time.perf_counter() - t0)
            telemetry.gauge(
                "smp_profile_active", "1 while a profiler capture is running"
            ).set(0)
            telemetry.counter(
                "smp_profile_captures_total", "completed profiler captures"
            ).inc()
            telemetry.gauge(
                "smp_profile_last_first_step",
                "first step of the last profiler capture",
            ).set(first)
            telemetry.gauge(
                "smp_profile_last_last_step",
                "last step of the last profiler capture",
            ).set(step)
            logger.info(
                "profiler capture stopped: steps %d..%d -> %s",
                first, step, self.rank_dir(),
            )
            self._forced_dir = None

    @staticmethod
    def _record_overhead(seconds):
        telemetry.counter(
            "smp_profile_overhead_seconds_total",
            "host seconds spent starting/stopping profiler captures "
            "(zero unless a capture ran)",
        ).inc(seconds)

    def stop_if_active(self):
        """Shutdown/atexit hook: a run that ends mid-window still gets a
        usable trace rather than a torn session. The recorded window ends
        at the last step edge this capture actually saw."""
        if self.active:
            last = self._last_step
            if last is None:
                last = self._started_at if self._started_at is not None else -1
            self._stop(last)

    def reset(self):
        """Testing hook: stop any live capture and re-read the env."""
        self.stop_if_active()
        self._parsed_env = False
        self._window = None
        self._sig_request = False
        self.last_window = None
        self._started_at = None
        self._last_step = None
        self._forced_dir = None


capture = ProfileCapture()
atexit.register(capture.stop_if_active)


# ----------------------------------------------------------------------
# Roofline / MFU attribution
# ----------------------------------------------------------------------

# Peak dense bf16 TFLOP/s and HBM GB/s per chip, by device_kind fragment
# (public spec sheets). Single source of truth — bench.py's MFU
# denominator reads THIS table through device_peaks.
_PEAK_TFLOPS = (
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0),
    ("v5e", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 46.0),
)
_PEAK_GBPS = (
    ("v6", 1640.0),
    ("v5p", 2765.0),
    ("v5 lite", 819.0),
    ("v5e", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)

_DEVICE_KIND_CACHE = []  # [kind] once resolved (jax.devices() is sticky)


def _env_float(name):
    raw = os.environ.get(name, "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        logger.warning("invalid %s=%r (want a number); ignored.", name, raw)
        return None


def _device_kind(device):
    if device is not None:
        return getattr(device, "device_kind", "").lower()
    if not _DEVICE_KIND_CACHE:
        try:
            _DEVICE_KIND_CACHE.append(
                getattr(jax.devices()[0], "device_kind", "").lower()
            )
        except Exception:  # pragma: no cover - backend bring-up failure
            _DEVICE_KIND_CACHE.append("")
    return _DEVICE_KIND_CACHE[0]


def device_peaks(device=None):
    """(peak FLOP/s, peak bytes/s) for the attribution denominator.

    ``SMP_PEAK_TFLOPS`` / ``SMP_PEAK_GBPS`` override (required on
    backends the spec table does not know, e.g. the CPU test mesh);
    otherwise looked up by ``device_kind``. Unknown entries are None —
    callers must treat MFU as unavailable rather than fabricate one.
    """
    flops = _env_float(PEAK_TFLOPS_ENV)
    flops = flops * 1e12 if flops is not None else None
    bps = _env_float(PEAK_GBPS_ENV)
    bps = bps * 1e9 if bps is not None else None
    if flops is None or bps is None:
        kind = _device_kind(device)
        if flops is None:
            for frag, v in _PEAK_TFLOPS:
                if frag in kind:
                    flops = v * 1e12
                    break
        if bps is None:
            for frag, v in _PEAK_GBPS:
                if frag in kind:
                    bps = v * 1e9
                    break
    return flops, bps


def cost_of(compiled):
    """(flops, bytes_accessed) from a compiled executable's
    ``cost_analysis`` — (None, None) when the backend won't say."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        flops = cost.get("flops")
        nbytes = cost.get("bytes accessed")
        return (
            float(flops) if flops is not None else None,
            float(nbytes) if nbytes is not None else None,
        )
    except Exception:
        return None, None


class RooflineReport:
    """One step program's roofline attribution (plain attributes +
    ``as_dict``). ``None`` fields mean "not attributable" (unknown peak,
    missing cost analysis), never a guess."""

    def __init__(self, **kw):
        self.name = kw.get("name")
        self.step_time_s = kw.get("step_time_s")
        self.flops = kw.get("flops")
        self.bytes_accessed = kw.get("bytes_accessed")
        self.peak_flops_per_s = kw.get("peak_flops_per_s")
        self.peak_bytes_per_s = kw.get("peak_bytes_per_s")
        self.mfu = kw.get("mfu")
        self.achieved_flops_per_s = kw.get("achieved_flops_per_s")
        self.achieved_bytes_per_s = kw.get("achieved_bytes_per_s")
        self.arithmetic_intensity = kw.get("arithmetic_intensity")
        self.ridge_intensity = kw.get("ridge_intensity")
        self.bound = kw.get("bound")        # "compute" | "memory" | None
        self.compute_s = kw.get("compute_s")
        self.memory_s = kw.get("memory_s")
        self.bubble_fraction = kw.get("bubble_fraction")
        self.bubble_s = kw.get("bubble_s")
        self.comm_s = kw.get("comm_s")

    def as_dict(self):
        return {
            k: getattr(self, k)
            for k in (
                "name", "step_time_s", "flops", "bytes_accessed",
                "peak_flops_per_s", "peak_bytes_per_s", "mfu",
                "achieved_flops_per_s", "achieved_bytes_per_s",
                "arithmetic_intensity", "ridge_intensity", "bound",
                "compute_s", "memory_s", "bubble_fraction", "bubble_s",
                "comm_s",
            )
        }


def _live_gauge_max(name):
    """Max value across a live gauge family's series (None when absent)."""
    fam = telemetry._families.get(name)
    if fam is None:
        return None
    with fam._lock:
        children = list(fam._children.values())
    return max((c.value for c in children), default=None)


def roofline(name="step", *, step_time_s, flops=None, bytes_accessed=None,
             compiled=None, bubble_fraction=None, device=None,
             peak_flops=None, peak_bytes_per_s=None, publish=True):
    """Join program cost with measured wall time into a roofline report.

    Args:
      name: label for the published gauges (``step=<name>``).
      step_time_s: measured wall time of one step of this program.
      flops / bytes_accessed: explicit program cost; missing pieces are
        filled from ``compiled.cost_analysis()`` when given.
      compiled: a compiled executable (``jax.jit(...).lower().compile()``
        or the step runner's AOT executable).
      bubble_fraction: pipeline idle fraction; defaults to the live
        ``smp_pipeline_bubble_fraction`` gauge (0.0 when no pipeline).
      device / peak_flops / peak_bytes_per_s: attribution denominators;
        default to ``device_peaks`` (spec table + the peak env overrides).
      publish: set the ``smp_mfu`` / ``smp_roofline_*`` gauges.

    Decomposition (published per label): ``compute_s`` is the ideal
    compute-bound time ``flops / peak_flops``; ``bubble_s`` is
    ``bubble_fraction * step_time``; ``comm_s`` is the residual — time
    the roofline model cannot attribute to ideal compute or schedule
    bubble (collectives, memory-bound stalls, host overhead).
    ``memory_s`` (``bytes / peak_bw``) is reported alongside as the
    bandwidth bound.
    """
    if compiled is not None and (flops is None or bytes_accessed is None):
        c_flops, c_bytes = cost_of(compiled)
        flops = flops if flops is not None else c_flops
        bytes_accessed = (
            bytes_accessed if bytes_accessed is not None else c_bytes
        )
    if peak_flops is None or peak_bytes_per_s is None:
        d_flops, d_bps = device_peaks(device)
        peak_flops = peak_flops if peak_flops is not None else d_flops
        peak_bytes_per_s = (
            peak_bytes_per_s if peak_bytes_per_s is not None else d_bps
        )
    if bubble_fraction is None:
        bubble_fraction = _live_gauge_max("smp_pipeline_bubble_fraction")
        bubble_fraction = 0.0 if bubble_fraction is None else bubble_fraction

    dt = float(step_time_s) if step_time_s else None
    achieved_f = flops / dt if (flops is not None and dt) else None
    achieved_b = bytes_accessed / dt if (bytes_accessed is not None and dt) else None
    mfu = (
        achieved_f / peak_flops
        if (achieved_f is not None and peak_flops) else None
    )
    ai = (
        flops / bytes_accessed
        if (flops is not None and bytes_accessed) else None
    )
    ridge = (
        peak_flops / peak_bytes_per_s
        if (peak_flops and peak_bytes_per_s) else None
    )
    bound = None
    if ai is not None and ridge is not None:
        bound = "compute" if ai >= ridge else "memory"
    compute_s = flops / peak_flops if (flops is not None and peak_flops) else None
    memory_s = (
        bytes_accessed / peak_bytes_per_s
        if (bytes_accessed is not None and peak_bytes_per_s) else None
    )
    bubble_s = bubble_fraction * dt if dt is not None else None
    comm_s = None
    if dt is not None and compute_s is not None and bubble_s is not None:
        comm_s = max(dt - compute_s - bubble_s, 0.0)

    report = RooflineReport(
        name=name, step_time_s=dt, flops=flops,
        bytes_accessed=bytes_accessed, peak_flops_per_s=peak_flops,
        peak_bytes_per_s=peak_bytes_per_s, mfu=mfu,
        achieved_flops_per_s=achieved_f, achieved_bytes_per_s=achieved_b,
        arithmetic_intensity=ai, ridge_intensity=ridge, bound=bound,
        compute_s=compute_s, memory_s=memory_s,
        bubble_fraction=bubble_fraction, bubble_s=bubble_s, comm_s=comm_s,
    )
    if publish:
        _publish(report)
    return report


def _publish(r):
    lab = dict(step=r.name)
    for value, metric, help_ in (
        (r.mfu, "smp_mfu",
         "model FLOPs utilization of the last measured step"),
        (r.flops, "smp_roofline_flops",
         "program FLOPs joined into the roofline report"),
        (r.bytes_accessed, "smp_roofline_bytes",
         "program bytes accessed joined into the roofline report"),
        (r.step_time_s, "smp_roofline_step_seconds",
         "measured step wall time of the roofline report"),
        (r.achieved_flops_per_s, "smp_roofline_achieved_flops_per_s",
         "achieved FLOP/s of the last measured step"),
        (r.achieved_bytes_per_s, "smp_roofline_achieved_bytes_per_s",
         "achieved HBM bytes/s of the last measured step"),
        (r.arithmetic_intensity, "smp_roofline_arithmetic_intensity",
         "program FLOPs per byte accessed"),
        (r.ridge_intensity, "smp_roofline_ridge_intensity",
         "device ridge point (peak FLOP/s / peak bytes/s)"),
        (r.compute_s, "smp_roofline_compute_seconds",
         "ideal compute-bound time (flops / peak FLOP/s)"),
        (r.memory_s, "smp_roofline_memory_seconds",
         "ideal bandwidth-bound time (bytes / peak bytes/s)"),
        (r.bubble_s, "smp_roofline_bubble_seconds",
         "pipeline-bubble share of the step time"),
        (r.comm_s, "smp_roofline_comm_seconds",
         "residual step time not attributed to ideal compute or bubble "
         "(collectives, memory stalls, host overhead)"),
        (r.peak_flops_per_s, "smp_roofline_peak_flops_per_s",
         "peak FLOP/s used as the MFU denominator"),
        (r.peak_bytes_per_s, "smp_roofline_peak_bytes_per_s",
         "peak bytes/s used as the bandwidth denominator"),
    ):
        if value is not None:
            telemetry.gauge(metric, help_).labels(**lab).set(float(value))
    if r.bound is not None:
        telemetry.gauge(
            "smp_roofline_compute_bound",
            "1 when arithmetic intensity sits above the ridge point",
        ).labels(**lab).set(1.0 if r.bound == "compute" else 0.0)


ROOFLINE_SAMPLE_EVERY = 16


def should_sample_step(step):
    """Steps where the engine blocks on the step's outputs to measure an
    EXACT wall time for the roofline gauges (step 1, then every 16th).

    Without a block, async dispatch returns long before the device
    finishes; dividing program FLOPs by that lower-bound time would
    publish an upper-bound — i.e. wrong, possibly >1.0 — MFU. Sampling
    keeps the gauges honest at ~zero throughput cost (one drained
    dispatch queue per 16 steps)."""
    return step % ROOFLINE_SAMPLE_EVERY == 1


def record_step_roofline(runner, step_time_s):
    """Per-step hook from the step engine: publish ``smp_mfu`` and the
    decomposition for this runner's program, costing a few float ops.

    The runner's compiled cost analysis is read once and cached on the
    runner; attribution is skipped entirely (cached as unavailable) when
    the executable or its cost analysis is missing. The engine only calls
    this with EXACT step times — the timeline-blocked path, or a sampled
    ``should_sample_step`` block — never the async-dispatch lower bound.
    """
    if runner is None or not step_time_s:
        return None
    cached = getattr(runner, "_roofline_cost", None)
    if cached is None:
        compiled = runner.holder.get("compiled") if hasattr(runner, "holder") else None
        cost = cost_of(compiled) if compiled is not None else (None, None)
        cached = cost if cost[0] is not None else False
        runner._roofline_cost = cached
    if cached is False:
        return None
    flops, nbytes = cached
    return roofline(
        getattr(runner, "step_name", "step"),
        step_time_s=step_time_s, flops=flops, bytes_accessed=nbytes,
    )


# ----------------------------------------------------------------------
# Breakdown API (scripts/perf_probe.py, scripts/step_breakdown.py, bench)
# ----------------------------------------------------------------------


class StepBreakdown:
    """Named component timings, emitted one JSON object per line in the
    exact schema ``bench.py`` writes to stderr:
    ``{"component": <name>, "ms": <float>, ...extras}``.

    ``record`` takes seconds (the JSON carries ms, like bench); every
    component also lands in the ``smp_breakdown_ms`` telemetry gauge so a
    probe run's breakdown rides in its telemetry dump.
    """

    def __init__(self, context=None):
        self._rows = []
        self._context = dict(context or {})

    @property
    def rows(self):
        return list(self._rows)

    def record(self, component, seconds, **extras):
        row = dict(self._context)
        row.update(extras)
        row["component"] = component
        row["ms"] = round(float(seconds) * 1e3, 3)
        self._rows.append(row)
        telemetry.gauge(
            "smp_breakdown_ms", "perf-probe component wall time (ms)"
        ).labels(component=component).set(float(seconds) * 1e3)
        return row

    def time(self, component, fn, *args, iters=10, readback=None, **extras):
        """Warmup call + timed loop; records the mean per-iteration wall
        time. ``readback`` forces a device->host sync (defaults to
        ``jax.block_until_ready``). Not for donating functions — those
        must thread their own state and call ``record`` directly."""
        out = fn(*args)
        self._force(out, readback)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        self._force(out, readback)
        dt = (time.perf_counter() - t0) / iters
        self.record(component, dt, iters=iters, **extras)
        return out, dt

    @staticmethod
    def _force(out, readback):
        if readback is not None:
            readback(out)
        else:
            jax.block_until_ready(out)

    def emit(self, stream=None):
        """Write every recorded row as one JSON line (bench schema).
        Returns the rows."""
        stream = sys.stderr if stream is None else stream
        for row in self._rows:
            stream.write(json.dumps(row) + "\n")
        stream.flush()
        return self.rows
