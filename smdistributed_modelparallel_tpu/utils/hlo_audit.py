"""Compiled-program X-ray (``smp.xray``): post-compile HLO audit.

Runtime observability (telemetry, flight recorder, health, roofline) says
what a run DID; this module says what the compiler BUILT. The motivating
failure is the PR-5 class: GSPMD's sharding propagation is best-effort
heuristics (GSPMD paper, arXiv 2105.04663), and one broken propagation
step silently REPLICATED the entire virtual-pipeline tick loop — every
device computing every stage, zero collective-permutes — caught only by
hand-reading HLO text. The standing guard was a raw
``hlo.count("collective-permute")`` in one test. This module makes that
inspection a first-class, structured pass over EVERY compiled step:

1. **Collective census** — every ``all-reduce`` / ``all-gather`` /
   ``reduce-scatter`` / ``collective-permute`` / ``all-to-all`` in the
   compiled module, with op counts, per-device result bytes, and
   mesh-axis attribution: ``replica_groups`` (literal or iota form) and
   ``source_target_pairs`` are matched against the device groups each
   mesh-axis subset generates, so "12 permutes on ``pp``, 4 all-reduces
   on ``rdp``" is a queryable fact, not a substring count.

2. **Sharding/replication detector** — flags (a) parameters whose
   partitioner-assigned sharding says partitioned but whose realized
   sharding is replicated, (b) gradient outputs that come back replicated
   where their parameter is partitioned, and (c) the PR-5 failure class
   itself: a pipelined program (pp > 1) whose census shows ZERO pp-axis
   collective-permutes — reported with the tick-loop ``while`` op name
   and a wasted-bytes estimate from its carry tuple.

3. **Remat census** — recomputed-FLOPs fraction: dot/convolution
   instructions that are structural duplicates (same result/operand
   shapes, contraction dims, source location) of an earlier instruction,
   FLOP-weighted. Exact for double-forward recompute (activation remat,
   the ZB split-backward's B+W forward re-runs); an upper bound when a
   transpose dot is structurally identical to its forward. Static census:
   multiplicities are per compiled program, not per loop trip.

4. **Memory breakdown** — XLA buffer assignment by class (arguments /
   outputs / temps / aliased / generated code) from ``memory_analysis``.

Every audit folds into a **program fingerprint**: a structured summary
(config snapshot, census, replication findings, remat fraction, memory,
FLOPs) plus content hashes — ``hlo_sha256`` over the metadata-stripped
HLO text and ``fingerprint`` over the canonical summary JSON. Keyed by
the step engine's compile-cache key, persisted to ``SMP_HLO_AUDIT_PATH``
(rank-qualified), published as ``smp_hlo_*`` telemetry gauges, and
referenced from the flight recorder's compile event. ``diff()`` (and
``scripts/hlo_report.py diff``) renders what changed between two
fingerprints; committed goldens gate the canonical pipeline configs in
the test tier.

``SMP_HLO_AUDIT=off`` disables the pass entirely: ``maybe_audit``
returns before touching the executable (no ``as_text`` call, no gauges —
a hard no-op, tested as such).

Import-hygiene contract: importing this module must never initialize an
accelerator backend (jax is imported for tree utilities only; devices
are touched exclusively through the mesh handed in at audit time).
"""

import hashlib
import itertools
import json
import os
import re
import time

import jax
import numpy as np

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    _atomic_json_dump,
    telemetry,
)

logger = get_logger()

AUDIT_ENV = "SMP_HLO_AUDIT"
AUDIT_PATH_ENV = "SMP_HLO_AUDIT_PATH"

COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# `-done` halves of async pairs carry no new information (the `-start`
# already holds the groups and the payload shape) and would double-count.
_COLL_RE = re.compile(
    r"=\s*(?P<shape>\([^=]*?\)|\S+)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?P<suffix>-start|-done)?\("
)
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{(\{[0-9, ]*\}(?:,\s*\{[0-9, ]*\})*)\}")
_GROUP_RE = re.compile(r"\{([0-9, ]*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")
_PAIR_RE = re.compile(r"\{(\d+),(\d+)\}")
_DOT_RE = re.compile(r"=\s*(?P<shape>\S+)\s+(?P<op>dot|convolution)\(")
_CONTRACT_RE = re.compile(
    r"lhs_contracting_dims=\{([0-9,]*)\}, rhs_contracting_dims=\{([0-9,]*)\}"
)
_METADATA_RE = re.compile(r"metadata=\{[^}]*\}")
_SOURCE_RE = re.compile(r'source_file="([^"]*)" source_line=(\d+)')
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_WHILE_RE = re.compile(r"%?([\w.\-]+)\s*=\s*(\([^=]*?\))\s+while\(")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def enabled():
    """Audit gate: ``SMP_HLO_AUDIT=off``/``0`` disables (default on)."""
    return os.environ.get(AUDIT_ENV, "on").lower() not in ("off", "0", "false")


# ----------------------------------------------------------------------
# HLO text parsing
# ----------------------------------------------------------------------


def _shape_bytes(shape_str):
    """Total bytes of every array shape token in an HLO shape string
    (sums tuple elements; scalars count one element)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        width = _DTYPE_BYTES.get(dtype)
        if width is None:
            continue  # token/opaque types carry no payload bytes
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * width
    return total


def _parse_replica_groups(line):
    """The replica groups of one collective line as a list of int tuples,
    ``"all"`` for the empty ``replica_groups={}`` (every participant in
    one group), or None when the line carries none."""
    if "replica_groups={}" in line:
        return "all"
    m = _GROUPS_LITERAL_RE.search(line)
    if m:
        groups = []
        for g in _GROUP_RE.findall(m.group(1)):
            ids = tuple(int(x) for x in g.replace(" ", "").split(",") if x)
            if ids:
                groups.append(ids)
        return groups or None
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        # Iota form [g0,g1,...]<=[r0,r1,...]T(perm): arange over the
        # reshape dims, transposed, flattened, then rows of the left
        # shape's trailing dim are the groups.
        left = [int(x) for x in m.group(1).split(",")]
        reshape = [int(x) for x in m.group(2).split(",")]
        ids = np.arange(int(np.prod(reshape))).reshape(reshape)
        if m.group(3):
            ids = ids.transpose([int(x) for x in m.group(3).split(",")])
        ids = ids.ravel().reshape(-1, left[-1])
        return [tuple(int(x) for x in row) for row in ids]
    return None


def _parse_pairs(line):
    m = _PAIRS_RE.search(line)
    if not m:
        return None
    return [(int(a), int(b)) for a, b in _PAIR_RE.findall(m.group(1))]


def _mesh_coord_maps(mesh):
    """Participant-id -> per-axis coordinate maps: ``pos`` keys by the
    mesh's flattened device order (the SPMD partition numbering), ``id``
    by device id (``use_global_device_ids=true`` groups)."""
    if mesh is None:
        return None
    by_pos, by_id = {}, {}
    axes = tuple(mesh.axis_names)
    flat = list(np.asarray(mesh.devices).ravel())
    shape = np.asarray(mesh.devices).shape
    for pos, coords in enumerate(np.ndindex(*shape)):
        by_pos[pos] = coords
        dev = flat[pos]
        did = getattr(dev, "id", pos)
        by_id[did] = coords
    return {"axes": axes, "pos": by_pos, "id": by_id}


def _axis_subsets(mesh):
    """Nontrivial mesh-axis subsets, smallest first, each with the
    partition of participant coordinates it generates."""
    axes = [
        (i, a) for i, a in enumerate(mesh.axis_names)
        if dict(mesh.shape).get(a, 1) > 1
    ]
    out = []
    for size in range(1, len(axes) + 1):
        for combo in itertools.combinations(axes, size):
            out.append(combo)
    return out


def _attribute_groups(groups, mesh, maps, use_global_ids):
    """Mesh-axis label for a replica-group set: the smallest axis subset
    whose generated device partition matches exactly. ``"world"`` when the
    match is every nontrivial axis, ``"self"`` for singleton groups,
    ``"unattributed"`` when nothing matches (manual groups, sliced
    meshes)."""
    if maps is None:
        return "unattributed"
    if groups and all(len(g) == 1 for g in groups):
        return "self"
    coord_of = maps["id"] if use_global_ids else maps["pos"]
    try:
        got = {frozenset(g) for g in groups}
    except TypeError:
        return "unattributed"
    if not all(i in coord_of for g in groups for i in g):
        return "unattributed"
    subsets = _axis_subsets(mesh)
    n_nontrivial = max((len(s) for s in subsets), default=0)
    for combo in subsets:
        vary = {i for i, _ in combo}
        buckets = {}
        for pid, coords in coord_of.items():
            key = tuple(c for i, c in enumerate(coords) if i not in vary)
            buckets.setdefault(key, set()).add(pid)
        if {frozenset(b) for b in buckets.values()} == got:
            if len(combo) == n_nontrivial and len(combo) > 1:
                return "world"
            return "+".join(a for _, a in combo)
    return "unattributed"


def _attribute_pairs(pairs, maps, use_global_ids):
    """Axis label for collective-permute source/target pairs: every pair
    must step along the SAME single mesh axis."""
    if maps is None or not pairs:
        return "unattributed"
    coord_of = maps["id"] if use_global_ids else maps["pos"]
    axes = maps["axes"]
    axis_hit = None
    for src, dst in pairs:
        cs, cd = coord_of.get(src), coord_of.get(dst)
        if cs is None or cd is None:
            return "unattributed"
        diff = [i for i, (a, b) in enumerate(zip(cs, cd)) if a != b]
        if len(diff) != 1:
            return "unattributed"
        if axis_hit is None:
            axis_hit = diff[0]
        elif axis_hit != diff[0]:
            return "unattributed"
    return axes[axis_hit] if axis_hit is not None else "unattributed"


def collective_census(hlo_text, mesh=None):
    """``{op: {"count", "bytes", "axes": {label: {"count", "bytes"}}}}``
    over every collective instruction in the HLO text. ``bytes`` is the
    per-device result payload (summed over tuple elements)."""
    census = {}
    maps = _mesh_coord_maps(mesh)
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None or m.group("suffix") == "-done":
            continue
        op = m.group("op")
        nbytes = _shape_bytes(m.group("shape"))
        use_global = "use_global_device_ids=true" in line
        if op == "collective-permute":
            pairs = _parse_pairs(line)
            axis = _attribute_pairs(pairs, maps, use_global)
        else:
            groups = _parse_replica_groups(line)
            if groups is None:
                axis = "unattributed"
            elif groups == "all":
                axis = "world"
            else:
                axis = _attribute_groups(groups, mesh, maps, use_global)
        ent = census.setdefault(op, {"count": 0, "bytes": 0, "axes": {}})
        ent["count"] += 1
        ent["bytes"] += nbytes
        ax = ent["axes"].setdefault(axis, {"count": 0, "bytes": 0})
        ax["count"] += 1
        ax["bytes"] += nbytes
    return census


def remat_census(hlo_text):
    """``{"flops", "recomputed_flops", "fraction", "dots",
    "recomputed_dots"}`` — FLOP-weighted structural-duplicate census of
    dot/convolution instructions (see module docstring for exactness)."""
    seen = {}
    for line in hlo_text.splitlines():
        m = _DOT_RE.search(line)
        if m is None:
            continue
        shapes = _SHAPE_RE.findall(line)
        contract = _CONTRACT_RE.search(line)
        src = _SOURCE_RE.search(line)
        key = (
            m.group("op"),
            tuple(shapes[:3]),
            contract.groups() if contract else None,
            src.groups() if src else None,
        )
        flops = _dot_flops(m.group("op"), shapes, contract)
        seen.setdefault(key, []).append(flops)
    total_f = recomputed_f = 0.0
    total_n = recomputed_n = 0
    for flops_list in seen.values():
        total_n += len(flops_list)
        total_f += sum(flops_list)
        if len(flops_list) > 1:
            recomputed_n += len(flops_list) - 1
            recomputed_f += sum(flops_list) - flops_list[0]
    fraction = recomputed_f / total_f if total_f else 0.0
    return {
        "flops": total_f,
        "recomputed_flops": recomputed_f,
        "fraction": round(fraction, 4),
        "dots": total_n,
        "recomputed_dots": recomputed_n,
    }


def _dot_flops(op, shapes, contract):
    """2 * |result| * |contraction| for a dot (from its text shapes);
    convolutions fall back to 2 * |result| (kernel size unparsed)."""
    def _dims(shape):
        _, dims = shape
        return [int(d) for d in dims.split(",") if d]

    if not shapes:
        return 0.0
    result = float(np.prod(_dims(shapes[0]))) if _dims(shapes[0]) else 1.0
    if op == "dot" and contract is not None and len(shapes) >= 2:
        lhs = _dims(shapes[1])
        k = 1.0
        for i in contract.group(1).split(","):
            if i and int(i) < len(lhs):
                k *= lhs[int(i)]
        return 2.0 * result * k
    return 2.0 * result


def while_carries(hlo_text):
    """``[{"name", "op_name", "bytes"}]`` for every ``while`` instruction
    (carry-tuple bytes from its result shape), largest first."""
    out = []
    for line in hlo_text.splitlines():
        m = _WHILE_RE.search(line)
        if m is None:
            continue
        op_name = _OP_NAME_RE.search(line)
        out.append({
            "name": m.group(1),
            "op_name": op_name.group(1) if op_name else m.group(1),
            "bytes": _shape_bytes(m.group(2)),
        })
    out.sort(key=lambda w: -w["bytes"])
    return out


# ----------------------------------------------------------------------
# ZeRO-3 traffic report (sharded_params: zero3)
# ----------------------------------------------------------------------

_COMP_HEADER_RE = re.compile(
    r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*(?:\([^)]*\))?\s*\(.*\)\s*->.*\{\s*$"
)
_INSTR_RE = re.compile(r"^\s*(ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_REF_RE = re.compile(r"%([\w.\-]+)")


def _computations(hlo_text):
    """``(name, [instruction lines])`` per computation in the HLO text."""
    name, lines = None, []
    for line in hlo_text.splitlines():
        m = _COMP_HEADER_RE.match(line)
        if m is not None:
            if name is not None:
                yield name, lines
            name, lines = m.group(1), []
            continue
        if line.startswith("}"):
            if name is not None:
                yield name, lines
            name, lines = None, []
            continue
        if name is not None:
            lines.append(line)
    if name is not None:
        yield name, lines


# The result-type prefix of a tuple-typed instruction can contain
# ``/*index=N*/`` comments, so the paren alternative must key on paren
# nesting (HLO types never nest parens), not on '='-freedom.
_RHS_OP_RE = re.compile(r"^(?:\([^()]*\)|\S+)\s+([a-z][a-z0-9\-]*)\(")
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")

#: Pure data-movement ops: an all-gather whose transitive users are ONLY
#: these (ending at the body ROOT tuple) computes nothing this iteration —
#: it is parked in the loop carry for the next tick. Anything else
#: (dot, a fusion whose body computes, convert feeding compute, ...)
#: counts as compute, so gathers consumed at use never misclassify as
#: registers. ``parameter``/``constant`` matter only for classifying
#: fused computations as move-only.
_MOVE_OPS = frozenset((
    "tuple", "copy", "bitcast", "get-tuple-element", "opt-barrier",
    "all-gather-done", "transpose", "reshape", "parameter", "constant",
))


def zero3_prefetch_evidence(hlo_text):
    """Structural double-buffering check: inside some while-loop body that
    performs both an all-gather and matmuls, at least one all-gather's
    result never feeds this iteration's compute — its only transitive
    users are data-movement ops (including fusions of them, e.g. the
    copy/bitcast fusions XLA builds for carry writes) ending at the carry
    tuple: the transfer register, i.e. the next layer's gather is issued
    before this layer's dependent matmuls. Returns the count of such
    register gathers."""
    comps = list(_computations(hlo_text))
    # A fusion is data-movement iff every instruction of its called
    # computation is.
    move_only = {}
    for name, lines in comps:
        ok = True
        for line in lines:
            m = _INSTR_RE.match(line)
            if m is None:
                continue
            km = _RHS_OP_RE.match(m.group(3))
            if km is None or km.group(1) not in _MOVE_OPS:
                ok = False
                break
        move_only[name] = ok

    registers = 0
    for name, lines in comps:
        users, kinds, dots, gathers, calls = {}, {}, set(), [], {}
        for line in lines:
            m = _INSTR_RE.match(line)
            if m is None:
                continue
            iname, rhs = m.group(2), m.group(3)
            for op in _REF_RE.findall(rhs):
                if op != iname:
                    users.setdefault(op, set()).add(iname)
            km = _RHS_OP_RE.match(rhs)
            kinds[iname] = km.group(1) if km else "?"
            if kinds[iname] == "fusion":
                fm = _CALLS_RE.search(rhs)
                if fm:
                    calls[iname] = fm.group(1)
            cm = _COLL_RE.search(line)
            if cm is not None and cm.group("op") == "all-gather" and (
                    cm.group("suffix") != "-done"):
                gathers.append(iname)
            if _DOT_RE.search(line):
                dots.add(iname)
        if not gathers or not dots:
            continue

        def moves(iname):
            kind = kinds.get(iname)
            if kind == "fusion":
                return move_only.get(calls.get(iname, ""), False)
            return kind in _MOVE_OPS

        for g in gathers:
            seen, frontier = set(), list(users.get(g, ()))
            parked = True
            while frontier:
                cur = frontier.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                if not moves(cur):
                    parked = False
                    break
                frontier.extend(users.get(cur, ()))
            if parked and seen:
                registers += 1
    return registers


def tp_ring_evidence(hlo_text, mesh=None):
    """Structural double-buffering check for the tp_overlap rings: inside
    some while-loop body that performs both a collective-permute and
    matmuls, at least one permute's result never feeds this iteration's
    compute — its only transitive users are data-movement ops ending at
    the carry tuple. That is the parked ring hop: the block in transit is
    consumed only by the NEXT iteration's partial matmul, so the hop
    rides under the matmul on the block already in hand. Returns the
    count of such parked hops (the permute-flavored sibling of
    ``zero3_prefetch_evidence``). With ``mesh``, only TP-ATTRIBUTED
    permutes count — a parked pipeline-stage or cp-ring hop must not
    stand in for the tp ring's own double buffering."""
    from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS

    maps = _mesh_coord_maps(mesh)
    comps = list(_computations(hlo_text))
    move_only = {}
    for name, lines in comps:
        ok = True
        for line in lines:
            m = _INSTR_RE.match(line)
            if m is None:
                continue
            km = _RHS_OP_RE.match(m.group(3))
            if km is None or km.group(1) not in _MOVE_OPS:
                ok = False
                break
        move_only[name] = ok

    parked = 0
    for name, lines in comps:
        users, kinds, dots, hops, calls = {}, {}, set(), [], {}
        for line in lines:
            m = _INSTR_RE.match(line)
            if m is None:
                continue
            iname, rhs = m.group(2), m.group(3)
            for op in _REF_RE.findall(rhs):
                if op != iname:
                    users.setdefault(op, set()).add(iname)
            km = _RHS_OP_RE.match(rhs)
            kinds[iname] = km.group(1) if km else "?"
            if kinds[iname] == "fusion":
                fm = _CALLS_RE.search(rhs)
                if fm:
                    calls[iname] = fm.group(1)
            cm = _COLL_RE.search(line)
            if cm is not None and cm.group("op") == "collective-permute" \
                    and cm.group("suffix") != "-done":
                if maps is None or _attribute_pairs(
                    _parse_pairs(line), maps,
                    "use_global_device_ids=true" in line,
                ) == TP_AXIS:
                    hops.append(iname)
            if _DOT_RE.search(line):
                dots.add(iname)
        if not hops or not dots:
            continue

        def moves(iname):
            kind = kinds.get(iname)
            if kind == "fusion":
                return move_only.get(calls.get(iname, ""), False)
            if kind == "collective-permute-done":
                return True
            return kind in _MOVE_OPS

        for h in hops:
            seen, frontier = set(), list(users.get(h, ()))
            ok = True
            while frontier:
                cur = frontier.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                if not moves(cur):
                    ok = False
                    break
                frontier.extend(users.get(cur, ()))
            if ok and seen:
                parked += 1
    return parked


#: op_name path markers of the per-layer block family (the overlapped
#: path): the nn transformer's scanned stack and the zoo stack. A tp
#: all-gather whose op_name carries one of these belongs to a block
#: matmul the ring was supposed to decompose; collectives at the
#: embed/head/optimizer boundary (tied LM-head dot, token-id gathers,
#: param-update resharding GSPMD chooses on its own) are reported
#: separately and allowed.
_LAYER_PATH_MARKERS = ("seq_layers/", "/layers/", "layers/block")


def tp_overlap_report(hlo_text, mesh=None):
    """Overlapped-tensor-parallelism report over the compiled program
    (``tp_overlap: ring``): the decomposed-ppermute census attributed to
    the tp axis, the parked-hop double-buffering evidence, and the
    residual synchronous tp collectives the ring is supposed to have
    eliminated. ``overlap_evidence`` is the gate the golden commits to:
    parked hops present AND zero residual tp all-gathers on the
    overlapped path (the per-layer block family — boundary collectives
    at embed/head/optimizer are reported as ``tp_boundary_*``). Bytes
    are per-device result payloads, the census convention."""
    from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS

    maps = _mesh_coord_maps(mesh)
    report = {
        "ring_permute_ops": 0, "ring_permute_bytes": 0,
        "tp_allgather_ops": 0, "tp_allgather_bytes": 0,
        "tp_boundary_allgather_ops": 0, "tp_boundary_allgather_bytes": 0,
        "tp_reduce_scatter_ops": 0, "tp_allreduce_ops": 0,
    }
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None or m.group("suffix") == "-done":
            continue
        op = m.group("op")
        use_global = "use_global_device_ids=true" in line
        if op == "collective-permute":
            axis = _attribute_pairs(_parse_pairs(line), maps, use_global)
        else:
            groups = _parse_replica_groups(line)
            if groups is None:
                axis = "unattributed"
            elif groups == "all":
                axis = "world"
            else:
                axis = _attribute_groups(groups, mesh, maps, use_global)
        if axis != TP_AXIS:
            continue
        nbytes = _shape_bytes(m.group("shape"))
        if op == "collective-permute":
            report["ring_permute_ops"] += 1
            report["ring_permute_bytes"] += nbytes
        elif op == "all-gather":
            onm = _OP_NAME_RE.search(line)
            in_layer = bool(onm) and any(
                marker in onm.group(1) for marker in _LAYER_PATH_MARKERS
            )
            key = "tp_allgather" if in_layer else "tp_boundary_allgather"
            report[f"{key}_ops"] += 1
            report[f"{key}_bytes"] += nbytes
        elif op == "reduce-scatter":
            report["tp_reduce_scatter_ops"] += 1
        elif op == "all-reduce":
            report["tp_allreduce_ops"] += 1
    report["parked_hops"] = tp_ring_evidence(hlo_text, mesh=mesh)
    # Known limitation: tp all-REDUCES cannot enter this gate — a clean
    # ring program legitimately carries them (replicated-param grads:
    # layernorms, biases, the embed/head boundary), and HLO offers no
    # robust marker separating those from a row-parallel matmul that
    # fell back to its synchronous all-reduce. Indivisible-geometry
    # fallbacks are therefore surfaced by the collective_matmul
    # warn-once logs and the census's tp_allreduce_ops count (pinned by
    # the golden), not by this boolean.
    report["overlap_evidence"] = bool(
        report["ring_permute_ops"] > 0
        and report["parked_hops"] > 0
        and report["tp_allgather_ops"] == 0
        and report["tp_reduce_scatter_ops"] == 0
    )
    return report


def _tp_overlap_mode(cfg):
    """The CANONICAL tp_overlap mode (collective_matmul.tp_overlap_mode):
    "off" whenever the knob cannot shape the program (tp=1, cp>1 — the
    documented, warned fallbacks). The audit gates on this, like the
    step-cache key and exec-cache knob facts, so an intentionally
    disabled ring never triggers the missing_tp_ring class."""
    from smdistributed_modelparallel_tpu.ops.collective_matmul import (
        tp_overlap_mode,
    )

    return tp_overlap_mode(cfg) if cfg is not None else "off"


def _tp_overlap_findings(tp_block, cfg, mesh):
    """The neutered-ring class: a program built under ``tp_overlap:
    ring`` whose census shows ZERO tp-axis collective-permutes — the
    ring decomposition silently did not lower (a neutered constraint, a
    fallen-back call site) and the layers are back on synchronous GSPMD
    collectives. Residual LAYER-PATH tp all-gathers alongside a
    requested ring are a second finding (the overlap claim does not
    hold for those bytes); boundary collectives (embed/head/optimizer)
    are reported in the ``tp_overlap`` block but never flagged."""
    from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS

    findings = []
    if tp_block is None:
        return findings
    mode = _tp_overlap_mode(cfg)
    tp = int(getattr(cfg, "tensor_parallel_degree", 1) or 1) if cfg else 1
    mesh_tp = dict(mesh.shape).get(TP_AXIS, 1) if mesh is not None else 1
    if mode != "ring" or tp <= 1 or mesh_tp <= 1:
        return findings
    ag_ops = tp_block.get("tp_allgather_ops", 0)
    ag_bytes = tp_block.get("tp_allgather_bytes", 0)
    if tp_block.get("ring_permute_ops", 0) == 0:
        findings.append({
            "kind": "missing_tp_ring",
            "tensor": "(tp matmul family)",
            "bytes": ag_bytes,
            "bytes_wasted": 0,
            "detail": (
                "tp_overlap=ring but the compiled program has 0 tp-axis "
                "collective-permutes: the ring decomposition did not "
                "lower and the tp matmuls are back on synchronous GSPMD "
                "collectives"
            ),
        })
    if ag_ops > 0:
        findings.append({
            "kind": "tp_residual_allgather",
            "tensor": "(tp layer blocks)",
            "bytes": ag_bytes,
            "bytes_wasted": 0,
            "detail": (
                f"tp_overlap=ring but {ag_ops} tp-axis all-gather(s) "
                "remain on the layer-block path "
                f"({ag_bytes} bytes/device stay synchronous on the "
                "critical path)"
            ),
        })
    return findings


def zero_report(hlo_text, mesh=None):
    """ZeRO-3 collective-traffic report over the compiled program: rdp-axis
    parameter-gather and gradient-scatter volume, how much of it is issued
    inside loop bodies (where it can overlap the loop's compute — the
    epilogue position on the critical tail cannot), and the structural
    double-buffering evidence from ``zero3_prefetch_evidence``. Bytes are
    per-device result payloads, same convention as the census."""
    from smdistributed_modelparallel_tpu.backend.topology import RDP_AXIS

    maps = _mesh_coord_maps(mesh)
    totals = {
        "gather_ops": 0, "gather_bytes": 0,
        "scatter_ops": 0, "scatter_bytes": 0,
        "allreduce_ops": 0, "allreduce_bytes": 0,
    }
    interior_bytes = total_gs_bytes = 0
    loop_gathers = 0
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None or m.group("suffix") == "-done":
            continue
        op = m.group("op")
        if op not in ("all-gather", "reduce-scatter", "all-reduce"):
            continue
        groups = _parse_replica_groups(line)
        use_global = "use_global_device_ids=true" in line
        if groups is None:
            axis = "unattributed"
        elif groups == "all":
            axis = "world"
        else:
            axis = _attribute_groups(groups, mesh, maps, use_global)
        if axis != RDP_AXIS:
            continue
        nbytes = _shape_bytes(m.group("shape"))
        onm = _OP_NAME_RE.search(line)
        in_loop = bool(onm and "while" in onm.group(1))
        if op == "all-gather":
            totals["gather_ops"] += 1
            totals["gather_bytes"] += nbytes
            loop_gathers += int(in_loop)
        elif op == "reduce-scatter":
            totals["scatter_ops"] += 1
            totals["scatter_bytes"] += nbytes
        else:
            totals["allreduce_ops"] += 1
            totals["allreduce_bytes"] += nbytes
            continue  # all-reduce volume is reported but not "overlap"
        total_gs_bytes += nbytes
        if in_loop:
            interior_bytes += nbytes
    totals["loop_gather_ops"] = loop_gathers
    totals["overlap_fraction"] = round(
        interior_bytes / total_gs_bytes, 4
    ) if total_gs_bytes else 0.0
    totals["prefetch_registers"] = zero3_prefetch_evidence(hlo_text)
    return totals


def memory_breakdown(compiled):
    """XLA buffer-assignment byte classes of a compiled executable, or
    ``{}`` when the backend won't say."""
    out = {}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return out
    if ma is None:
        return out
    for attr, key in (
        ("argument_size_in_bytes", "argument_bytes"),
        ("output_size_in_bytes", "output_bytes"),
        ("temp_size_in_bytes", "temp_bytes"),
        ("alias_size_in_bytes", "alias_bytes"),
        ("generated_code_size_in_bytes", "generated_code_bytes"),
    ):
        v = getattr(ma, attr, None)
        if v is not None:
            out[key] = int(v)
    if {"argument_bytes", "output_bytes", "temp_bytes"} <= out.keys():
        out["total_bytes"] = (
            out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        )
    return out


# ----------------------------------------------------------------------
# Sharding / replication detector
# ----------------------------------------------------------------------


def _spec_partitions(sharding, mesh):
    """How many ways a NamedSharding's spec splits the value (1 ==
    effectively replicated intent)."""
    spec = getattr(sharding, "spec", None)
    if spec is None or mesh is None:
        return 1
    n = 1
    sizes = dict(mesh.shape)
    for entry in spec:
        if entry is None:
            continue
        for axis in entry if isinstance(entry, tuple) else (entry,):
            if isinstance(axis, str):
                n *= sizes.get(axis, 1)
    return n


def _leaf_path(path):
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def _param_findings(params, expected_shardings, mesh, min_bytes):
    """Partitioner said partitioned, realized array is replicated."""
    findings = []
    if params is None or expected_shardings is None:
        return findings
    try:
        exp_leaves = jax.tree_util.tree_leaves(expected_shardings)
        par = jax.tree_util.tree_flatten_with_path(params)[0]
    except Exception:
        return findings
    if len(exp_leaves) != len(par):
        return findings
    for (path, leaf), want in zip(par, exp_leaves):
        nparts = _spec_partitions(want, mesh)
        if nparts <= 1:
            continue
        realized = getattr(leaf, "sharding", None)
        nbytes = int(getattr(leaf, "nbytes", 0) or 0)
        if realized is None or nbytes < min_bytes:
            continue
        try:
            replicated = realized.is_fully_replicated
        except Exception:
            continue
        if replicated:
            findings.append({
                "kind": "replicated_param",
                "tensor": _leaf_path(path),
                "bytes": nbytes,
                "bytes_wasted": int(nbytes * (nparts - 1) / nparts),
                "detail": f"partitioner assigned {nparts}-way sharding; "
                          "realized input is fully replicated",
            })
    return findings


def _grads_findings(compiled, params, expected_shardings, mesh, min_bytes):
    """Gradient outputs replicated where their parameter is partitioned.
    The step runner's first output is the grads tree (mirrors params)."""
    findings = []
    if params is None or expected_shardings is None:
        return findings
    try:
        out_shardings = compiled.output_shardings
        grads_sub = out_shardings[0]
        if grads_sub is None:
            return findings
        grads_leaves = jax.tree_util.tree_leaves(
            grads_sub, is_leaf=lambda x: hasattr(x, "is_fully_replicated")
        )
        exp_leaves = jax.tree_util.tree_leaves(expected_shardings)
        par = jax.tree_util.tree_flatten_with_path(params)[0]
    except Exception:
        return findings
    if len(grads_leaves) != len(par) or len(exp_leaves) != len(par):
        return findings
    for (path, leaf), want, got in zip(par, exp_leaves, grads_leaves):
        nparts = _spec_partitions(want, mesh)
        nbytes = int(getattr(leaf, "nbytes", 0) or 0)
        if nparts <= 1 or nbytes < min_bytes:
            continue
        try:
            replicated = got.is_fully_replicated
        except Exception:
            continue
        if replicated:
            findings.append({
                "kind": "replicated_grad_output",
                "tensor": _leaf_path(path),
                "bytes": nbytes,
                "bytes_wasted": int(nbytes * (nparts - 1) / nparts),
                "detail": f"parameter is {nparts}-way partitioned but its "
                          "gradient output is fully replicated",
            })
    return findings


def _loop_findings(hlo_text, census, cfg, mesh):
    """The PR-5 class: pipelined program with zero pp-axis permutes ->
    the tick loop is replicated across the pipeline axis."""
    from smdistributed_modelparallel_tpu.backend.topology import PP_AXIS

    findings = []
    pp = int(getattr(cfg, "pipeline_parallel_degree", 1) or 1) if cfg else 1
    mesh_pp = dict(mesh.shape).get(PP_AXIS, 1) if mesh is not None else 1
    if pp <= 1 or mesh_pp <= 1:
        return findings
    permutes = census.get("collective-permute", {})
    pp_permutes = permutes.get("axes", {}).get(PP_AXIS, {}).get("count", 0)
    if pp_permutes > 0:
        return findings
    carries = while_carries(hlo_text)
    carry = carries[0] if carries else None
    carry_bytes = carry["bytes"] if carry else 0
    findings.append({
        "kind": "replicated_loop_carry",
        "tensor": carry["op_name"] if carry else "(no while found)",
        "bytes": carry_bytes,
        "bytes_wasted": int(carry_bytes * (pp - 1) / pp),
        "detail": (
            f"pipeline_parallel_degree={pp} but the compiled program has "
            "0 pp-axis collective-permutes: GSPMD replicated the tick "
            "loop (every device computes every stage)"
        ),
    })
    return findings


def serving_kv_findings(compiled, mesh, cache_template=None,
                        min_bytes=1024):
    """Replication detector for the serving programs' paged KV pool
    (``smp.serving``): under a tp > 1 mesh every ``pool_key`` /
    ``pool_value`` output leaf must be tp-partitioned on its head axis
    (the ``PagedKVCache`` sharding contract) — a replicated pool
    multiplies the dominant serving HBM cost by tp. ``cache_template``
    (shape/dtype tree of the engine's cache) sizes the wasted bytes; the
    detector itself reads the compiled program's output shardings, so it
    audits fresh compiles and deserialized exec-cache hits alike."""
    from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS

    findings = []
    tp = dict(mesh.shape).get(TP_AXIS, 1) if mesh is not None else 1
    if tp <= 1:
        return findings
    sizes = {}
    if cache_template is not None:
        try:
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                cache_template
            )[0]:
                name = _leaf_path(path)
                size = 1
                for d in leaf.shape:
                    size *= int(d)
                sizes[name] = size * jnp_dtype_bytes(leaf.dtype)
        except Exception:
            sizes = {}
    try:
        out_shardings = compiled.output_shardings
        leaves = jax.tree_util.tree_flatten_with_path(
            out_shardings, is_leaf=lambda x: hasattr(x, "is_fully_replicated")
        )[0]
    except Exception:
        return findings
    for path, sharding in leaves:
        name = _leaf_path(path)
        if "pool_key" not in name and "pool_value" not in name:
            continue
        try:
            replicated = sharding.is_fully_replicated
        except Exception:
            continue
        if not replicated:
            continue
        nbytes = 0
        for known, size in sizes.items():
            if name.endswith(known) or known.endswith(name):
                nbytes = size
                break
        if sizes and nbytes < min_bytes:
            continue
        findings.append({
            "kind": "replicated_kv_cache",
            "tensor": name,
            "bytes": nbytes,
            "bytes_wasted": int(nbytes * (tp - 1) / tp),
            "detail": (
                f"tensor_parallel_degree={tp} but the paged KV pool "
                "output is fully replicated (expected head-axis tp "
                "sharding)"
            ),
        })
    return findings


def jnp_dtype_bytes(dtype):
    try:
        import numpy as np

        return int(np.dtype(dtype).itemsize)
    except Exception:
        return 4


# ----------------------------------------------------------------------
# Low-precision (fp8) evidence census + the silently-upcast detector
# ----------------------------------------------------------------------

_F8_E4M3_RE = re.compile(r"f8e4m3", re.IGNORECASE)
_F8_E5M2_RE = re.compile(r"f8e5m2", re.IGNORECASE)
_HLO_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(\S+)")
_HLO_SHAPE_RE = re.compile(r"\[([\d,]*)\]")


def _shape_elements(type_str):
    m = _HLO_SHAPE_RE.search(type_str)
    if not m or not m.group(1):
        return 1
    n = 1
    for d in m.group(1).split(","):
        n *= int(d)
    return n


def quant_report(hlo_text):
    """fp8 evidence census over the compiled HLO (matmul_precision:
    fp8 programs only — the block is additive, so every bf16
    fingerprint is unchanged).

    - ``native_f8_dots``: dot/convolution lines consuming f8-typed
      operands directly — what an f8-capable TPU MXU lowers to.
    - ``fp8_origin_dots``: dots whose operands are one-hop ``convert``
      upcasts OF an f8 value — XLA:CPU's legalization (it upcasts f8
      operands to f32 before the dot). The VALUES flowing through are
      still the quantized grid, so CPU-smoke programs count here.
    - ``f8_casts``: value-producing ops with an f8 result type, by
      format (e4m3 forward operands, e5m2 backward cotangents).

    A quantized program shows nonzero evidence in at least one bucket;
    all-zero under mode=fp8 is the ``quant_upcast`` finding."""
    casts = {"e4m3": 0, "e5m2": 0}
    f8_names = set()
    upcast_names = set()
    native_dots = 0
    origin_dots = 0
    for line in hlo_text.splitlines():
        m = _HLO_DEF_RE.match(line)
        if not m:
            continue
        name, out_type = m.group(1), m.group(2)
        out_f8 = bool(
            _F8_E4M3_RE.search(out_type) or _F8_E5M2_RE.search(out_type)
        )
        if out_f8:
            f8_names.add(name)
            if _F8_E4M3_RE.search(out_type):
                casts["e4m3"] += 1
            else:
                casts["e5m2"] += 1
        body = line[m.end(2):]
        if "convert(" in body and not out_f8:
            # Upcast convert FROM f8: operand type printed inline, or the
            # operand name is a known f8 producer.
            if (_F8_E4M3_RE.search(body) or _F8_E5M2_RE.search(body)
                    or any(
                        op in f8_names
                        for op in re.findall(r"%([\w.\-]+)", body)
                    )):
                upcast_names.add(name)
        if " dot(" in line or re.search(r"\bdot\(", body):
            ops = re.findall(r"%([\w.\-]+)", body)
            if (_F8_E4M3_RE.search(body) or _F8_E5M2_RE.search(body)
                    or any(op in f8_names for op in ops)):
                native_dots += 1
            elif any(op in upcast_names for op in ops):
                origin_dots += 1
    return {
        "native_f8_dots": native_dots,
        "fp8_origin_dots": origin_dots,
        "f8_casts": casts,
    }


def _largest_wide_dot(hlo_text):
    """(name, elements) of the biggest dot with non-f8 operands — the
    one the quant_upcast finding names as the likeliest missed seam."""
    best = None
    for line in hlo_text.splitlines():
        m = _HLO_DEF_RE.match(line)
        if not m:
            continue
        body = line[m.end(2):]
        if not re.search(r"\bdot\(", body):
            continue
        if _F8_E4M3_RE.search(line) or _F8_E5M2_RE.search(line):
            continue
        n = _shape_elements(m.group(2))
        if best is None or n > best[1]:
            best = (m.group(1), n)
    return best


def _quant_findings(quant_block, hlo_text):
    """The silently-upcast-matmul detector: mode=fp8 promised f8 dots
    but the compiled program carries ZERO fp8 evidence — no native f8
    dot, no fp8-origin dot, no f8 cast. That is the quantization
    equivalent of the missing_tp_ring finding: the knob was paid for
    (scale state threaded, cache keys split) and silently bought
    nothing."""
    findings = []
    if quant_block is None:
        return findings
    if (quant_block["native_f8_dots"] or quant_block["fp8_origin_dots"]
            or any(quant_block["f8_casts"].values())):
        return findings
    wide = _largest_wide_dot(hlo_text)
    return [{
        "kind": "quant_upcast",
        "tensor": wide[0] if wide else "*",
        "bytes_wasted": 0,
        "detail": (
            "matmul_precision=fp8 but the compiled program contains no "
            "f8 evidence at all (no f8-operand dot, no fp8-origin dot, "
            "no f8 cast) — every seam dispatched the full-precision "
            "path"
            + (f"; largest full-precision dot: %{wide[0]} "
               f"({wide[1]} elements)" if wide else "")
        ),
    }]


# ----------------------------------------------------------------------
# The audit itself
# ----------------------------------------------------------------------


class ProgramAudit:
    """Structured audit of one compiled step program."""

    def __init__(self, name, key, census, remat, memory, findings,
                 flops, bytes_accessed, hlo_sha256, config, zero=None,
                 recompute=None, tp_overlap=None, quant=None):
        self.name = name
        self.key = key
        self.census = census
        self.remat = remat
        self.memory = memory
        self.findings = findings
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.hlo_sha256 = hlo_sha256
        self.config = config
        self.zero = zero
        self.recompute = recompute
        self.tp_overlap = tp_overlap
        self.quant = quant
        self.fingerprint = self._fingerprint()
        self.fingerprint_hash = fingerprint_hash(self.fingerprint)

    # -- census queries -------------------------------------------------

    def collective_count(self, op, axis=None):
        ent = self.census.get(op, {})
        if axis is None:
            return ent.get("count", 0)
        return ent.get("axes", {}).get(axis, {}).get("count", 0)

    def collective_bytes(self, op, axis=None):
        ent = self.census.get(op, {})
        if axis is None:
            return ent.get("bytes", 0)
        return ent.get("axes", {}).get(axis, {}).get("bytes", 0)

    @property
    def replicated_bytes(self):
        return sum(f.get("bytes_wasted", 0) for f in self.findings)

    # -- export ---------------------------------------------------------

    def _fingerprint(self):
        fp = {
            "name": self.name,
            "key": self.key,
            "config": self.config,
            "collectives": self.census,
            "replicated": self.findings,
            "replicated_bytes": self.replicated_bytes,
            "remat": self.remat,
            "memory": self.memory,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "hlo_sha256": self.hlo_sha256,
        }
        # Additive: only zero3 programs carry the block, so fingerprints
        # (and committed goldens) of every other program are unchanged.
        if self.zero is not None:
            fp["zero"] = self.zero
        # Additive likewise: only builds under a non-default recompute
        # plan carry the block — default-knob fingerprints are unchanged.
        if self.recompute is not None:
            fp["recompute"] = self.recompute
        # Additive likewise: only tp_overlap != "off" programs carry the
        # ring census/overlap-evidence block.
        if self.tp_overlap is not None:
            fp["tp_overlap"] = self.tp_overlap
        # Additive likewise: only matmul_precision=fp8 step programs
        # carry the fp8 evidence census.
        if self.quant is not None:
            fp["quant"] = self.quant
        return fp

    def as_dict(self):
        d = dict(self.fingerprint)
        d["fingerprint"] = self.fingerprint_hash
        return d


def _config_snapshot(cfg):
    if cfg is None:
        return {}
    snap = {
        "pipeline": getattr(cfg, "pipeline", None),
        "pp": getattr(cfg, "pipeline_parallel_degree", 1),
        "tp": getattr(cfg, "tensor_parallel_degree", 1),
        "v": getattr(cfg, "virtual_pipeline_degree", 1),
        "mb": getattr(cfg, "microbatches", 1),
    }
    # Additive (default omitted) so pre-zero3 fingerprints stay stable.
    sharded = getattr(cfg, "sharded_params", "none")
    if sharded and sharded != "none":
        snap["sharded_params"] = sharded
    # Additive likewise for the recompute knob (default "full" omitted).
    recompute = getattr(cfg, "recompute", "full")
    if recompute and recompute != "full":
        snap["recompute"] = recompute
    # Additive likewise for overlapped tp (default "off" omitted; the
    # CANONICAL mode, so a knob that cannot shape the program — tp=1,
    # cp>1 — never enters the snapshot).
    tp_overlap = _tp_overlap_mode(cfg)
    if tp_overlap != "off":
        snap["tp_overlap"] = tp_overlap
    # Additive likewise for the quant knob family (bf16/none omitted).
    try:
        from smdistributed_modelparallel_tpu import quant as _quant

        mode = _quant.matmul_precision_mode(cfg)
        if mode != "bf16":
            snap["matmul_precision"] = mode
        if _quant.kv_quant_mode() != "none":
            snap["kv_quant"] = _quant.kv_quant_mode()
        if _quant.decode_weights_mode() != "none":
            snap["decode_weights"] = _quant.decode_weights_mode()
    except Exception:  # pragma: no cover - defensive
        pass
    return snap


def fingerprint_hash(fp):
    """Short stable hash of the structured summary. Content-hash fields
    (``hlo_sha256``) are folded in; byte-identical programs hash equal,
    and any census/finding/memory movement changes it."""
    payload = json.dumps(fp, sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def cache_key_hash(key):
    """Stable-enough digest of the step engine's compile-cache key (its
    repr covers treedefs, shapes, flags)."""
    return hashlib.sha256(repr(key).encode()).hexdigest()[:16]


def audit_compiled(name, compiled, key=None, params=None,
                   expected_param_shardings=None, mesh=None, cfg=None,
                   min_bytes=1024, publish=True, persist=True,
                   extra_findings_fn=None, tp_ring_expected=None):
    """Run the full audit over one compiled executable. Explicit calls
    always run (the ``SMP_HLO_AUDIT`` gate lives in ``maybe_audit``)."""
    from smdistributed_modelparallel_tpu.backend.state import state

    # Uninitialized framework (offline audits, e.g. of a deserialized
    # executable outside a training session): audit without mesh/config
    # attribution rather than refuse.
    try:
        mesh = mesh if mesh is not None else state.mesh
        cfg = cfg if cfg is not None else state.cfg
    except Exception:
        pass
    text = compiled.as_text()
    census = collective_census(text, mesh=mesh)
    remat = remat_census(text)
    memory = memory_breakdown(compiled)
    zero = None
    if bool(getattr(cfg, "zero3_enabled", False)):
        zero = zero_report(text, mesh=mesh)
    # ``tp_ring_expected=False`` marks a program family the ring never
    # lowers into by design (the serving engine's decode/prefill
    # programs: decode-guarded attention, S=1 fallbacks) — no census, no
    # gauges, and crucially no missing_tp_ring false alarm for it.
    tp_overlap = None
    if _tp_overlap_mode(cfg) != "off" and tp_ring_expected is not False:
        tp_overlap = tp_overlap_report(text, mesh=mesh)
    # fp8 evidence census: training step programs only (serving/decode
    # programs never dispatch the fp8 seams — ``tp_ring_expected=False``
    # marks that family, exactly as for the ring detector).
    quant = None
    try:
        from smdistributed_modelparallel_tpu import quant as _quant_mod

        if (_quant_mod.matmul_precision_mode(cfg) != "bf16"
                and tp_ring_expected is not False):
            quant = quant_report(text)
    except Exception:  # pragma: no cover - defensive
        pass
    recompute = None
    try:
        from smdistributed_modelparallel_tpu.parallel import (
            remat_plan as _remat_plan,
        )

        recompute = _remat_plan.active_for(cfg)
    except Exception:  # pragma: no cover - defensive
        pass
    findings = []
    findings += _param_findings(
        params, expected_param_shardings, mesh, min_bytes
    )
    findings += _grads_findings(
        compiled, params, expected_param_shardings, mesh, min_bytes
    )
    findings += _loop_findings(text, census, cfg, mesh)
    findings += _tp_overlap_findings(tp_overlap, cfg, mesh)
    findings += _quant_findings(quant, text)
    if extra_findings_fn is not None:
        # Program-owner-specific detectors (e.g. the serving engine's
        # replicated-KV-pool check) — run on whatever executable is being
        # audited, fresh compile or deserialized cache hit.
        try:
            findings += list(extra_findings_fn(compiled, mesh) or [])
        except Exception as e:  # pragma: no cover - defensive
            logger.warning("[xray] %s: extra findings pass failed: %s",
                           name, e)
    flops = bytes_accessed = None
    try:
        from smdistributed_modelparallel_tpu.utils.profiling import cost_of

        flops, bytes_accessed = cost_of(compiled)
    except Exception:
        pass
    hlo_sha = hashlib.sha256(
        _METADATA_RE.sub("", text).encode()
    ).hexdigest()
    audit = ProgramAudit(
        name, key, census, remat, memory, findings, flops, bytes_accessed,
        hlo_sha, _config_snapshot(cfg), zero=zero, recompute=recompute,
        tp_overlap=tp_overlap, quant=quant,
    )
    if publish:
        # Unpublished audits stay out of the registry too: a verification
        # pass over a candidate executable (exec-cache load) must not
        # register a program that may then be rejected — republish()
        # registers it after the veto point.
        audits[name] = audit
        _publish(audit)
    if persist:
        _persist(audit)
    for f in findings:
        logger.warning(
            "[xray] %s: %s %s (%s wasted bytes): %s",
            name, f["kind"], f["tensor"], f.get("bytes_wasted"), f["detail"],
        )
    return audit


def maybe_audit(name, compiled, key=None, params=None,
                expected_param_shardings=None, extra_findings_fn=None,
                tp_ring_expected=None):
    """Post-compile hook from the step engine. ``SMP_HLO_AUDIT=off`` is a
    hard no-op (returns before touching the executable); failures are
    logged, never raised into the step path."""
    if not enabled():
        return None
    t0 = time.perf_counter()
    try:
        audit = audit_compiled(
            name, compiled, key=key, params=params,
            expected_param_shardings=expected_param_shardings,
            extra_findings_fn=extra_findings_fn,
            tp_ring_expected=tp_ring_expected,
        )
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("[xray] hlo audit of %s failed: %s", name, e)
        return None
    _count_audit(audit, time.perf_counter() - t0)
    return audit


def _count_audit(audit, seconds):
    """Shared publication tail: the audit counters + the flight-recorder
    compile event carrying the program fingerprint. Used by both the
    fresh-compile path (maybe_audit) and the verified-cache-hit path
    (republish) so the two can never diverge."""
    telemetry.counter(
        "smp_hlo_audits_total", "completed post-compile HLO audits"
    ).inc()
    telemetry.counter(
        "smp_hlo_audit_seconds_total",
        "host seconds spent in post-compile HLO audits",
    ).inc(seconds)
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )

    flight_recorder.record_compile(
        "hlo_audit", audit.name, seconds, fingerprint=audit.fingerprint_hash
    )


def republish(audit, seconds=0.0):
    """Re-publish a verified audit along the exact channels a fresh
    compile's ``maybe_audit`` uses: gauges, persistence, the audit
    registry, the audit counters, and the flight-recorder compile event
    with the program fingerprint. The executable-cache hit path calls
    this AFTER fingerprint verification so a warm start never silently
    bypasses the drift gates."""
    audits[audit.name] = audit
    _publish(audit)
    _persist(audit)
    _count_audit(audit, seconds)


#: Latest audit per program name (``step``, ``step_pipeline_1f1b``, ...).
audits = {}


def of_step_function(step_fn):
    """The audit of a ``@smp.step`` function's single compiled program —
    the stored post-compile audit when the pass ran, else computed on
    demand from the cached runner's executable. Returns None when no AOT
    executable exists (jit-fallback backends)."""
    runners = list(getattr(step_fn, "_cache", {}).values())
    if len(runners) != 1:
        raise ValueError(
            f"expected exactly one compiled program, found {len(runners)}"
        )
    runner = runners[0]
    audit = getattr(runner, "hlo_audit", None)
    if audit is not None:
        return audit
    compiled = runner.holder.get("compiled")
    if compiled is None:
        return None
    return audit_compiled(
        getattr(runner, "step_name", "step"), compiled,
        key=getattr(runner, "audit_key", None),
        publish=False, persist=False,
    )


def bench_summary(audit):
    """The compact block bench.py stamps into BENCH_r*.json."""
    if audit is None:
        return None
    return {
        "fingerprint": audit.fingerprint_hash,
        "collective_ops": {
            op: ent["count"] for op, ent in sorted(audit.census.items())
        },
        "collective_bytes": {
            op: ent["bytes"] for op, ent in sorted(audit.census.items())
        },
        "remat_fraction": audit.remat.get("fraction", 0.0),
        "replicated_bytes": audit.replicated_bytes,
    }


# ----------------------------------------------------------------------
# Fingerprint diff
# ----------------------------------------------------------------------

#: The environment-stable fingerprint subset the golden regression gates
#: compare (memory/FLOPs/hashes move with jaxlib versions; these move
#: only when the program's parallel structure does).
SEMANTIC_FIELDS = ("config", "collectives", "replicated", "remat", "zero",
                   "recompute", "tp_overlap", "quant")


def diff(a, b, fields=None, remat_tol=0.02):
    """What changed between two fingerprints, as a list of
    ``{"field", "a", "b"}`` rows (empty == clean). ``fields`` restricts
    the comparison (e.g. ``SEMANTIC_FIELDS`` for the golden gates);
    ``remat_tol`` is the absolute tolerance on the remat fraction."""
    def picked(name):
        return fields is None or name in fields

    changes = []

    def add(field, va, vb):
        changes.append({"field": field, "a": va, "b": vb})

    if picked("config"):
        ca, cb = a.get("config", {}), b.get("config", {})
        for k in sorted(set(ca) | set(cb)):
            if ca.get(k) != cb.get(k):
                add(f"config.{k}", ca.get(k), cb.get(k))
    if picked("collectives"):
        colla, collb = a.get("collectives", {}), b.get("collectives", {})
        for op in sorted(set(colla) | set(collb)):
            ea = colla.get(op, {"count": 0, "bytes": 0, "axes": {}})
            eb = collb.get(op, {"count": 0, "bytes": 0, "axes": {}})
            axes = sorted(set(ea.get("axes", {})) | set(eb.get("axes", {})))
            for axis in axes:
                xa = ea.get("axes", {}).get(axis, {"count": 0, "bytes": 0})
                xb = eb.get("axes", {}).get(axis, {"count": 0, "bytes": 0})
                for k in ("count", "bytes"):
                    if xa.get(k, 0) != xb.get(k, 0):
                        add(f"collectives.{op}.{axis}.{k}",
                            xa.get(k, 0), xb.get(k, 0))
    if picked("replicated"):
        ra = a.get("replicated_bytes", 0)
        rb = b.get("replicated_bytes", 0)
        if ra != rb:
            add("replicated_bytes", ra, rb)
        na, nb = len(a.get("replicated", [])), len(b.get("replicated", []))
        if na != nb:
            add("replicated_findings", na, nb)
    if picked("remat"):
        fa = a.get("remat", {}).get("fraction", 0.0)
        fb = b.get("remat", {}).get("fraction", 0.0)
        if abs((fa or 0.0) - (fb or 0.0)) > remat_tol:
            add("remat.fraction", fa, fb)
    if picked("zero"):
        za, zb = a.get("zero") or {}, b.get("zero") or {}
        for k in sorted(set(za) | set(zb)):
            if za.get(k) != zb.get(k):
                add(f"zero.{k}", za.get(k), zb.get(k))
    if picked("recompute"):
        ra, rb = a.get("recompute") or {}, b.get("recompute") or {}
        for k in sorted(set(ra) | set(rb)):
            if ra.get(k) != rb.get(k):
                add(f"recompute.{k}", ra.get(k), rb.get(k))
    if picked("tp_overlap"):
        ta, tb = a.get("tp_overlap") or {}, b.get("tp_overlap") or {}
        for k in sorted(set(ta) | set(tb)):
            if ta.get(k) != tb.get(k):
                add(f"tp_overlap.{k}", ta.get(k), tb.get(k))
    if picked("quant"):
        # Evidence presence, not exact counts: cast/dot tallies move with
        # jaxlib fusion decisions; whether a bucket holds f8 evidence at
        # all only moves when the program's quantization does.
        qa, qb = a.get("quant") or {}, b.get("quant") or {}
        if bool(qa) != bool(qb):
            add("quant.present", bool(qa), bool(qb))
        elif qa:
            for k in ("native_f8_dots", "fp8_origin_dots"):
                if bool(qa.get(k)) != bool(qb.get(k)):
                    add(f"quant.{k}", qa.get(k), qb.get(k))
            fa_, fb_ = qa.get("f8_casts") or {}, qb.get("f8_casts") or {}
            for k in sorted(set(fa_) | set(fb_)):
                if bool(fa_.get(k)) != bool(fb_.get(k)):
                    add(f"quant.f8_casts.{k}", fa_.get(k), fb_.get(k))
    if picked("memory"):
        ma, mb = a.get("memory", {}), b.get("memory", {})
        for k in sorted(set(ma) | set(mb)):
            if ma.get(k) != mb.get(k):
                add(f"memory.{k}", ma.get(k), mb.get(k))
    if picked("flops"):
        if a.get("flops") != b.get("flops"):
            add("flops", a.get("flops"), b.get("flops"))
    if picked("hlo_sha256"):
        if a.get("hlo_sha256") != b.get("hlo_sha256"):
            add("hlo_sha256", a.get("hlo_sha256"), b.get("hlo_sha256"))
    return changes


# ----------------------------------------------------------------------
# Telemetry + persistence
# ----------------------------------------------------------------------


def _publish(audit):
    lab = dict(step=audit.name)
    for op, ent in audit.census.items():
        for axis, ax in ent["axes"].items():
            telemetry.gauge(
                "smp_hlo_collective_ops",
                "collective instruction count in the compiled program, "
                "by op kind and attributed mesh axis",
            ).labels(op=op, axis=axis, **lab).set(ax["count"])
            telemetry.gauge(
                "smp_hlo_collective_bytes",
                "per-device collective result bytes in the compiled "
                "program, by op kind and attributed mesh axis",
            ).labels(op=op, axis=axis, **lab).set(ax["bytes"])
    telemetry.gauge(
        "smp_hlo_replicated_bytes",
        "estimated per-device bytes wasted to detected replication",
    ).labels(**lab).set(audit.replicated_bytes)
    telemetry.gauge(
        "smp_hlo_replicated_findings",
        "sharding/replication findings in the compiled program",
    ).labels(**lab).set(len(audit.findings))
    telemetry.gauge(
        "smp_hlo_remat_fraction",
        "recomputed-FLOPs fraction of dot/conv instructions (static, "
        "structural-duplicate census)",
    ).labels(**lab).set(audit.remat.get("fraction", 0.0))
    for k, v in audit.memory.items():
        telemetry.gauge(
            "smp_hlo_memory_bytes",
            "XLA buffer-assignment bytes of the compiled program by class",
        ).labels(kind=k, **lab).set(v)
    if audit.zero is not None:
        from smdistributed_modelparallel_tpu.utils.telemetry import (
            record_zero3_xray,
        )

        record_zero3_xray(audit.name, audit.zero)
    if audit.tp_overlap is not None:
        from smdistributed_modelparallel_tpu.utils.telemetry import (
            record_tp_overlap_xray,
        )

        record_tp_overlap_xray(audit.name, audit.tp_overlap)


def _persist(audit):
    path = os.environ.get(AUDIT_PATH_ENV)
    if not path:
        return None
    path = telemetry._rank_path(path)
    data = {"version": 1, "programs": {}}
    try:
        with open(path, encoding="utf-8") as f:
            prev = json.load(f)
        if isinstance(prev, dict) and isinstance(prev.get("programs"), dict):
            data = prev
    except (OSError, ValueError):
        pass
    key_id = audit.name if not audit.key else f"{audit.name}@{audit.key}"
    data["programs"][key_id] = audit.as_dict()
    return _atomic_json_dump(data, path, "hlo-audit dump")
