"""Pipeline timeline: Chrome-trace (Perfetto-loadable) event recording.

Parity target: reference C++ timeline (``smp_create_timeline`` /
``smp_timeline_start_step`` / ``smp_timeline_end_step`` /
``smp_timeline_record_pipeline_event`` — SURVEY §2.1 N5, called around every
server action in ``torch/server.py:366-478``). The TPU build has no server
loop; events bracket host-side phases (trace, partition, compile, step) and
per-step device execution, and the JSON file loads in chrome://tracing or
Perfetto alongside ``jax.profiler`` traces.

Recording backend: the native C++ recorder (``native/src/timeline.cc``,
N5 rebuilt — interned strings, preallocated arena, C-side JSON
serialization) when ``libsmptpu.so`` loads; pure-Python list append
otherwise. Same API either way.

Multi-rank discipline (both backends):

- the output path is **rank-qualified** (telemetry's ``_rank_path``): N
  processes pointed at one ``SMP_TIMELINE_PATH`` on a shared filesystem
  write ``path.rank<i>`` files instead of clobbering each other;
- ``flush()`` is **atomic** (tmp file + ``os.replace``) so a concurrent
  reader — or ``scripts/trace_fuse.py`` running mid-job — never sees a
  torn JSON;
- every timeline opens with a ``smp_clock_anchor/<unix_us>/<rank>``
  instant (the wall-clock time of the timeline's t=0) and records
  ``smp_sync/<name>/<group>/<seq>`` instants at barrier exits. Encoding
  these as ordinary named instants keeps the two recording backends
  byte-compatible; ``trace_fuse.py`` parses them to align per-rank
  clocks into one fused trace.
"""

import os
import threading
import time

from smdistributed_modelparallel_tpu.utils.telemetry import (
    _atomic_json_dump,
    telemetry,
)


class Timeline:
    def __init__(self, path=None):
        raw = path or os.environ.get("SMP_TIMELINE_PATH", "")
        self.enabled = bool(raw)
        # Rank-qualify ONCE, at construction (state.initialize builds the
        # timeline after core init, so the process index is known).
        self.path = telemetry._rank_path(raw) if raw else raw
        self._events = []
        self._lock = threading.Lock()
        self._step = -1
        # Anchor: wall-clock of the timeline's t=0, captured back-to-back
        # with the monotonic origin.
        self._wall0_us = int(time.time() * 1e6)
        self._t0 = time.perf_counter()
        self._native = None
        if self.enabled:
            from smdistributed_modelparallel_tpu.backend import native

            lib = native.load()
            if lib is not None:
                # The native recorder serializes straight to the path it
                # was created with; give it the tmp name so flush() can
                # install the result atomically.
                self._native = native.NativeTimeline(lib, self._tmp_path())
            rank = telemetry.process_index
            name = (f"smp_clock_anchor/{self._wall0_us}/"
                    f"{0 if rank is None else rank}")
            # The anchor instant must carry ts=0 EXACTLY: _wall0_us is the
            # wall time of the monotonic origin, and native.load() above
            # may have burned many ms (cold dlopen/build) — recording at
            # _now_us() would skew every fused offset by that delay.
            if self._native is not None:
                self._native.record_instant(name, 0.0, "sync")
            else:
                self._events.append(
                    {"name": name, "ph": "i", "ts": 0.0, "pid": 0,
                     "tid": "sync", "s": "g"}
                )

    def _tmp_path(self):
        return f"{self.path}.tmp.{os.getpid()}"

    def _now_us(self):
        return (time.perf_counter() - self._t0) * 1e6

    def start_step(self, step):
        self._step = step
        if self._native is not None:
            self._native.start_step(step)
        self.record_instant(f"step_{step}_begin")

    def end_step(self, step):
        self.record_instant(f"step_{step}_end")
        if self._native is not None:
            self._native.end_step(step)

    def sync_mark(self, name, group, seq):
        """Barrier-exit alignment instant (see module docstring)."""
        self.record_instant(f"smp_sync/{name}/{group}/{seq}", track="sync")

    def record_event(self, name, begin_us, end_us, microbatch=None, track="pipeline"):
        if not self.enabled:
            return
        if self._native is not None:
            self._native.record_event(name, begin_us, end_us, microbatch, track)
            return
        args = {"step": self._step}
        if microbatch is not None:
            args["microbatch"] = microbatch
        with self._lock:
            self._events.append(
                {"name": name, "ph": "X", "ts": begin_us, "dur": end_us - begin_us,
                 "pid": 0, "tid": track, "args": args}
            )

    def record_instant(self, name, track="pipeline"):
        if not self.enabled:
            return
        if self._native is not None:
            self._native.record_instant(name, self._now_us(), track)
            return
        with self._lock:
            self._events.append(
                {"name": name, "ph": "i", "ts": self._now_us(), "pid": 0,
                 "tid": track, "s": "g"}
            )

    class _Span:
        def __init__(self, timeline, name, microbatch, track):
            self.timeline, self.name, self.microbatch, self.track = timeline, name, microbatch, track

        def __enter__(self):
            self.begin = self.timeline._now_us()
            return self

        def __exit__(self, *exc):
            self.timeline.record_event(
                self.name, self.begin, self.timeline._now_us(),
                microbatch=self.microbatch, track=self.track,
            )
            return False

    def span(self, name, microbatch=None, track="host"):
        return self._Span(self, name, microbatch, track)

    def flush(self):
        if not self.enabled:
            return
        if self._native is not None:
            # C-side serialization lands in the tmp name; atomic install.
            self._native.flush(pid=os.getpid())
            try:
                os.replace(self._tmp_path(), self.path)
            except OSError as e:
                from smdistributed_modelparallel_tpu.utils.logger import (
                    get_logger,
                )

                get_logger().warning(
                    "timeline flush to %s failed: %s", self.path, e
                )
            return
        if not self._events:
            return
        with self._lock:
            payload = {"traceEvents": list(self._events),
                       "displayTimeUnit": "ms"}
        # telemetry's tmp+os.replace helper: atomic, and WARNS on failure
        # (a silently missing trace is only discovered post-run).
        _atomic_json_dump(payload, self.path, "timeline flush")
