"""Pipeline timeline: Chrome-trace (Perfetto-loadable) event recording.

Parity target: reference C++ timeline (``smp_create_timeline`` /
``smp_timeline_start_step`` / ``smp_timeline_end_step`` /
``smp_timeline_record_pipeline_event`` — SURVEY §2.1 N5, called around every
server action in ``torch/server.py:366-478``). The TPU build has no server
loop; events bracket host-side phases (trace, partition, compile, step) and
per-step device execution, and the JSON file loads in chrome://tracing or
Perfetto alongside ``jax.profiler`` traces.

Recording backend: the native C++ recorder (``native/src/timeline.cc``,
N5 rebuilt — interned strings, preallocated arena, C-side JSON
serialization) when ``libsmptpu.so`` loads; pure-Python list append
otherwise. Same API either way.
"""

import json
import os
import threading
import time

class Timeline:
    def __init__(self, path=None):
        self.path = path or os.environ.get("SMP_TIMELINE_PATH", "")
        self.enabled = bool(self.path)
        self._events = []
        self._lock = threading.Lock()
        self._step = -1
        self._t0 = time.perf_counter()
        self._native = None
        if self.enabled:
            from smdistributed_modelparallel_tpu.backend import native

            lib = native.load()
            if lib is not None:
                self._native = native.NativeTimeline(lib, self.path)

    def _now_us(self):
        return (time.perf_counter() - self._t0) * 1e6

    def start_step(self, step):
        self._step = step
        if self._native is not None:
            self._native.start_step(step)
        self.record_instant(f"step_{step}_begin")

    def end_step(self, step):
        self.record_instant(f"step_{step}_end")
        if self._native is not None:
            self._native.end_step(step)

    def record_event(self, name, begin_us, end_us, microbatch=None, track="pipeline"):
        if not self.enabled:
            return
        if self._native is not None:
            self._native.record_event(name, begin_us, end_us, microbatch, track)
            return
        args = {"step": self._step}
        if microbatch is not None:
            args["microbatch"] = microbatch
        with self._lock:
            self._events.append(
                {"name": name, "ph": "X", "ts": begin_us, "dur": end_us - begin_us,
                 "pid": 0, "tid": track, "args": args}
            )

    def record_instant(self, name, track="pipeline"):
        if not self.enabled:
            return
        if self._native is not None:
            self._native.record_instant(name, self._now_us(), track)
            return
        with self._lock:
            self._events.append(
                {"name": name, "ph": "i", "ts": self._now_us(), "pid": 0,
                 "tid": track, "s": "g"}
            )

    class _Span:
        def __init__(self, timeline, name, microbatch, track):
            self.timeline, self.name, self.microbatch, self.track = timeline, name, microbatch, track

        def __enter__(self):
            self.begin = self.timeline._now_us()
            return self

        def __exit__(self, *exc):
            self.timeline.record_event(
                self.name, self.begin, self.timeline._now_us(),
                microbatch=self.microbatch, track=self.track,
            )
            return False

    def span(self, name, microbatch=None, track="host"):
        return self._Span(self, name, microbatch, track)

    def flush(self):
        if not self.enabled:
            return
        if self._native is not None:
            self._native.flush(pid=os.getpid())
            return
        if not self._events:
            return
        with self._lock:
            payload = {"traceEvents": self._events, "displayTimeUnit": "ms"}
            with open(self.path, "w") as f:
                json.dump(payload, f)
