"""Environment-driven logging for the TPU-native model-parallelism framework.

Parity target: reference ``backend/logger.py:14-122`` — a process-wide logger
whose level and per-file filtering are controlled by ``SMP_LOG_LEVEL``,
``SMP_LOG_ALLOW_FILES`` / ``SMP_LOG_BLOCK_FILES`` and ``SMP_LOG_HIDE_TIME``.
Re-designed for JAX: messages are prefixed with the JAX process index instead
of an MPI rank.
"""

import logging
import os
import sys

_LEVELS = {
    "off": logging.CRITICAL + 10,
    "fatal": logging.CRITICAL,
    "error": logging.ERROR,
    "warning": logging.WARNING,
    "info": logging.INFO,
    "debug": logging.DEBUG,
    "trace": logging.DEBUG - 5,
}

_LOGGER_NAME = "smp_tpu"
_configured = False


class _RelpathFilter(logging.Filter):
    """Attach a repo-relative pathname and honor allow/block file lists."""

    def __init__(self, allow, block):
        super().__init__()
        self.allow = allow
        self.block = block

    def filter(self, record):
        path = record.pathname.replace(os.sep, "/")
        marker = "smdistributed_modelparallel_tpu/"
        idx = path.rfind(marker)
        record.relpath = path[idx + len(marker):] if idx >= 0 else os.path.basename(path)
        name = os.path.basename(record.pathname)
        if self.allow and name not in self.allow and record.relpath not in self.allow:
            return False
        if self.block and (name in self.block or record.relpath in self.block):
            return False
        return True


def _parse_files(env_var):
    raw = os.environ.get(env_var, "")
    return {f.strip() for f in raw.split(",") if f.strip()}


def get_log_level():
    return _LEVELS.get(os.environ.get("SMP_LOG_LEVEL", "info").lower(), logging.INFO)


def get_logger():
    """Return the process-wide framework logger, configuring it on first use."""
    global _configured
    logger = logging.getLogger(_LOGGER_NAME)
    if _configured:
        return logger
    _configured = True
    logging.addLevelName(_LEVELS["trace"], "TRACE")
    logger.setLevel(get_log_level())
    logger.propagate = False
    handler = logging.StreamHandler(sys.stderr)
    hide_time = os.environ.get("SMP_LOG_HIDE_TIME", "0") in ("1", "true", "True")
    fmt = "[%(levelname)s" + ("" if hide_time else " %(asctime)s") + " %(relpath)s:%(lineno)d] %(message)s"
    handler.setFormatter(logging.Formatter(fmt, datefmt="%H:%M:%S"))
    handler.addFilter(_RelpathFilter(_parse_files("SMP_LOG_ALLOW_FILES"), _parse_files("SMP_LOG_BLOCK_FILES")))
    logger.addHandler(handler)
    return logger


def rmsg(msg):
    """Prefix a message with this process's (process_index, pp, tp, rdp) tag.

    Parity: reference ``torch/utils.py`` ``rmsg`` tags messages with
    (rank, pp_rank, tp_rank).
    """
    try:
        from smdistributed_modelparallel_tpu.backend.state import state
        if state.initialized:
            core = state.core
            return (
                f"[r{core.rank()} pp{core.pp_rank()} tp{core.tp_rank()} rdp{core.rdp_rank()}] {msg}"
            )
    except Exception:
        pass
    return f"[uninit] {msg}"
