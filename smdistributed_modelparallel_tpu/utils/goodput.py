"""Goodput ledger: exclusive-and-exhaustive wall-clock attribution.

The stack can measure latency percentiles (PR-16), fleet SLO goodput
(PR-17) and compiled-program structure, but none of that answers the
production question "where did every chip-second of this job go". This
module attributes EVERY second of the process's wall clock to exactly
one state:

======================  ================================================
state                   meaning
======================  ================================================
``step``                productive step compute (the only goodput state)
``trace``               jaxpr trace / lowering
``compile_fresh``       XLA compilation, cold
``compile_cache``       executable-cache deserialize (disk_cache hits)
``data_wait``           blocked on the input pipeline
``sync_wait``           control-plane barriers / host p2p receives
``ckpt_save``           blocking checkpoint save
``ckpt_restore``        checkpoint restore / resume
``recovery_*``          supervisor recovery phases (detect / rendezvous /
                        reshard_load / first_step)
``preempt_drain``       preemption drain + emergency-save rendezvous
``wedged``              watchdog-detected stall (or an injected wedge)
``startup``             framework bring-up (``init/*`` phases)
``idle``                none of the above
======================  ================================================

The ledger is driven from seams that already exist — the telemetry
``set_phase`` listener (chained after the flight-recorder's), the step
engine's edge hook, ``exec_cache``'s compile events, and explicit
scopes in ``checkpoint.py`` / ``resilience/preemption.py`` /
``resilience/supervisor.py`` / ``resilience/chaos.py`` — and maintains
the invariant (tested under a fake clock) that attributed seconds sum
to wall clock. It publishes ``smp_goodput_fraction`` plus the
``smp_goodput_seconds_total`` / ``smp_badput_seconds_total{state=}``
counters the fleet aggregator merges exactly like the histograms
(counter summing IS rank weighting), and every transition lands in the
flight recorder so ``scripts/trace_fuse.py`` can draw the badput track.

On top of the ledger sit two closed loops:

- **Perf-regression sentinel** (``SMP_REGRESSION_RATIO``): rolling-
  baseline change-point detection over windowed deltas of the
  cumulative ``smp_step_time_seconds`` / ITL histograms. When a
  window's p50 degrades past the ratio vs. the trailing-baseline
  median, it raises a latched ``smp_perf_regression`` flight event
  (one fire per episode, cleared when the p50 recovers).
- **Auto-forensics** (``SMP_FORENSICS_PATH``): when the sentinel, a
  fleet straggler/imbalance detector, an SLO violation streak, or a
  goodput drop below ``SMP_GOODPUT_MIN`` fires, capture one bounded,
  cooldown-rate-limited forensic bundle: a one-step ``jax.profiler``
  capture (reusing the ``SMP_PROFILE`` arming machinery), a flight-
  recorder ring dump, thread stacks, the current HLO fingerprint, and
  the offending badput/sentinel windows.

Zero-cost-off contract (PR-16/17): with none of ``SMP_GOODPUT`` /
``SMP_GOODPUT_MIN`` / ``SMP_REGRESSION_RATIO`` / ``SMP_FORENSICS_PATH``
set, ``from_env`` returns None and NOTHING is constructed — no state
machine, no listener, and every seam call is one attribute test.
"""

import collections
import contextlib
import json
import os
import statistics
import sys
import threading
import time
import traceback

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    quantile_from_counts,
    telemetry,
)

logger = get_logger()

GOODPUT_ENV = "SMP_GOODPUT"
GOODPUT_MIN_ENV = "SMP_GOODPUT_MIN"
REGRESSION_RATIO_ENV = "SMP_REGRESSION_RATIO"
FORENSICS_PATH_ENV = "SMP_FORENSICS_PATH"
FORENSICS_COOLDOWN_ENV = "SMP_FORENSICS_COOLDOWN"

#: Every attribution state, in display order. ``step`` is the single
#: productive (goodput) state; everything else is badput by definition.
STATES = (
    "step", "trace", "compile_fresh", "compile_cache", "data_wait",
    "sync_wait", "ckpt_save", "ckpt_restore", "recovery_detect",
    "recovery_rendezvous", "recovery_reshard_load", "recovery_first_step",
    "preempt_drain", "wedged", "startup", "idle",
)
PRODUCTIVE = frozenset({"step"})

#: Transitions kept for the watchdog dump / forensic bundles.
TRANSITION_HISTORY = 256

DEFAULT_TICK_SECONDS = 5.0
DEFAULT_FORENSICS_COOLDOWN = 600.0
DEFAULT_FORENSICS_MAX = 8
#: Goodput-below-min never fires this early — startup would dominate.
DEFAULT_MIN_ELAPSED = 60.0


def _flight():
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )

    return flight_recorder


def _env_float(name):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        logger.warning("invalid %s=%r (want a number); ignored.", name, raw)
        return None


def goodput_enabled():
    """The ledger arms when ``SMP_GOODPUT`` is truthy OR any dependent
    knob (min-goodput gate, sentinel ratio, forensics path) is set —
    those knobs are meaningless without the ledger under them."""
    raw = os.environ.get(GOODPUT_ENV, "").strip().lower()
    if raw not in ("", "0", "off", "false", "no"):
        return True
    return any(
        os.environ.get(v)
        for v in (GOODPUT_MIN_ENV, REGRESSION_RATIO_ENV, FORENSICS_PATH_ENV)
    )


def classify_phase(phase):
    """Map a telemetry phase string to an attribution state, or None
    when the phase carries no attribution signal (state unchanged)."""
    if not phase:
        return None
    if phase.endswith("/trace"):
        return "trace"
    if phase.startswith("step_"):
        return "step"
    if phase.startswith("run/"):
        return "step"
    if phase.startswith("compile/"):
        # Tentative: exec_cache's compile event reattributes to
        # compile_cache when the executable came off disk.
        return "compile_fresh"
    if phase.startswith("init/") or phase == "startup":
        return "startup"
    if phase in ("initialized", "shutdown"):
        return "idle"
    if phase.startswith(("barrier/", "recv_from/")):
        return "sync_wait"
    return None


class RegressionSentinel:
    """Rolling-baseline change-point detector over the cumulative
    step-time / ITL histograms.

    Each ``check()`` cuts a window (bucket-count deltas vs. the previous
    check — the same arithmetic the time-series and fleet windows use)
    and compares its p50 against the median of the trailing baseline
    windows. A degradation past ``ratio`` latches the source as
    regressed (one fire per episode); recovery below the ratio clears
    it. Regressed windows never extend the baseline, so a persistent
    regression cannot normalize itself away.
    """

    SOURCES = (
        ("step_time", "smp_step_time_seconds", ()),
        ("itl", "smp_serve_latency_seconds", (("kind", "itl"),)),
    )

    def __init__(self, registry=None, ratio=None, min_count=8,
                 baseline_windows=3, history=32):
        self.registry = registry if registry is not None else telemetry
        self.ratio = (
            _env_float(REGRESSION_RATIO_ENV) if ratio is None
            else float(ratio)
        )
        self.min_count = int(min_count)
        self.baseline_windows = int(baseline_windows)
        self._prev = {}
        self._baseline = {
            src: collections.deque(maxlen=8) for src, _, _ in self.SOURCES
        }
        self._regressed = set()
        self.windows = {
            src: collections.deque(maxlen=history)
            for src, _, _ in self.SOURCES
        }
        self.verdicts = []

    @property
    def enabled(self):
        return self.ratio is not None and self.ratio > 0

    def _series(self, metrics, name, labels):
        fam = metrics.get(name)
        if not fam:
            return None
        want = tuple(sorted(labels))
        for s in fam.get("series", ()):
            if tuple(sorted((s.get("labels") or {}).items())) == want:
                return s
        return None

    def check(self, now=None, wall=None):
        """Cut one window per source; returns the list of verdicts FIRED
        by this check (empty when nothing newly regressed)."""
        if not self.enabled:
            return []
        metrics = self.registry.report().get("metrics", {})
        fired = []
        for source, fam_name, labels in self.SOURCES:
            s = self._series(metrics, fam_name, labels)
            if s is None or not s.get("counts"):
                continue
            buckets = list(s["buckets"])
            counts = list(s["counts"])
            prev = self._prev.get(source)
            self._prev[source] = (buckets, counts, s["sum"], s["count"])
            if prev is None or prev[0] != buckets:
                continue
            dcounts = [a - b for a, b in zip(counts, prev[1])]
            dn = s["count"] - prev[3]
            if dn < self.min_count or min(dcounts) < 0:
                continue
            p50 = quantile_from_counts(buckets, dcounts, 0.5)
            if p50 is None:
                continue
            base = self._baseline[source]
            record = {
                "source": source, "p50_s": round(p50, 6), "count": dn,
                "t_wall": wall if wall is not None else time.time(),
            }
            if len(base) >= self.baseline_windows:
                baseline = statistics.median(base)
                r = p50 / baseline if baseline > 0 else 1.0
                record["baseline_s"] = round(baseline, 6)
                record["ratio"] = round(r, 3)
                flag = self.registry.gauge(
                    "smp_perf_regression",
                    "1 while the windowed p50 sits past "
                    "SMP_REGRESSION_RATIO x the trailing baseline",
                )
                if r > self.ratio and source not in self._regressed:
                    self._regressed.add(source)
                    record["fired"] = True
                    self.verdicts.append(record)
                    fired.append(record)
                    self.registry.counter(
                        "smp_perf_regression_total",
                        "perf-regression sentinel fires (one per latched "
                        "episode)",
                    ).labels(source=source).inc()
                    flag.labels(source=source).set(1)
                    _flight().record_perf(
                        "regression", source,
                        detail=f"p50 {p50:.4f}s = {r:.2f}x baseline "
                               f"{baseline:.4f}s > {self.ratio:g}")
                    logger.warning(
                        "PERF REGRESSION (%s): windowed p50 %.4fs is "
                        "%.2fx the trailing baseline %.4fs "
                        "(SMP_REGRESSION_RATIO=%g).",
                        source, p50, r, baseline, self.ratio,
                    )
                elif r <= self.ratio and source in self._regressed:
                    self._regressed.discard(source)
                    flag.labels(source=source).set(0)
                    _flight().record_perf(
                        "regression_clear", source,
                        detail=f"p50 {p50:.4f}s back to {r:.2f}x baseline")
            if source not in self._regressed:
                base.append(p50)
            self.windows[source].append(record)
        return fired

    @property
    def regressed(self):
        return set(self._regressed)


class ForensicsEngine:
    """Anomaly-triggered forensic bundle capture, bounded and
    cooldown-rate-limited.

    One bundle = a directory under ``SMP_FORENSICS_PATH`` holding
    ``forensics.json`` (reason, goodput snapshot, sentinel windows,
    thread stacks, HLO fingerprint), ``flight_recorder.jsonl`` (the ring
    dump), and — once the next step edge passes — a one-step
    ``jax.profiler`` capture under ``profile/`` via the ``SMP_PROFILE``
    arming machinery.
    """

    def __init__(self, path=None, registry=None, cooldown=None,
                 max_bundles=DEFAULT_FORENSICS_MAX, clock=None, wall=None):
        self.path = (
            os.environ.get(FORENSICS_PATH_ENV) if path is None else path
        ) or None
        self.registry = registry if registry is not None else telemetry
        env_cd = _env_float(FORENSICS_COOLDOWN_ENV)
        self.cooldown = (
            (env_cd if env_cd is not None else DEFAULT_FORENSICS_COOLDOWN)
            if cooldown is None else float(cooldown)
        )
        self.max_bundles = int(max_bundles)
        self._clock = clock or time.monotonic
        self._wall = wall or time.time
        self._lock = threading.Lock()
        self._last = None
        self._count = 0
        self.bundles = []

    @property
    def enabled(self):
        return self.path is not None

    def _counter(self):
        return self.registry.counter(
            "smp_forensics_total",
            "auto-forensics triggers by outcome (captured / suppressed)",
        )

    def trigger(self, reason, detail="", context=None):
        """Capture one bundle, or return None when suppressed (cooldown
        not elapsed, or the bundle cap is spent). Never raises: a broken
        capture must not take down the run it is diagnosing."""
        if not self.enabled:
            return None
        with self._lock:
            now = self._clock()
            if self._count >= self.max_bundles:
                self._counter().labels(outcome="suppressed").inc()
                return None
            if self._last is not None and now - self._last < self.cooldown:
                self._counter().labels(outcome="suppressed").inc()
                return None
            self._last = now
            self._count += 1
            seq = self._count
        try:
            return self._capture(seq, reason, detail, context)
        except Exception as e:  # pragma: no cover - diagnostics only
            logger.warning("forensic capture failed: %s", e)
            return None

    def _capture(self, seq, reason, detail, context):
        bundle = self.registry._rank_path(
            os.path.join(self.path, f"bundle_{seq:03d}_{reason}")
        )
        os.makedirs(bundle, exist_ok=True)
        stacks = {}
        try:
            names = {t.ident: t.name for t in threading.enumerate()}
            for tid, frame in sys._current_frames().items():
                stacks[f"{names.get(tid, '?')}:{tid}"] = (
                    traceback.format_stack(frame)
                )
        except Exception:
            pass
        doc = {
            "kind": "forensics",
            "seq": seq,
            "reason": reason,
            "detail": detail,
            "t_wall": self._wall(),
            "pid": os.getpid(),
            "rank": self.registry.process_index,
            "threads": stacks,
        }
        if context:
            doc.update(context)
        try:
            from smdistributed_modelparallel_tpu.backend.state import state

            doc["hlo_fingerprint"] = (state.last_compile_report or {}).get(
                "fingerprint"
            )
        except Exception:
            pass
        fr = _flight()
        ring_path = fr.dump(os.path.join(bundle, "flight_recorder.jsonl"))
        doc["flight_recorder"] = ring_path
        # One-step profiler capture at the next step edge, into the
        # bundle (the SIGUSR2 arming path, called in-process).
        try:
            from smdistributed_modelparallel_tpu.utils import profiling

            profiling.capture.request_capture(
                path=os.path.join(bundle, "profile")
            )
            doc["profile"] = os.path.join(bundle, "profile")
        except Exception:
            doc["profile"] = None
        try:
            with open(os.path.join(bundle, "forensics.json"), "w") as f:
                json.dump(doc, f, indent=1, default=str)
        except OSError as e:
            logger.warning("forensics.json write failed: %s", e)
        self._counter().labels(outcome="captured").inc()
        fr.record_perf("forensics", reason, detail=bundle)
        self.bundles.append(bundle)
        logger.warning(
            "FORENSICS (%s): bundle %d captured under %s%s.",
            reason, seq, bundle,
            " (profiler armed for the next step)" if doc.get("profile")
            else "",
        )
        return bundle


class GoodputLedger:
    """The attribution state machine.

    A small stack models nesting: the BASE entry follows the ambient
    telemetry phase (``observe_phase``), while explicit ``scope()``
    pushes (checkpoint saves, preemption drains, injected wedges)
    temporarily outrank it. Every transition attributes the elapsed
    time since the previous one to the state being left, so at any
    instant ``sum(seconds().values()) == now - t0`` exactly — the
    invariant the fake-clock tests pin.
    """

    def __init__(self, registry=None, tick_seconds=DEFAULT_TICK_SECONDS,
                 min_goodput=None, regression_ratio=None, forensics=None,
                 min_elapsed=DEFAULT_MIN_ELAPSED, clock=None, wall=None):
        self.registry = registry if registry is not None else telemetry
        self._clock = clock or time.monotonic
        self._wall = wall or time.time
        self.tick_seconds = float(tick_seconds)
        self.min_goodput = (
            _env_float(GOODPUT_MIN_ENV) if min_goodput is None
            else float(min_goodput)
        )
        self.min_elapsed = float(min_elapsed)
        self.sentinel = RegressionSentinel(
            registry=self.registry, ratio=regression_ratio
        )
        self.forensics = (
            ForensicsEngine(registry=self.registry, clock=self._clock,
                            wall=self._wall)
            if forensics is None else forensics
        )
        self._lock = threading.RLock()
        self._t0 = self._clock()
        self._t_last = self._t0
        self._stack = ["startup"]
        self._seconds = {}
        self._transitions = collections.deque(maxlen=TRANSITION_HISTORY)
        self._published = {}
        self._last_tick = self._t0
        self._min_fired = False

    @classmethod
    def from_env(cls, registry=None):
        """The env-configured ledger, or None when no goodput knob is
        set — in which case NOTHING is constructed."""
        if not goodput_enabled():
            return None
        return cls(registry=registry)

    # -- the transition primitive ---------------------------------------

    def _shift(self, new_state, now=None):
        """Attribute elapsed time to the current state, then make
        ``new_state`` current. Caller holds the lock."""
        now = self._clock() if now is None else now
        prev = self._stack[-1]
        dt = now - self._t_last
        if dt > 0:
            self._seconds[prev] = self._seconds.get(prev, 0.0) + dt
        self._t_last = now
        if new_state != prev:
            self._transitions.append(
                (round(now - self._t0, 6), prev, new_state)
            )
            _flight().record_goodput(new_state, prev, max(dt, 0.0))
        return prev

    def _sync(self, now=None):
        self._shift(self._stack[-1], now)

    # -- drivers --------------------------------------------------------

    def enter(self, state, now=None):
        """Unconditional transition of the current (top) state."""
        with self._lock:
            self._shift(state, now)
            self._stack[-1] = state

    def observe_phase(self, phase):
        """The telemetry ``set_phase`` listener: ambient phases drive
        the BASE of the stack only — an explicit scope (ckpt_save,
        preempt_drain, wedged) in progress outranks them."""
        state = classify_phase(phase)
        if state is None:
            return
        with self._lock:
            if len(self._stack) == 1:
                self._shift(state)
                self._stack[-1] = state
            else:
                self._stack[0] = state

    @contextlib.contextmanager
    def scope(self, state):
        """Explicitly-attributed region; restores the enclosing state
        (including ambient phase changes observed meanwhile) on exit."""
        with self._lock:
            self._shift(state)
            self._stack.append(state)
        try:
            yield self
        finally:
            with self._lock:
                if len(self._stack) > 1:
                    # Shift BEFORE popping: the elapsed interval belongs
                    # to the scope state (the current top), and the
                    # transition target is the enclosing entry.
                    self._shift(self._stack[-2])
                    self._stack.pop()

    def mark_stalled(self, reason=""):
        """Watchdog seam (called from the timer thread while the main
        thread is parked): from here on, time accrues to ``wedged``
        until the stalled thread resumes and transitions away."""
        with self._lock:
            self._shift("wedged")
            self._stack[-1] = "wedged"

    def note_compile(self, source, seconds):
        """exec_cache compile-event seam: a compile phase is attributed
        ``compile_fresh`` tentatively (the source is only known when the
        event lands); disk-cache hits move their seconds over."""
        if source != "disk_cache":
            return
        with self._lock:
            self._sync()
            avail = self._seconds.get("compile_fresh", 0.0)
            moved = min(max(float(seconds), 0.0), avail)
            if moved <= 0:
                return
            self._seconds["compile_fresh"] = avail - moved
            self._seconds["compile_cache"] = (
                self._seconds.get("compile_cache", 0.0) + moved
            )

    # -- readout --------------------------------------------------------

    def seconds(self, now=None):
        """Attributed seconds by state, current state's partial interval
        included: values sum to ``wall_seconds(now)`` exactly."""
        with self._lock:
            self._sync(now)
            return dict(self._seconds)

    def wall_seconds(self, now=None):
        now = self._clock() if now is None else now
        return now - self._t0

    def goodput_fraction(self, now=None):
        secs = self.seconds(now)
        total = sum(secs.values())
        if total <= 0:
            return 1.0
        return sum(secs.get(s, 0.0) for s in PRODUCTIVE) / total

    @property
    def state(self):
        with self._lock:
            return self._stack[-1]

    def transitions(self, last=None):
        with self._lock:
            items = list(self._transitions)
        if last is not None:
            items = items[-last:]
        return [
            {"t_s": t, "from": a, "to": b} for t, a, b in items
        ]

    def snapshot(self, last=32):
        """The watchdog-dump / forensics block: current state, per-state
        seconds, goodput fraction, and the last N transitions."""
        now = self._clock()
        secs = self.seconds(now)
        return {
            "state": self.state,
            "wall_s": round(self.wall_seconds(now), 3),
            "goodput_fraction": round(self.goodput_fraction(now), 4),
            "seconds": {s: round(v, 3) for s, v in sorted(secs.items())},
            "transitions": self.transitions(last=last),
        }

    def window_block(self):
        """The per-window fold for MetricsTimeSeries records."""
        now = self._clock()
        secs = self.seconds(now)
        return {
            "fraction": round(self.goodput_fraction(now), 4),
            "badput": {
                s: round(v, 3) for s, v in sorted(secs.items())
                if s not in PRODUCTIVE and v > 0
            },
        }

    # -- publishing + the closed loops ----------------------------------

    def publish(self, now=None):
        """Refresh the gauges and bump the cumulative second counters by
        the delta since the last publish (counters must stay monotonic
        so the fleet merge can sum them across ranks)."""
        now = self._clock() if now is None else now
        with self._lock:
            secs = self.seconds(now)
            frac = self.goodput_fraction(now)
            good_c = self.registry.counter(
                "smp_goodput_seconds_total",
                "wall-clock seconds attributed to productive step compute",
            )
            bad_c = self.registry.counter(
                "smp_badput_seconds_total",
                "wall-clock seconds attributed to non-productive states",
            )
            for s, v in secs.items():
                d = v - self._published.get(s, 0.0)
                if d <= 0:
                    continue
                if s in PRODUCTIVE:
                    good_c.inc(d)
                else:
                    bad_c.labels(state=s).inc(d)
                self._published[s] = v
        self.registry.gauge(
            "smp_goodput_fraction",
            "fraction of this rank's wall clock attributed to productive "
            "step compute",
        ).set(frac)
        return frac

    def maybe_tick(self, now=None):
        """The periodic driver (step edges / time-series samples): at
        most once per ``tick_seconds``, publish, run the sentinel, and
        evaluate the goodput floor. Cheap otherwise."""
        now = self._clock() if now is None else now
        with self._lock:
            if now - self._last_tick < self.tick_seconds:
                return None
            self._last_tick = now
        return self.tick(now)

    def tick(self, now=None):
        now = self._clock() if now is None else now
        frac = self.publish(now)
        fired = self.sentinel.check(now=now, wall=self._wall())
        for verdict in fired:
            self.trigger_forensics(
                "perf_regression",
                detail=f"{verdict['source']} p50 {verdict['p50_s']}s "
                       f"ratio {verdict.get('ratio')}",
            )
        if (self.min_goodput is not None
                and not self._min_fired
                and self.wall_seconds(now) >= self.min_elapsed
                and frac < self.min_goodput):
            self._min_fired = True
            _flight().record_perf(
                "goodput_min", "goodput",
                detail=f"{frac:.3f} < {self.min_goodput:g}")
            self.trigger_forensics(
                "goodput_min",
                detail=f"goodput {frac:.3f} < SMP_GOODPUT_MIN "
                       f"{self.min_goodput:g}",
            )
        return frac

    def on_step_edge(self, step):
        self.maybe_tick()

    def trigger_forensics(self, reason, detail=""):
        context = {
            "goodput": self.snapshot(),
            "sentinel": {
                "verdicts": list(self.sentinel.verdicts),
                "windows": {
                    src: list(win)
                    for src, win in self.sentinel.windows.items() if win
                },
            },
        }
        return self.forensics.trigger(reason, detail=detail,
                                      context=context)

    def bench_block(self, now=None):
        """The ``"goodput"`` block bench.py stamps into BENCH_r*.json."""
        now = self._clock() if now is None else now
        secs = self.seconds(now)
        return {
            "fraction": round(self.goodput_fraction(now), 4),
            "wall_s": round(self.wall_seconds(now), 3),
            "seconds": {s: round(v, 3) for s, v in sorted(secs.items())},
            "sentinel": list(self.sentinel.verdicts),
            "forensics": list(self.forensics.bundles),
        }


class GoodputController:
    """Process-wide singleton (``smp.goodput``): owns the ledger's
    lifecycle and the ``set_phase`` listener chain. Every accessor is a
    single attribute test while disarmed."""

    def __init__(self):
        self.ledger = None
        self._chained = None
        self._prev_listener = None

    def start(self, registry=None):
        """Arm from the environment (state.initialize); idempotent.
        Chains the phase listener AFTER the flight-recorder's so phases
        keep flowing to the ring."""
        if self.ledger is not None:
            return self.ledger
        led = GoodputLedger.from_env(registry=registry)
        if led is None:
            return None
        self.ledger = led
        reg = led.registry
        prev = reg._phase_listener

        def _chain(phase, _prev=prev, _led=led):
            if _prev is not None:
                _prev(phase)
            _led.observe_phase(phase)

        self._prev_listener = prev
        self._chained = _chain
        reg._phase_listener = _chain
        logger.info(
            "goodput ledger armed (min=%s, regression_ratio=%s, "
            "forensics=%s).", led.min_goodput, led.sentinel.ratio,
            led.forensics.path,
        )
        return led

    def stop(self):
        """Final publish + unchain; idempotent."""
        led = self.ledger
        if led is None:
            return
        try:
            led.tick()
        except Exception:
            logger.warning("goodput final tick failed", exc_info=True)
        reg = led.registry
        if reg._phase_listener is self._chained:
            reg._phase_listener = self._prev_listener
        self._chained = None
        self._prev_listener = None

    def reset(self):
        """Testing hook (state.reset): drop the ledger entirely."""
        self.stop()
        self.ledger = None

    # -- seam helpers (one attribute test each while disarmed) ----------

    def scope(self, state):
        led = self.ledger
        return led.scope(state) if led is not None else _NULL_SCOPE

    def enter(self, state):
        led = self.ledger
        if led is not None:
            led.enter(state)

    def on_step_edge(self, step):
        led = self.ledger
        if led is not None:
            led.on_step_edge(step)

    def note_compile(self, source, seconds):
        led = self.ledger
        if led is not None:
            led.note_compile(source, seconds)

    def mark_stalled(self, reason=""):
        led = self.ledger
        if led is not None:
            led.mark_stalled(reason)

    def trigger_forensics(self, reason, detail=""):
        led = self.ledger
        if led is not None:
            return led.trigger_forensics(reason, detail=detail)
        return None

    def snapshot(self):
        led = self.ledger
        return led.snapshot() if led is not None else None

    def window_block(self):
        led = self.ledger
        return led.window_block() if led is not None else None

    def bench_block(self):
        led = self.ledger
        return led.bench_block() if led is not None else None


_NULL_SCOPE = contextlib.nullcontext()

goodput = GoodputController()
