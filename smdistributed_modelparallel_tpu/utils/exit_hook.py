"""Exit-status capture for consistent multi-process shutdown.

Parity target: reference ``backend/core.py:165-189`` (``ExitHook``) — hooks
``sys.exit`` and ``sys.excepthook`` so the shutdown path knows whether the
process is dying cleanly, and ``shutdown()`` passes that status to the
backend (``smp_shutdown(success)``) so every rank exits with the same
story. The reference's C++ backend relays the flag between its helper and
main processes; here the relay is a best-effort status message to process
0 over the native bus (``backend/collectives.py``), which logs which peers
failed — recovery itself remains checkpoint/resume, as in the reference
(SURVEY §5.3: "no elasticity").
"""

import sys


class ExitHook:
    """Captures sys.exit codes and uncaught exceptions.

    Same surface as the reference class: ``hook()`` installs, ``exit_code``
    / ``exception`` record what ended the process, ``success`` derives the
    consistent status. ``unhook()`` restores the original handlers (the
    reference never unhooks; tests need to).
    """

    def __init__(self):
        self.exit_code = None
        self.exception = None
        self._orig_exit = None
        self._orig_excepthook = None

    def hook(self):
        if self._orig_exit is not None:
            return  # already installed
        self._orig_exit = sys.exit
        sys.exit = self.exit
        self._orig_excepthook = sys.excepthook
        sys.excepthook = self.exc_handler

    def unhook(self):
        if self._orig_exit is None:
            return
        sys.exit = self._orig_exit
        sys.excepthook = self._orig_excepthook
        self._orig_exit = None
        self._orig_excepthook = None

    def exit(self, code=0):
        self.exit_code = code
        self._orig_exit(code)

    def exc_handler(self, exc_type, exc, *args):
        self.exception = exc
        self._orig_excepthook(exc_type, exc, *args)

    @property
    def success(self):
        """True when nothing recorded a failing exit: no uncaught
        exception, and sys.exit (if called) carried a falsy code."""
        return not self.exit_code and self.exception is None
