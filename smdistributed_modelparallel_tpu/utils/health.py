"""Training-health monitor: in-graph numerics sentinel, fault bisection,
and OOM post-mortem.

The reference treats numerics health as first-class: its fp16 loss scaler
allgathers an overflow flag across pp+tp every step
(``torch/fp16/loss_scaler.py``) and its metrics upload includes memory
accounting (§5.5). Under GSPMD the step is ONE compiled program, so health
checks must live *inside that program* — a host-side assert would force a
device sync per step and see only what the host already fetched. This
module is that in-graph half plus the host machinery around it:

- **Sentinel** (``SMP_HEALTH_CHECK=off|cheap|full``, default ``off``):
  while the step program is being traced, tagged tensors (loss, outputs,
  globally-averaged grads, per-pipeline-stage boundary activations, and —
  under ``full`` — the parameters) each contribute one fused
  finiteness-count / finite-abs-max reduce into a single small ``[K, 3]``
  f32 "health word" output of the compiled step. ``off`` compiles to
  NOTHING (``tag`` is identity, the collector is inactive — the step HLO
  is byte-identical; ``tests/test_health.py`` asserts it).
- **Asynchronous fetch**: the health word of step N is *submitted* to the
  monitor without reading it; it is decoded when step N+1 is submitted —
  by then the device has finished step N, so the host never blocks on the
  step it just dispatched. ``full`` mode decodes synchronously every step
  (a debug mode, one tiny device->host readback per step).
- **Bisection**: when a sentinel trips, the monitor re-runs the faulting
  step on the retained step inputs OUTSIDE the compiled program —
  layer-by-layer through the model's ``PipelineSpec`` (so the first
  non-finite value is attributed to ``<layer_path>#<i>`` + microbatch +
  rank), or via flax ``capture_intermediates`` for non-pipelined modules,
  falling back to a per-microbatch grad re-run for backward-only faults.
  The attribution lands in telemetry (``smp_health_fault_total``), the
  flight-recorder ring, and a JSON dump at ``SMP_HEALTH_PATH``.
- **OOM post-mortem**: the step engine routes RESOURCE_EXHAUSTED failures
  through :func:`oom_postmortem`, which dumps the executable's XLA
  memory-analysis breakdown (argument/temp/output/alias bytes), a live-
  buffer summary grouped by shape, per-device allocator stats, and the
  active remat/offload configuration next to the flight-recorder ring.

Import-hygiene contract: importing this module must never initialize an
accelerator backend (jax/jnp imports are fine; no device arrays at
import).
"""

import json
import math
import os
import time
from contextlib import contextmanager

import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.utils import flight_recorder as _fr
from smdistributed_modelparallel_tpu.utils import telemetry as _tel
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    _atomic_json_dump,
    telemetry,
)

logger = get_logger()

HEALTH_CHECK_ENV = "SMP_HEALTH_CHECK"
HEALTH_PATH_ENV = "SMP_HEALTH_PATH"
DEFAULT_HEALTH_PATH = "smp_health_dump.json"

_MODES = ("off", "cheap", "full")
_warned_mode = set()

# Cheap mode samples the optimizer-update norm gauges every Nth
# optimizer.step (the float readback is a host sync on the update's
# completion); full mode records every step. The first step always
# records so short runs/tests see the gauges.
UPDATE_STATS_EVERY = 16
_update_stats_calls = [0]


def mode():
    """Configured sentinel mode, read from the environment at call time
    (the step cache keys on it, so flipping the env recompiles)."""
    raw = os.environ.get(HEALTH_CHECK_ENV, "").strip().lower()
    if raw in ("", "0", "false", "off", "no", "none"):
        return "off"
    if raw in ("1", "on", "true", "cheap"):
        return "cheap"
    if raw == "full":
        return "full"
    if raw not in _warned_mode:
        _warned_mode.add(raw)
        logger.warning(
            "invalid %s=%r (want off|cheap|full); health checks disabled.",
            HEALTH_CHECK_ENV, raw,
        )
    return "off"


def enabled():
    return mode() != "off"


def _health_path():
    path = os.environ.get(HEALTH_PATH_ENV) or DEFAULT_HEALTH_PATH
    return telemetry._rank_path(path)


# ----------------------------------------------------------------------
# In-graph collector (active only while a step program is being traced)
# ----------------------------------------------------------------------


def _inexact_leaves(tree):
    return [
        l for l in jax.tree_util.tree_leaves(tree)
        if hasattr(l, "dtype") and jnp.issubdtype(jnp.result_type(l), jnp.inexact)
    ]


class HealthCollector:
    """Accumulates (name, bad_count, finite_abs_max, first_bad_microbatch)
    entries during one step-program trace; ``pack()`` fuses them into the
    ``[K, 3]`` health-word output. Entries hold tracers — a collector
    never outlives the trace that filled it."""

    def __init__(self, mode):
        self.mode = mode
        self.entries = []  # [(name, bad, absmax, microbatch)]

    def add(self, name, bad, absmax, microbatch=None):
        mb = -1.0 if microbatch is None else microbatch
        self.entries.append((str(name), bad, absmax, mb))

    def add_tree(self, name, tree):
        """One entry for a whole pytree (no microbatch axis)."""
        leaves = _inexact_leaves(tree)
        if not leaves:
            return
        bad = jnp.zeros((), jnp.float32)
        mx = jnp.zeros((), jnp.float32)
        for l in leaves:
            lf = l.astype(jnp.float32)
            fin = jnp.isfinite(lf)
            bad = bad + jnp.sum(~fin).astype(jnp.float32)
            mx = jnp.maximum(mx, jnp.max(jnp.where(fin, jnp.abs(lf), 0.0)))
        self.add(name, bad, mx)

    def add_stacked(self, name, tree, num_mb=None):
        """One entry for a pytree whose leaves lead with a microbatch axis;
        also records the first microbatch index with a non-finite value."""
        leaves = _inexact_leaves(tree)
        if not leaves:
            return
        n = int(leaves[0].shape[0]) if num_mb is None else int(num_mb)
        per = jnp.zeros((n,), jnp.float32)
        mx = jnp.zeros((), jnp.float32)
        for l in leaves:
            lf = l.astype(jnp.float32).reshape((n, -1))
            fin = jnp.isfinite(lf)
            per = per + jnp.sum(~fin, axis=1).astype(jnp.float32)
            mx = jnp.maximum(mx, jnp.max(jnp.where(fin, jnp.abs(lf), 0.0)))
        bad = jnp.sum(per)
        first = jnp.where(bad > 0, jnp.argmax(per > 0).astype(jnp.float32), -1.0)
        self.add(name, bad, mx, first)

    def add_stage_stats(self, schedule, bad, absmax, first_mb,
                        chunk_ids=None, pass_name=None):
        """Per-pipeline-stage entries from an executor's accumulated
        boundary-activation stats ([S] vectors; static S). Under virtual
        pipeline chunks the executors pass [S, V] grids plus a matching
        ``chunk_ids`` grid of GLOBAL chunk (boundary) indices, and the
        tags gain that coordinate — so a sentinel trip attributes to the
        exact model chunk, the stage says where it physically ran, and
        the two executors' tags for the same layers reconcile even though
        their placements differ (1F1B interleaves chunks, the fill-drain
        forward path runs them sequentially). Split-backward schedules
        additionally pass ``pass_name`` and the tags gain the pass
        coordinate (``.../fwd`` boundary activations vs ``.../bwd_input``
        cotangents — the zero-bubble executor monitors both)."""
        suffix = f"/{pass_name}" if pass_name else ""
        if getattr(bad, "ndim", 1) == 2:
            num_stages, virtual = (int(d) for d in bad.shape)
            for s in range(num_stages):
                for k in range(virtual):
                    g = int(chunk_ids[s][k]) if chunk_ids is not None else k
                    self.add(
                        f"pp/{schedule}/stage{s}/chunk{g}{suffix}",
                        bad[s, k], absmax[s, k], first_mb[s, k],
                    )
            return
        num_stages = int(bad.shape[0])
        for s in range(num_stages):
            self.add(f"pp/{schedule}/stage{s}{suffix}",
                     bad[s], absmax[s], first_mb[s])

    # Entries added inside an inner trace (e.g. under the fill-drain
    # executor's value_and_grad) must travel OUT through that transform's
    # aux outputs, not through this Python list — mark/drain inside the
    # differentiated closure, restore from the aux values outside.

    def mark(self):
        return len(self.entries)

    def drain(self, mark):
        drained = self.entries[mark:]
        del self.entries[mark:]
        return drained

    def restore(self, entries):
        self.entries.extend(entries)

    def pack(self):
        """(word [K, 3] f32, [name, ...]) or (None, None) when empty."""
        if not self.entries:
            return None, None
        rows = [
            jnp.stack([
                jnp.asarray(b, jnp.float32),
                jnp.asarray(a, jnp.float32),
                jnp.asarray(m, jnp.float32),
            ])
            for (_, b, a, m) in self.entries
        ]
        return jnp.stack(rows), [n for (n, _, _, _) in self.entries]


_collector = None


def active():
    """The collector of the step trace in progress, or None (mode off /
    not inside a step trace). Checked at TRACE time — the off path costs
    one module-attribute read and compiles to nothing."""
    return _collector


@contextmanager
def collecting(health_mode):
    """Activate a fresh collector for one step-program trace."""
    global _collector
    prev = _collector
    _collector = HealthCollector(health_mode) if health_mode != "off" else None
    try:
        yield _collector
    finally:
        _collector = prev


def tag(name, x):
    """Tag a tensor for the sentinel inside an ``@smp.step`` function:
    ``loss = smp.health.tag("loss", loss)``. Identity always — with the
    sentinel off (or outside a step trace) it compiles to nothing."""
    hc = _collector
    if hc is not None:
        hc.add_tree(name, x)
    return x


def stage_row_stats(tree, num_stages):
    """([S] non-finite counts, [S] finite abs-max) over a pytree whose
    leaves lead with the stage axis — the executors' per-tick reduce."""
    bad = jnp.zeros((num_stages,), jnp.float32)
    mx = jnp.zeros((num_stages,), jnp.float32)
    for l in _inexact_leaves(tree):
        lf = l.astype(jnp.float32).reshape((num_stages, -1))
        fin = jnp.isfinite(lf)
        bad = bad + jnp.sum(~fin, axis=1).astype(jnp.float32)
        mx = jnp.maximum(mx, jnp.max(jnp.where(fin, jnp.abs(lf), 0.0), axis=1))
    return bad, mx


# ----------------------------------------------------------------------
# Host-side monitor (async fetch + trip handling)
# ----------------------------------------------------------------------


class HealthMonitor:
    """Holds the pending (still-on-device) health word and decodes the
    previous step's word on each submit — the device->host copy of step N
    overlaps step N+1's execution, so cheap mode adds no per-step sync."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._pending = None
        self.last_check = None       # {"step", "tags": {name: {...}}}
        self.checked_steps = []      # decode order (test hook)
        self.trips = []              # bounded trip records
        self.max_bisections = 4
        self._bisections = 0

    @property
    def pending_step(self):
        return self._pending["step"] if self._pending else None

    def submit(self, step, word, schema, health_mode, bisect_fn=None):
        prev, self._pending = self._pending, {
            "step": step, "word": word, "schema": list(schema),
            "bisect": bisect_fn,
        }
        if prev is not None:
            self._check(prev)
        if health_mode == "full":
            self.flush()

    def flush(self):
        """Decode the pending word now (blocks until its step finishes).
        Called at smp.shutdown so the final step is never left unchecked."""
        pending, self._pending = self._pending, None
        if pending is not None:
            self._check(pending)

    def _check(self, pending):
        import numpy as np

        try:
            w = np.asarray(jax.device_get(pending["word"]), dtype=np.float64)
        except Exception as e:  # the step itself failed; nothing to decode
            logger.debug("health word fetch failed: %r", e)
            return
        step = pending["step"]
        self.checked_steps.append(step)
        tags = {}
        for i, name in enumerate(pending["schema"]):
            tags[name] = {
                "bad": float(w[i, 0]),
                "absmax": float(w[i, 1]),
                "microbatch": int(w[i, 2]),
            }
        self.last_check = {"step": step, "tags": tags}
        _tel.record_health_check(step, tags)
        bad_tags = {
            n: d for n, d in tags.items()
            if d["bad"] > 0 or not math.isfinite(d["absmax"])
        }
        if bad_tags:
            self._trip(pending, bad_tags)

    def _trip(self, pending, bad_tags):
        step = pending["step"]
        for name, d in bad_tags.items():
            _tel.record_health_trip(
                name, step, d["bad"], d["absmax"], d["microbatch"]
            )
        logger.error(
            "HEALTH SENTINEL TRIPPED at step %d: non-finite values in %s",
            step, sorted(bad_tags),
        )
        attribution = None
        bisect_fn = pending.get("bisect")
        if bisect_fn is not None and self._bisections < self.max_bisections:
            self._bisections += 1
            logger.warning(
                "health: bisecting step %d (re-running with per-module "
                "checkpoints) ...", step,
            )
            try:
                attribution = bisect_fn(bad_tags)
            except Exception as e:  # diagnostics must not kill training
                logger.error("health bisection failed: %r", e)
                attribution = {"error": repr(e)}
        if attribution and attribution.get("layer"):
            _tel.record_health_fault(
                attribution["layer"], attribution.get("microbatch", -1),
                ",".join(sorted(bad_tags)), step,
            )
            logger.error(
                "health: first non-finite value attributed to layer=%s "
                "microbatch=%s rank=%s",
                attribution["layer"], attribution.get("microbatch"),
                attribution.get("rank"),
            )
        self.trips.append({
            "kind": "health_trip",
            "step": step,
            "time": time.time(),
            "rank": telemetry.process_index or 0,
            "tags": bad_tags,
            "attribution": attribution,
        })
        del self.trips[:-16]
        self.dump()

    def report(self):
        return {
            "mode": mode(),
            "pending_step": self.pending_step,
            "checked_steps": list(self.checked_steps[-64:]),
            "last_check": self.last_check,
            "trips": list(self.trips),
        }

    def dump(self, path=None):
        """Write the monitor report (trips + last word) as JSON, atomically,
        rank-qualified — same conventions as the telemetry dump."""
        path = path or _health_path()
        payload = {"kind": "health", **self.report()}
        return _atomic_json_dump(payload, path, "health dump")


monitor = HealthMonitor()


def reset():
    """Testing hook (smp.reset): drop pending words and trip history."""
    monitor.reset()
    _update_stats_calls[0] = 0


def report():
    """``smp.health.report()`` — monitor state as a plain dict."""
    return monitor.report()


# ----------------------------------------------------------------------
# Bisection: attribute the first non-finite value to layer + microbatch
# ----------------------------------------------------------------------


def _first_bad_path(tree, prefix=""):
    """'/'-joined path of the first leaf holding a non-finite value,
    walking mappings in INSERTION order (module execution order for flax
    intermediates), or None. Non-array leaves are skipped."""
    if hasattr(tree, "items"):
        for k, v in tree.items():
            got = _first_bad_path(v, f"{prefix}/{k}" if prefix else str(k))
            if got is not None:
                return got
        return None
    if isinstance(tree, (tuple, list)):
        for i, v in enumerate(tree):
            got = _first_bad_path(v, f"{prefix}/{i}" if prefix else str(i))
            if got is not None:
                return got
        return None
    if not (hasattr(tree, "dtype")
            and jnp.issubdtype(jnp.result_type(tree), jnp.inexact)):
        return None
    if bool(jnp.any(~jnp.isfinite(tree))):
        return prefix or "<root>"
    return None


def _bisect_rngs(model, key):
    return {
        s: jax.random.fold_in(key, i)
        for i, s in enumerate(model.rng_streams)
    }


def _tree_deleted(tree):
    """True if any leaf's device buffer has been donated/deleted."""
    for l in jax.tree_util.tree_leaves(tree):
        try:
            if isinstance(l, jax.Array) and l.is_deleted():
                return True
        except Exception:
            return True
    return False


def _apply_layer(spec, lp, carry, layer_idx, rngs):
    xs = None
    if spec.layer_xs is not None:
        xs = jax.tree_util.tree_map(
            lambda v: jnp.asarray(v)[layer_idx], spec.layer_xs
        )
    if spec.carry_is_tuple:
        x, cross, amask = carry
        out = spec.layer_module.apply(
            {"params": lp}, x, cross_states=cross, attention_mask=amask,
            xs=xs, rngs=rngs,
        )
        return (out, cross, amask)
    if xs is not None:
        return spec.layer_module.apply({"params": lp}, carry, xs=xs, rngs=rngs)
    return spec.layer_module.apply({"params": lp}, carry, rngs=rngs)


def _captured_model_inputs(model, fn, args, kwargs):
    """Re-run the user step fn with the model call intercepted to recover
    the exact (args, kwargs) of its single ``model(...)`` call."""
    if model._output_aval is None:
        return None
    model._begin_capture(model._output_aval)
    try:
        fn(*args, **kwargs)
    finally:
        model._end_step_trace()
    captured = model._last_captured
    if len(captured) != 1:
        return None
    return captured[0]


def _bisect_forward(model, fn, params, args, kwargs, key):
    """Eager layer-by-layer re-run of one microbatch's forward; returns
    {"layer": <name>} for the first module producing a non-finite value,
    or None if the forward is clean."""
    captured = _captured_model_inputs(model, fn, args, kwargs)
    if captured is None:
        return None
    cargs, ckwargs = captured
    rngs = _bisect_rngs(model, key)
    from smdistributed_modelparallel_tpu.nn.auto_distribute import unwrap_hooks

    module = unwrap_hooks(model.module)
    spec = model._pipeline_spec
    if spec is not None:
        from smdistributed_modelparallel_tpu.parallel.pipeline import _get_subtree

        if spec.embed_method is not None:
            carry = module.apply(
                {"params": params}, *cargs, method=spec.embed_method,
                rngs=rngs, **ckwargs,
            )
        else:
            carry = cargs[0]
        if _first_bad_path(carry) is not None:
            return {"layer": "embed"}
        layer_params = _get_subtree(params, spec.layer_path)
        for l in range(spec.num_layers):
            lp = jax.tree_util.tree_map(lambda x, _l=l: x[_l], layer_params)
            carry = _apply_layer(spec, lp, carry, l, rngs)
            if _first_bad_path(carry) is not None:
                return {"layer": f"{spec.layer_path}#{l}"}
        hidden = carry[0] if spec.carry_is_tuple else carry
        if spec.head_method is not None:
            out = module.apply(
                {"params": params}, hidden, method=spec.head_method, rngs=rngs
            )
            if _first_bad_path(out) is not None:
                return {"layer": "head"}
        return None
    out, mut = module.apply(
        {"params": params}, *cargs, rngs=rngs,
        capture_intermediates=True, mutable=["intermediates"], **ckwargs,
    )
    bad = _first_bad_path(mut.get("intermediates", {}))
    if bad is not None:
        return {"layer": bad}
    if _first_bad_path(out) is not None:
        return {"layer": "output"}
    return None


def _bisect_grads(model, fn, params, args, kwargs, key):
    """Per-microbatch gradient re-run for backward-only faults: the first
    parameter path whose gradient is non-finite."""
    rngs = _bisect_rngs(model, key)

    def loss_fn(p):
        model._begin_step_trace(p, rngs)
        try:
            fn(*args, **kwargs)
        finally:
            loss = model._end_step_trace()
        if loss is None:
            return jnp.zeros(())
        return jnp.asarray(loss, jnp.float32)

    try:
        grads = jax.grad(loss_fn)(params)
    except Exception as e:
        logger.debug("health grad bisection failed: %r", e)
        return None
    bad = _first_bad_path(grads)
    if bad is not None:
        return {"layer": "grad:" + bad}
    return None


def bisect_step(model, fn, mb_args_fn, num_mb, rng, has_backward, bad_tags,
                step_params=None):
    """Attribute a tripped step: first non-finite value -> (layer name,
    microbatch, rank). ``mb_args_fn(mb)`` rebuilds one microbatch's user-fn
    arguments from the retained step inputs; ``step_params`` is the exact
    parameter tree the faulting step was dispatched with — without it the
    re-run would use post-update params, and a grad-induced NaN that
    poisoned the whole tree would mis-attribute to the first layer."""
    from smdistributed_modelparallel_tpu.backend.state import state

    rank = telemetry.process_index or 0
    params = step_params
    params_source = "dispatch"
    if params is None or _tree_deleted(params):
        # Donated buffers (fused_step_donation / the standalone update)
        # cannot be read back; fall back to the live tree and say so.
        params = model.params
        params_source = "current"
    result = {"rank": rank, "microbatch": -1, "layer": None,
              "params_source": params_source}
    bad_param = _first_bad_path(params)
    if bad_param is not None:
        result["param"] = bad_param
    grads_suspect = any(t == "grads" or t.startswith("grad") for t in bad_tags)
    # The compiled step derives keys as split(rng) -> use_rng, then
    # split(use_rng, num_mb) per microbatch (step.py full_impl/step_impl);
    # reproduce that exactly so RNG-dependent faults (dropout) re-trigger.
    use_rng = jax.random.split(rng)[0]
    mb_keys = jax.random.split(use_rng, num_mb)
    with jax.set_mesh(state.mesh):
        for mb in range(num_mb):
            args, kwargs = mb_args_fn(mb)
            bad_input = _first_bad_path((args, kwargs))
            if bad_input is not None:
                return {**result, "layer": "input:" + bad_input,
                        "microbatch": mb}
            key = mb_keys[mb]
            att = _bisect_forward(model, fn, params, args, kwargs, key)
            if att is None and has_backward and grads_suspect:
                att = _bisect_grads(model, fn, params, args, kwargs, key)
            if att is not None:
                return {**result, **att, "microbatch": mb}
    if bad_param is not None:
        # Nothing re-triggered (e.g. a poisoned but unused parameter):
        # the parameter itself is still the attribution.
        result["layer"] = "param:" + bad_param
    else:
        result["note"] = "re-run found no non-finite value (transient?)"
    return result


def make_bisector(model, fn, mb_args_fn, num_mb, rng, has_backward,
                  step_params=None):
    def bisect(bad_tags):
        return bisect_step(
            model, fn, mb_args_fn, num_mb, rng, has_backward, bad_tags,
            step_params=step_params,
        )

    return bisect


# ----------------------------------------------------------------------
# Gradient / update-ratio gauges (optimizer.step wiring)
# ----------------------------------------------------------------------


@jax.jit
def _sq_sum(tree):
    total = jnp.zeros((), jnp.float32)
    for l in _inexact_leaves(tree):
        total = total + jnp.sum(jnp.square(l.astype(jnp.float32)))
    return total


@jax.jit
def _diff_sq_sum(new, old):
    total = jnp.zeros((), jnp.float32)
    for a, b in zip(
        jax.tree_util.tree_leaves(new), jax.tree_util.tree_leaves(old)
    ):
        if jnp.issubdtype(jnp.result_type(a), jnp.inexact):
            total = total + jnp.sum(
                jnp.square(a.astype(jnp.float32) - b.astype(jnp.float32))
            )
    return total


def record_update_stats(model, old_params, new_params):
    """Grad-norm / param-norm / update-ratio gauges around one optimizer
    step. Rate-limited in cheap mode (the float readback syncs on the
    update's completion); ``old_params=None`` (donated buffers) skips the
    update-ratio. Never raises."""
    n = _update_stats_calls[0]
    _update_stats_calls[0] = n + 1
    if mode() != "full" and n % UPDATE_STATS_EVERY != 0:
        return
    try:
        grad_norm = None
        store = model._grads_store
        if store is not None:
            if store[0] == "avg":
                grad_norm = float(jnp.sqrt(_sq_sum(store[1])))
            else:
                # Raw microbatch-sum accumulator: the norm is homogeneous,
                # so divide the norm instead of materializing the average.
                grad_norm = float(jnp.sqrt(_sq_sum(store[1]))) / float(store[2])
        param_norm = float(jnp.sqrt(_sq_sum(new_params)))
        update_norm = None
        if old_params is not None:
            update_norm = float(jnp.sqrt(_diff_sq_sum(new_params, old_params)))
        _tel.record_update_stats(grad_norm, param_norm, update_norm)
    except Exception as e:  # diagnostics must not break the update path
        logger.debug("health update stats failed: %r", e)


# ----------------------------------------------------------------------
# OOM post-mortem
# ----------------------------------------------------------------------


def is_resource_exhausted(err):
    msg = str(err)
    return (
        "RESOURCE_EXHAUSTED" in msg
        or "Out of memory" in msg
        or "out of memory" in msg
    )


def _live_buffer_summary(top=20):
    groups = {}
    try:
        arrays = jax.live_arrays()
    except Exception:
        return None
    total = 0
    for a in arrays:
        try:
            nbytes = int(a.nbytes)
            key = (str(a.dtype), tuple(int(d) for d in a.shape))
        except Exception:
            continue
        total += nbytes
        cnt, byt = groups.get(key, (0, 0))
        groups[key] = (cnt + 1, byt + nbytes)
    ranked = sorted(groups.items(), key=lambda kv: -kv[1][1])[:top]
    return {
        "count": len(arrays),
        "total_bytes": total,
        "top_by_bytes": [
            {"dtype": dt, "shape": list(shape), "count": cnt, "bytes": byt}
            for (dt, shape), (cnt, byt) in ranked
        ],
    }


def _memory_config_summary():
    from smdistributed_modelparallel_tpu.backend.state import state

    cfg = state.cfg
    out = {}
    if cfg is not None:
        for k in ("microbatches", "active_microbatches", "offload_activations",
                  "fused_optimizer_step", "fused_step_donation",
                  "pipeline_parallel_degree", "tensor_parallel_degree"):
            out[k] = getattr(cfg, k, None)
    try:
        from smdistributed_modelparallel_tpu.parallel.memory import (
            offload_supported,
        )

        out["offload_supported"] = bool(offload_supported())
    except Exception:
        pass
    mm = state.module_manager
    if mm is not None:
        out["checkpoint_configs"] = sorted(mm.checkpoint_configs)
    model = state.model
    if model is not None and model._pipeline_spec is not None:
        out["pipeline_carry_remat"] = bool(model._pipeline_spec.carry_remat)
    return out


def oom_postmortem(name, compiled, err, path=None):
    """Dump an OOM breakdown next to the flight-recorder ring and record
    the event in telemetry + the ring. Returns the dump path (or None).

    ``compiled``: the failing AOT executable when available — its XLA
    ``memory_analysis`` is the authoritative argument/temp/output/alias
    byte breakdown of the program that exhausted HBM.
    """
    payload = {
        "kind": "oom_postmortem",
        "name": name,
        "time": time.time(),
        "rank": telemetry.process_index or 0,
        "error": str(err)[:4000],
    }
    mem = {}
    if compiled is not None:
        try:
            ma = compiled.memory_analysis()
            for k in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "alias_size_in_bytes",
                      "generated_code_size_in_bytes",
                      "host_argument_size_in_bytes",
                      "host_output_size_in_bytes", "host_temp_size_in_bytes"):
                v = getattr(ma, k, None)
                if v is not None:
                    mem[k] = int(v)
        except Exception as e:
            mem["error"] = repr(e)
    payload["memory_analysis"] = mem or None
    payload["live_buffers"] = _live_buffer_summary()
    devices = {}
    try:
        for d in jax.local_devices():
            try:
                ms = d.memory_stats() or {}
            except Exception:
                continue
            devices[str(d)] = {
                k: ms.get(k)
                for k in ("bytes_in_use", "peak_bytes_in_use",
                          "largest_alloc_size", "bytes_limit")
                if k in ms
            }
    except Exception:
        pass
    payload["device_memory_stats"] = devices or None
    try:
        payload["memory_config"] = _memory_config_summary()
    except Exception as e:
        payload["memory_config"] = {"error": repr(e)}
    _tel.record_oom(name)
    out_path = _atomic_json_dump(payload, path or _health_path(),
                                 "OOM post-mortem")
    logger.error(
        "RESOURCE_EXHAUSTED in %s: post-mortem (XLA memory breakdown, live "
        "buffers, remat/offload config) written to %s", name, out_path,
    )
    # Put the ring on disk too (no-op without SMP_FLIGHT_RECORDER_PATH):
    # the events before the OOM are the context the breakdown lacks.
    try:
        _fr.flight_recorder.dump()
    except Exception:
        pass
    return out_path


def maybe_oom_postmortem(name, compiled, err):
    """Postmortem iff ``err`` is a RESOURCE_EXHAUSTED; the caller re-raises
    either way."""
    if is_resource_exhausted(err):
        try:
            oom_postmortem(name, compiled, err)
        except Exception as e:  # pragma: no cover - diagnostics must not mask
            logger.error("OOM post-mortem itself failed: %r", e)
