"""Input pipeline helpers: per-process batch sharding + device prefetch.

The reference leans on torch ``DataLoader`` + its one-process-per-GPU model
(each rank trivially loads its own shard); under SPMD one process feeds
many chips, so the framework provides the two pieces that replace that
pattern TPU-natively:

- ``shard_batches(it)`` — slice each yielded batch down to this PROCESS's
  portion of the global batch (multi-host input pipelines load disjoint
  data per host);
- ``prefetch_to_device(it, size=2)`` — a bounded background pipeline that
  stages upcoming batches onto device with the step engine's input
  shardings, so host->device transfer overlaps the previous step's
  compute (the classic double-buffering recipe; on TPU the transfer
  rides DMA while the MXU works).

``smp.dataloader(it)`` composes both.
"""

import queue
import threading

import jax
import numpy as np

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError
from smdistributed_modelparallel_tpu.utils.goodput import goodput as _goodput


def _global_batch_sharding(arr):
    # EXACTLY the step engine's input placement (same helper), with the
    # configured microbatch count — so prefetched arrays are already where
    # step.py::_place wants them and the per-step device_put is skipped.
    from smdistributed_modelparallel_tpu.step import _input_sharding

    num_mb = state.cfg.microbatches
    return _input_sharding(state.mesh, state.cfg, arr, (0, num_mb, False))


def shard_batches(iterator, batch_axis=0):
    """Slice each batch pytree down to this process's portion.

    Every process must iterate the SAME global stream (same order, same
    batch sizes); process p keeps rows [p*B/P, (p+1)*B/P) of each leaf's
    ``batch_axis``. Leaves without a batch dim (scalars, metadata) pass
    through unchanged, as do whole batches on single-process runs.
    """
    P_ = jax.process_count()
    me = jax.process_index()
    for batch in iterator:
        if P_ == 1:
            yield batch
            continue

        def cut(leaf):
            arr = np.asarray(leaf)
            if arr.ndim <= batch_axis:
                return leaf  # scalar / metadata leaf: nothing to slice
            B = arr.shape[batch_axis]
            if B % P_ != 0:
                raise SMPValidationError(
                    f"Global batch dim {B} must be divisible by the "
                    f"process count ({P_})."
                )
            per = B // P_
            idx = [slice(None)] * arr.ndim
            idx[batch_axis] = slice(me * per, (me + 1) * per)
            return arr[tuple(idx)]

        yield jax.tree_util.tree_map(cut, batch)


class prefetch_to_device:
    """Iterator wrapper staging up to ``size`` upcoming batches on device.

    A daemon thread pulls host batches and calls ``jax.device_put`` with
    the framework's batch shardings; consumers receive device-committed
    arrays, so the step engine's placement check
    (``step.py::_place``) is a no-op and the NEXT batch's host->device
    transfer overlaps the CURRENT step's compute. Exceptions from the
    source iterator re-raise at the consumption point; once exhausted (or
    failed) the iterator keeps raising StopIteration (or the error).

    ``close()`` (also the context-manager exit) stops the fill thread and
    releases the staged batches — call it when abandoning the iterator
    mid-stream, or the queued device batches stay alive until GC.
    """

    _DONE = object()

    def __init__(self, iterator, size=2):
        if size < 1:
            raise SMPValidationError("prefetch size must be >= 1")
        if not state.initialized:
            raise SMPValidationError(
                "smp.init must run before prefetch_to_device (shardings "
                "come from the mesh)."
            )
        self._q = queue.Queue(maxsize=size)
        self._stop = threading.Event()
        self._terminal = None  # StopIteration or the source exception
        self._thread = threading.Thread(
            target=self._fill, args=(iterator,), daemon=True,
            name="smp-prefetch",
        )
        self._thread.start()

    def _put(self, item):
        """Bounded put that gives up when the consumer closed us."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _fill(self, iterator):
        try:
            for batch in iterator:
                if self._stop.is_set():
                    return
                staged = jax.tree_util.tree_map(
                    lambda leaf: jax.device_put(
                        leaf, _global_batch_sharding(leaf)
                    ),
                    batch,
                )
                if not self._put(staged):
                    return
        except Exception as e:  # noqa: BLE001 - re-raised at consumption
            self._put(e)
            return
        self._put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        if self._terminal is not None:
            raise self._terminal
        led = _goodput.ledger
        if led is not None and self._q.empty():
            # The input pipeline is BEHIND (the prefetch queue ran dry):
            # the blocked wait attributes to data_wait in the goodput
            # ledger. A ready batch skips the scope entirely.
            with led.scope("data_wait"):
                item = self._q.get()
        else:
            item = self._q.get()
        if item is self._DONE:
            self._terminal = StopIteration()
            raise StopIteration
        if isinstance(item, Exception):
            self._terminal = item
            raise item
        return item

    def close(self):
        """Stop the fill thread and drop staged batches."""
        self._stop.set()
        self._terminal = StopIteration()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def dataloader(iterator, size=2, batch_axis=0):
    """``prefetch_to_device(shard_batches(iterator))`` — the standard
    multi-host input pipeline composition."""
    return prefetch_to_device(
        shard_batches(iterator, batch_axis=batch_axis), size=size
    )
