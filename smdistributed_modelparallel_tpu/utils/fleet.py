"""Fleet metrics plane (``smp.fleet``): one live, fleet-level view of
the N per-rank telemetry registries.

Three moving parts, all off by default:

- A **publisher** on every rank: each ``SMP_FLEET_INTERVAL`` seconds it
  serializes a compact snapshot of the local registry (counter totals,
  gauges, raw histogram bucket counts — no help strings) and pushes it
  to the current aggregator over the native bus on reserved control tx
  -7 (``FLEET_TX``), via the same quiet ``send_raw``/``drain_bytes``
  paths heartbeats use: no chaos seam, no flight-recording, no retries.
  A failed send is not an error — a dead link is next tick's election
  signal.

- An **aggregator** on the lowest-alive rank (re-elected through the
  supervisor's failure detector when it dies; a replica death must not
  kill the metrics plane). It merges snapshots exactly — counters
  summed, histograms by element-wise bucket-count addition (every rank
  shares the deterministic ``LATENCY_BUCKETS``), gauges kept per-rank
  with min/max/median skew stats — so fleet p50/p90/p99 are bit-equal
  to ``scripts/telemetry_report.py --dir`` offline-merging the same
  ranks' dumps. Each interval it evaluates ``SMP_SLO`` at FLEET level
  into fleet goodput and appends a ``fleet_window`` record to the
  ``SMP_FLEET_PATH`` JSONL — the autoscaler's input feed (deliberately
  NOT rank-qualified: only the one live aggregator writes it, and a
  successor appends to the same file so the feed survives failover).

- A **scrape endpoint** (stdlib ``http.server`` daemon thread on
  ``SMP_METRICS_PORT``): ``/metrics`` (per-rank Prometheus text) and
  ``/metrics.json`` everywhere; ``/fleet`` (merged JSON view with
  per-rank freshness) and ``/fleet/metrics`` (merged Prometheus text)
  answer on the aggregator and 404 — with a pointer to the aggregator
  rank — elsewhere.

On top of the merged view the aggregator runs three fleet detectors,
publishing ``smp_fleet_*`` gauges and flight-recorder ``fleet`` events
on transitions:

- **straggler**: a rank whose ITL (falling back to step-time) p99 sits
  above ``SMP_FLEET_STRAGGLER_RATIO`` x the fleet median of per-rank
  p99s (lower median — deterministic and conservative in 2-rank
  fleets).
- **kv imbalance**: max/mean of per-rank used KV-pool blocks above
  ``SMP_FLEET_KV_IMBALANCE_RATIO``.
- **stale feed**: a rank that stopped publishing for
  ``SMP_FLEET_STALE_WINDOWS`` intervals but still heartbeats —
  distinct from dead (dead ranks leave the merge; stale ranks stay,
  flagged in the freshness map).

Contract shared with utils/timeseries.py: ``SMP_FLEET_INTERVAL``
unset/0 constructs NOTHING — no thread, no bus traffic, no port.
"""

import collections
import json
import os
import statistics
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    SERVE_LATENCY_KINDS,
    merge_metric_reports,
    quantile_from_counts,
    render_prometheus_report,
    telemetry,
)
from smdistributed_modelparallel_tpu.utils.timeseries import (
    SLO_ENV,
    evaluate_slo,
    parse_slo,
)

logger = get_logger()

FLEET_INTERVAL_ENV = "SMP_FLEET_INTERVAL"
FLEET_PATH_ENV = "SMP_FLEET_PATH"
METRICS_PORT_ENV = "SMP_METRICS_PORT"
STRAGGLER_RATIO_ENV = "SMP_FLEET_STRAGGLER_RATIO"
KV_IMBALANCE_RATIO_ENV = "SMP_FLEET_KV_IMBALANCE_RATIO"
STALE_WINDOWS_ENV = "SMP_FLEET_STALE_WINDOWS"

#: Reserved control tx for fleet metric snapshots (-1 exit relay, -2
#: preempt notice, -3 preempt step-edge, -4 heartbeats, -5 recovery
#: rendezvous, -6 serving mirror — see backend/native.py).
FLEET_TX = -7

#: Fleet windows kept in memory (the JSONL is the durable feed).
DEFAULT_RING = 256

_SNAPSHOT_VERSION = 1


def _flight():
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )

    return flight_recorder


def _trigger_forensics(reason, detail):
    """Detector fire edges request an auto-forensics bundle; a no-op
    while SMP_FORENSICS_PATH is unset, and never raises — the metrics
    plane must not die collecting evidence."""
    try:
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        goodput.trigger_forensics(reason, detail=detail)
    except Exception:
        logger.warning("forensics trigger (%s) failed", reason,
                       exc_info=True)


def fleet_interval():
    """Publish/aggregate cadence in seconds; 0.0 disables the plane."""
    raw = os.environ.get(FLEET_INTERVAL_ENV, "")
    if not raw:
        return 0.0
    try:
        return max(float(raw), 0.0)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r; fleet plane stays off.",
                       FLEET_INTERVAL_ENV, raw)
        return 0.0


def metrics_port():
    """Scrape-endpoint port, or None when unset (no server). 0 binds an
    ephemeral port (tests / bench); the bound port is exposed as
    ``plane.bound_port``."""
    raw = os.environ.get(METRICS_PORT_ENV, "")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r; no scrape endpoint.",
                       METRICS_PORT_ENV, raw)
        return None


def _env_ratio(name, default):
    try:
        val = float(os.environ.get(name, "") or default)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r; using %s.",
                       name, os.environ.get(name), default)
        return float(default)
    return val if val > 0 else float(default)


def straggler_ratio():
    return _env_ratio(STRAGGLER_RATIO_ENV, 2.0)


def kv_imbalance_ratio():
    return _env_ratio(KV_IMBALANCE_RATIO_ENV, 2.0)


def stale_windows():
    return max(int(_env_ratio(STALE_WINDOWS_ENV, 3.0)), 1)


def _label_key(labels):
    return tuple(sorted((labels or {}).items()))


def _lower_median(values):
    """Deterministic 'typical rank' statistic for ratio detectors: the
    lower median never averages a straggler into the baseline (a plain
    median of a 2-rank fleet would be pulled halfway toward the slow
    rank and mask it)."""
    return sorted(values)[(len(values) - 1) // 2]


def _skew(per_rank):
    """min/max/median/sum skew stats over a ``{rank: value}`` map."""
    vals = list(per_rank.values())
    return {
        "min": min(vals),
        "max": max(vals),
        "median": statistics.median(vals),
        "sum": sum(vals),
        "by_rank": {str(r): per_rank[r] for r in sorted(per_rank)},
    }


class _ScrapeHandler(BaseHTTPRequestHandler):
    """GET-only scrape surface. Every route answers from in-memory
    state; nothing here blocks on the bus."""

    # Scrapes must not spam stdout (BaseHTTPRequestHandler logs every
    # request to stderr by default).
    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def _reply(self, code, body, ctype):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code, doc):
        self._reply(code, json.dumps(doc).encode(), "application/json")

    def do_GET(self):  # noqa: N802 - stdlib signature
        plane = self.server.plane
        path = self.path.split("?", 1)[0]
        if path != "/" and path.endswith("/"):
            path = path.rstrip("/")
        try:
            if path == "/metrics":
                self._reply(200, plane.registry.render_prometheus().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/metrics.json":
                self._json(200, plane.registry.report())
            elif path in ("/fleet", "/fleet/metrics"):
                if not plane.is_aggregator:
                    self._json(404, {
                        "error": "not the aggregator",
                        "rank": plane.rank,
                        "aggregator": plane.aggregator,
                    })
                    return
                doc = plane.fleet_report()
                if path == "/fleet":
                    self._json(200, doc)
                else:
                    body = render_prometheus_report(doc["merged"]).encode()
                    self._reply(200, body,
                                "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/":
                self._json(200, {
                    "rank": plane.rank,
                    "aggregator": plane.aggregator,
                    "paths": ["/metrics", "/metrics.json", "/fleet",
                              "/fleet/metrics"],
                })
            else:
                self._json(404, {"error": f"unknown path {path!r}"})
        except Exception as e:  # a broken scrape must not kill the server
            try:
                self._json(500, {"error": str(e)})
            except OSError:
                pass


class FleetMetricsPlane:
    """Publisher + (when elected) aggregator + scrape server for one
    rank. Constructed only when ``SMP_FLEET_INTERVAL`` is set —
    ``from_env`` returns None otherwise and NOTHING is built.

    ``clock``/``wall``/``alive_fn`` are injectable for the fake-clock
    detector unit tests; ``bus=None, world=1`` is the single-process
    degenerate case (this rank aggregates itself, no traffic).
    """

    def __init__(self, registry=None, bus=None, rank=None, world=None,
                 interval=None, path=None, slo=None, port=None,
                 straggler_ratio_=None, kv_imbalance_ratio_=None,
                 stale_windows_=None, alive_fn=None,
                 clock=time.monotonic, wall=time.time):
        self.registry = registry if registry is not None else telemetry
        self.bus = bus
        if bus is not None:
            default_rank, default_world = bus.rank, bus.world
        else:
            default_rank = self.registry.process_index or 0
            default_world = self.registry.process_count or 1
        self.rank = default_rank if rank is None else int(rank)
        self.world = default_world if world is None else int(world)
        self.interval = fleet_interval() if interval is None else float(
            interval)
        self.path = os.environ.get(FLEET_PATH_ENV, "") if path is None \
            else path
        self.port = metrics_port() if port is None else port
        self.straggler_ratio = straggler_ratio() \
            if straggler_ratio_ is None else float(straggler_ratio_)
        self.kv_imbalance_ratio = kv_imbalance_ratio() \
            if kv_imbalance_ratio_ is None else float(kv_imbalance_ratio_)
        self.stale_windows = stale_windows() \
            if stale_windows_ is None else int(stale_windows_)
        if slo is None:
            raw = os.environ.get(SLO_ENV, "")
            try:
                self.slo = parse_slo(raw) if raw else None
            except ValueError as e:
                logger.warning("ignoring invalid %s: %s", SLO_ENV, e)
                self.slo = None
        else:
            self.slo = parse_slo(slo) if isinstance(slo, str) else slo
        self._alive_fn = alive_fn
        self._clock = clock
        self._wall = wall

        self._lock = threading.RLock()
        self._thread = None
        self._stop_event = threading.Event()
        self._stopped = False
        self._server = None
        self._server_thread = None
        self.bound_port = None

        self._t_start = self._clock()
        self._last_tick = None
        self._pub_seq = 0
        self._seq = 0
        self._ok_windows = 0
        self._aggregator = None
        #: {rank: {"snap": snapshot, "t": clock_time_ingested}}
        self._snapshots = {}
        #: previous merged cumulative values, for window deltas.
        self._prev_counters = None
        self._prev_hists = None
        self._last_window_t = None
        self._ring = collections.deque(maxlen=DEFAULT_RING)
        #: detector state, for transition-edge events.
        self._straggling = set()
        self._stale = set()
        self._kv_imbalanced = False

    # -- construction ---------------------------------------------------

    @classmethod
    def from_env(cls, bus=None, registry=None):
        """The PR-16 timeseries contract: interval unset/0 -> None,
        nothing constructed — no thread, no bus traffic, no port."""
        if fleet_interval() <= 0:
            return None
        return cls(registry=registry, bus=bus)

    # -- liveness / election --------------------------------------------

    def _alive(self, peer):
        if peer == self.rank:
            return True
        if self._alive_fn is not None:
            return bool(self._alive_fn(peer))
        if self.bus is None:
            return False
        from smdistributed_modelparallel_tpu.resilience.supervisor import (
            DEAD,
            classify_failed,
        )

        # Only DEAD excludes a rank from the plane: a wedged rank's
        # publisher thread may well still run, and its feed going quiet
        # is exactly what the stale-feed detector reports.
        return peer not in classify_failed(self.bus, (peer,), kinds=(DEAD,))

    def _dead_ranks(self):
        return sorted(r for r in range(self.world)
                      if r != self.rank and not self._alive(r))

    def _elect(self):
        """Lowest-alive rank. Every rank runs the same election against
        the same detector verdicts, so they converge without a round."""
        for r in range(self.world):
            if r == self.rank or self._alive(r):
                return r
        return self.rank

    @property
    def aggregator(self):
        with self._lock:
            if self._aggregator is None:
                return self._elect()
            return self._aggregator

    @property
    def is_aggregator(self):
        return self.aggregator == self.rank

    # -- publisher ------------------------------------------------------

    def _local_snapshot(self):
        report = self.registry.report()
        metrics = {}
        for name, fam in report["metrics"].items():
            # Strip help strings: they are identical on every rank and
            # would dominate the wire size of every snapshot.
            metrics[name] = {"kind": fam["kind"], "series": fam["series"]}
        return {
            "v": _SNAPSHOT_VERSION,
            "rank": self.rank,
            "seq": self._pub_seq,
            "t_wall": self._wall(),
            "phase": report["meta"].get("phase"),
            "metrics": metrics,
        }

    def _ingest(self, rank, snap, now):
        cur = self._snapshots.get(rank)
        if cur is not None and cur["snap"].get("seq", -1) > snap.get("seq",
                                                                    -1):
            return  # out-of-order frame from a slow drain
        self._snapshots[rank] = {"snap": snap, "t": now}

    # -- the per-interval tick ------------------------------------------

    def tick(self, now=None):
        """Cheap when idle: one clock read under the interval. Called
        from the daemon thread and inline from the serving engine's
        step loop (so a busy decode loop keeps the feed fresh even if
        the GIL starves the thread). Returns the fleet window dict when
        this tick aggregated one, else None."""
        with self._lock:
            if self._stopped:
                return None
            now = self._clock() if now is None else now
            if (self._last_tick is not None
                    and now - self._last_tick < self.interval):
                return None
            return self._tick_locked(now)

    def _tick_locked(self, now):
        self._last_tick = now
        self._pub_seq += 1
        snap = self._local_snapshot()
        agg = self._elect()
        if agg != self._aggregator:
            prev = self._aggregator
            self._aggregator = agg
            if prev is not None:
                logger.warning("fleet aggregator re-elected: rank %s -> %s",
                               prev, agg)
            _flight().record_fleet("elect", rank=agg,
                                   detail=f"prev={prev}")
            self.registry.gauge(
                "smp_fleet_aggregator",
                "rank currently aggregating the fleet metrics plane",
            ).set(agg)
            if agg == self.rank:
                # Takeover: our merged baseline (if any) predates the
                # gap, so the first window we cut is marked resync and
                # uses cumulative — not delta — percentiles.
                self._prev_counters = None
                self._prev_hists = None
        # Drain inbound frames regardless of role: under a stale
        # election peers may still address us, and the bus buffers are
        # bounded.
        if self.bus is not None:
            for p in range(self.world):
                if p == self.rank:
                    continue
                try:
                    frames = self.bus.drain_bytes(p, FLEET_TX)
                except Exception:
                    frames = []
                if self.rank != agg:
                    continue  # drained-and-dropped
                for raw in frames:
                    try:
                        peer_snap = json.loads(raw)
                    except (ValueError, UnicodeDecodeError):
                        continue
                    r = peer_snap.get("rank")
                    if isinstance(r, int) and 0 <= r < self.world:
                        self._ingest(r, peer_snap, now)
        if self.rank == agg:
            self._ingest(self.rank, snap, now)
            return self._aggregate_locked(now)
        if self.bus is not None:
            # rc deliberately ignored: -2 (link dead) means the
            # aggregator died; the election above flips next tick.
            self.bus.send_raw(agg, json.dumps(snap).encode(), FLEET_TX)
        return None

    # -- aggregator: merge + window + detectors -------------------------

    def _merge_ranks(self, now):
        """(ranks, merged_report, stale, dead) over alive snapshots."""
        dead = self._dead_ranks()
        entries = {r: e for r, e in self._snapshots.items()
                   if r == self.rank or self._alive(r)}
        stale = sorted(self._stale_ranks(now, entries))
        reports = {
            r: {"meta": {"rank": r}, "metrics": e["snap"]["metrics"]}
            for r, e in entries.items()
        }
        return sorted(entries), merge_metric_reports(reports), stale, dead

    def _stale_ranks(self, now, entries):
        """Alive ranks whose feed went quiet: never published, or the
        last snapshot is older than stale_windows intervals."""
        horizon = self.stale_windows * self.interval
        out = set()
        for r in range(self.world):
            if r == self.rank or not self._alive(r):
                continue
            e = entries.get(r)
            age = now - (e["t"] if e is not None else self._t_start)
            if age > horizon:
                out.add(r)
        return out

    def _per_rank_gauge(self, ranks, name, **labels):
        key = _label_key(labels)
        out = {}
        for r in ranks:
            e = self._snapshots.get(r)
            fam = e["snap"]["metrics"].get(name) if e else None
            if not fam:
                continue
            for s in fam["series"]:
                if _label_key(s.get("labels")) == key:
                    out[r] = s.get("value", 0.0)
                    break
        return out

    def _per_rank_hist(self, ranks, name, **labels):
        key = _label_key(labels)
        out = {}
        for r in ranks:
            e = self._snapshots.get(r)
            fam = e["snap"]["metrics"].get(name) if e else None
            if not fam:
                continue
            for s in fam["series"]:
                if (_label_key(s.get("labels")) == key
                        and s.get("count", 0) > 0):
                    out[r] = s
                    break
        return out

    @staticmethod
    def _hist_series(merged, name):
        fam = merged["metrics"].get(name)
        if not fam:
            return {}
        return {_label_key(s.get("labels")): s for s in fam["series"]}

    @staticmethod
    def _counter_values(merged, name):
        fam = merged["metrics"].get(name)
        if not fam:
            return {}
        return {_label_key(s.get("labels")): s.get("value", 0)
                for s in fam["series"]}

    def _aggregate_locked(self, now):
        ranks, merged, stale, dead = self._merge_ranks(now)
        self._seq += 1
        t_wall = self._wall()
        dt = now - (self._last_window_t if self._last_window_t is not None
                    else self._t_start)
        dt = max(dt, 1e-9)
        self._last_window_t = now

        counters = {
            name: self._counter_values(merged, name)
            for name in ("smp_serve_requests_total", "smp_serve_tokens_total")
        }
        hists = {}
        for kind in SERVE_LATENCY_KINDS:
            s = self._hist_series(
                merged, "smp_serve_latency_seconds").get(
                    _label_key({"kind": kind}))
            if s is not None:
                hists[kind] = s
        step = self._hist_series(merged, "smp_step_time_seconds").get(())
        if step is not None:
            hists["step_time"] = step

        resync = self._prev_counters is None
        window = {
            "kind": "fleet_window",
            "seq": self._seq,
            "t_wall": round(t_wall, 3),
            "window_s": round(dt, 3),
            "aggregator": self.rank,
            "ranks": ranks,
            "dead": dead,
            "stale": stale,
            "resync": resync,
        }

        # Counter deltas -> fleet rates.
        def delta(name, **labels):
            cur = counters.get(name, {}).get(_label_key(labels))
            if cur is None:
                return None
            if resync:
                return cur
            prev = self._prev_counters.get((name, _label_key(labels)), 0)
            return max(cur - prev, 0)

        for event in ("admitted", "finished", "readmitted",
                      "deadline_miss"):
            d = delta("smp_serve_requests_total", event=event)
            if d is not None:
                window[f"requests_{event}"] = d
        gen = delta("smp_serve_tokens_total", kind="generated")
        if gen is not None:
            window["tokens_generated"] = gen
        # Rates only on true delta windows: a resync window's "delta" is
        # the cumulative total over an ill-defined interval.
        if not resync:
            if gen is not None:
                window["tokens_per_s"] = round(gen / dt, 3)
            fin = window.get("requests_finished")
            if fin is not None:
                window["requests_per_s"] = round(fin / dt, 3)

        # Window latency percentiles from merged bucket-count deltas
        # (cumulative counts on resync windows).
        for kind, s in hists.items():
            counts, hsum, hcount = s["counts"], s["sum"], s["count"]
            if not resync:
                pkey = (kind, tuple(s["buckets"]))
                prev = self._prev_hists.get(pkey)
                if prev is not None:
                    counts = [a - b for a, b in zip(counts, prev["counts"])]
                    if min(counts) < 0:  # rank set shrank; fall back
                        counts, window["resync"] = s["counts"], True
                    else:
                        hsum = s["sum"] - prev["sum"]
                        hcount = s["count"] - prev["count"]
            if hcount <= 0:
                continue
            for stat, q in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
                val = quantile_from_counts(s["buckets"], counts, q)
                if val is not None:
                    window[f"{kind}_{stat}_ms"] = round(val * 1e3, 3)
            window[f"{kind}_mean_ms"] = round(hsum / hcount * 1e3, 3)
            window[f"{kind}_count"] = hcount

        # Per-rank gauges -> skew stats; SLO sees the worst rank.
        qd = self._per_rank_gauge(ranks, "smp_serve_queue_depth")
        if qd:
            window["queue_depth_by_rank"] = _skew(qd)
            window["queue_depth"] = max(qd.values())
        kv_used = self._per_rank_gauge(ranks, "smp_serve_kv_blocks",
                                       state="used")
        if kv_used:
            window["kv_used_by_rank"] = _skew(kv_used)

        # Fleet goodput: per-rank wall-clock attribution counters merged
        # exactly like the histograms — counter summing IS rank
        # weighting (a rank with more attributed seconds weighs more).
        good = self._counter_values(merged, "smp_goodput_seconds_total")
        bad = self._counter_values(merged, "smp_badput_seconds_total")
        if good or bad:
            good_s = sum(good.values())
            bad_s = sum(bad.values())
            total = good_s + bad_s
            if total > 0:
                window["train_goodput"] = round(good_s / total, 4)
                window["badput_by_state"] = {
                    dict(key).get("state", "?"): round(val, 3)
                    for key, val in sorted(bad.items())
                }
                self.registry.gauge(
                    "smp_fleet_train_goodput",
                    "fleet wall-clock goodput fraction (merged goodput "
                    "seconds / merged attributed seconds, rank-weighted)",
                ).set(window["train_goodput"])
            gp = self._per_rank_gauge(ranks, "smp_goodput_fraction")
            if gp:
                window["goodput_by_rank"] = _skew(gp)

        self._detect_stragglers(ranks, window)
        self._detect_kv_imbalance(kv_used, window)
        self._mark_stale(stale, dead, window)

        if self.slo:
            verdict = evaluate_slo(self.slo, window)
            if verdict["ok"]:
                self._ok_windows += 1
            verdict["goodput"] = self._ok_windows / self._seq
            window["slo"] = verdict
            self.registry.gauge(
                "smp_fleet_goodput_fraction",
                "fraction of fleet windows with zero fleet-level SLO "
                "violations",
            ).set(verdict["goodput"])

        # Remember cumulative values for the next window's deltas.
        self._prev_counters = {
            (name, key): val
            for name, vals in counters.items() for key, val in vals.items()
        }
        self._prev_hists = {
            (kind, tuple(s["buckets"])): {
                "counts": list(s["counts"]), "sum": s["sum"],
                "count": s["count"],
            }
            for kind, s in hists.items()
        }

        self.registry.gauge(
            "smp_fleet_windows", "fleet windows aggregated so far"
        ).set(self._seq)
        self.registry.gauge(
            "smp_fleet_ranks", "ranks contributing to the fleet merge"
        ).set(len(ranks))

        self._ring.append(window)
        self._append_jsonl(window)
        return window

    # -- detectors ------------------------------------------------------

    def _detect_stragglers(self, ranks, window):
        """Per-rank ITL p99 (falling back to step-time) against the
        fleet lower-median of per-rank p99s. Cumulative distributions:
        a straggler verdict is a slowly-latching signal by design (an
        autoscaler should not flap on one bad window)."""
        per_rank = self._per_rank_hist(ranks, "smp_serve_latency_seconds",
                                       kind="itl")
        source = "itl"
        if len(per_rank) < 2:
            per_rank = self._per_rank_hist(ranks, "smp_step_time_seconds")
            source = "step_time"
        g_flag = self.registry.gauge(
            "smp_fleet_straggler",
            "1 when this rank's p99 exceeds the straggler ratio x fleet "
            "median",
        )
        g_ratio = self.registry.gauge(
            "smp_fleet_straggler_ratio",
            "this rank's p99 / fleet median p99 (itl, else step time)",
        )
        if len(per_rank) < 2:
            for r in list(self._straggling):
                g_flag.labels(rank=str(r)).set(0)
            self._straggling.clear()
            return
        p99 = {
            r: quantile_from_counts(s["buckets"], s["counts"], 0.99)
            for r, s in per_rank.items()
        }
        p99 = {r: v for r, v in p99.items() if v is not None}
        if len(p99) < 2:
            return
        median = _lower_median(list(p99.values()))
        stragglers = set()
        ratios = {}
        for r, v in sorted(p99.items()):
            ratio = v / median if median > 0 else 1.0
            ratios[r] = round(ratio, 3)
            g_ratio.labels(rank=str(r)).set(ratios[r])
            is_straggler = ratio > self.straggler_ratio
            g_flag.labels(rank=str(r)).set(1 if is_straggler else 0)
            if is_straggler:
                stragglers.add(r)
        newly = sorted(stragglers - self._straggling)
        for r in newly:
            _flight().record_fleet(
                "straggler", rank=r,
                detail=f"{source} p99 ratio {ratios[r]} > "
                       f"{self.straggler_ratio}")
        if newly:
            # A straggler verdict's fire edge is evidence-worthy: one
            # rate-limited forensic bundle (no-op while disarmed).
            _trigger_forensics(
                "fleet_straggler",
                f"ranks {newly} {source} p99 over "
                f"{self.straggler_ratio}x fleet median",
            )
        for r in sorted(self._straggling - stragglers):
            _flight().record_fleet("straggler_clear", rank=r, detail=source)
        self._straggling = stragglers
        if stragglers:
            window["straggler"] = {
                "source": source,
                "ranks": sorted(stragglers),
                "ratios": {str(r): ratios[r] for r in sorted(stragglers)},
            }

    def _detect_kv_imbalance(self, kv_used, window):
        if len(kv_used) < 2:
            return
        mean = sum(kv_used.values()) / len(kv_used)
        ratio = (max(kv_used.values()) / mean) if mean > 0 else 1.0
        self.registry.gauge(
            "smp_fleet_kv_imbalance_ratio",
            "max/mean of per-rank used paged-KV blocks",
        ).set(round(ratio, 3))
        imbalanced = ratio > self.kv_imbalance_ratio
        if imbalanced:
            worst = max(kv_used, key=lambda r: kv_used[r])
            window["kv_imbalance"] = {"ratio": round(ratio, 3),
                                      "worst_rank": worst}
            if not self._kv_imbalanced:
                _flight().record_fleet(
                    "kv_imbalance", rank=worst,
                    detail=f"max/mean {ratio:.2f} > "
                           f"{self.kv_imbalance_ratio}")
                _trigger_forensics(
                    "fleet_kv_imbalance",
                    f"rank {worst} max/mean {ratio:.2f} > "
                    f"{self.kv_imbalance_ratio}",
                )
        elif self._kv_imbalanced:
            _flight().record_fleet("kv_imbalance_clear")
        self._kv_imbalanced = imbalanced

    def _mark_stale(self, stale, dead, window):
        g = self.registry.gauge(
            "smp_fleet_stale_feed",
            "1 when this rank heartbeats but stopped publishing metric "
            "snapshots",
        )
        stale = set(stale)
        for r in sorted(stale - self._stale):
            g.labels(rank=str(r)).set(1)
            _flight().record_fleet("stale_feed", rank=r)
        for r in sorted(self._stale - stale):
            g.labels(rank=str(r)).set(0)
            _flight().record_fleet("stale_feed_clear", rank=r)
        self._stale = stale
        if dead:
            window["dead"] = dead

    # -- merged views ---------------------------------------------------

    def fleet_report(self, now=None):
        """The scrape endpoint's merged JSON document: fleet percentiles
        computed from merged cumulative bucket counts — bit-equal to
        ``telemetry_report.py --dir`` over the same ranks' dumps — plus
        per-rank freshness and the merged metric families themselves."""
        with self._lock:
            now = self._clock() if now is None else now
            ranks, merged, stale, dead = self._merge_ranks(now)
            freshness = {}
            for r in ranks:
                e = self._snapshots[r]
                freshness[str(r)] = {
                    "age_s": round(max(now - e["t"], 0.0), 3),
                    "seq": e["snap"].get("seq"),
                    "phase": e["snap"].get("phase"),
                    "stale": r in stale,
                }
            percentiles = {}
            lat = self._hist_series(merged, "smp_serve_latency_seconds")
            for kind in SERVE_LATENCY_KINDS:
                s = lat.get(_label_key({"kind": kind}))
                if s is None or s.get("count", 0) <= 0:
                    continue
                percentiles[kind] = self._percentile_doc(s)
            step = self._hist_series(
                merged, "smp_step_time_seconds").get(())
            if step is not None and step.get("count", 0) > 0:
                percentiles["step_time"] = self._percentile_doc(step)
            return {
                "kind": "fleet_report",
                "t_wall": self._wall(),
                "aggregator": self.rank,
                "world": self.world,
                "ranks": ranks,
                "dead": dead,
                "stale": stale,
                "windows": self._seq,
                "freshness": freshness,
                "percentiles": percentiles,
                "merged": merged,
            }

    @staticmethod
    def _percentile_doc(series):
        doc = {"count": series["count"]}
        for stat, q in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
            doc[f"{stat}_s"] = quantile_from_counts(
                series["buckets"], series["counts"], q)
        if series["count"] > 0:
            doc["mean_s"] = series["sum"] / series["count"]
        return doc

    def windows(self):
        with self._lock:
            return list(self._ring)

    def last_window(self):
        """Newest aggregated window, or None before the first closes.
        The serving controller's policy tick reads this: one fresh
        window per evaluation, no ring scan."""
        with self._lock:
            return self._ring[-1] if self._ring else None

    @property
    def straggling(self):
        with self._lock:
            return set(self._straggling)

    def _append_jsonl(self, window):
        if not self.path:
            return
        # Deliberately NOT rank-qualified (unlike every other dump):
        # only the live aggregator writes, and a successor appending to
        # the same file is what keeps the feed continuous across
        # failover.
        try:
            with open(self.path, "a") as fh:
                fh.write(json.dumps(window) + "\n")
        except OSError as e:
            logger.warning("fleet window append to %s failed: %s",
                           self.path, e)

    # -- lifecycle ------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="smp-fleet", daemon=True)
        self._thread.start()
        if self.port is not None:
            self._start_server()
        return self

    def _loop(self):
        while not self._stop_event.wait(self.interval):
            try:
                self.tick()
            except Exception:  # the metrics plane must never kill a run
                logger.warning("fleet tick failed", exc_info=True)

    def _start_server(self):
        try:
            server = ThreadingHTTPServer(("", self.port), _ScrapeHandler)
        except OSError as e:
            logger.warning("could not bind %s=%s: %s; no scrape endpoint.",
                           METRICS_PORT_ENV, self.port, e)
            return
        server.daemon_threads = True
        server.plane = self
        self._server = server
        self.bound_port = server.server_address[1]
        self._server_thread = threading.Thread(
            target=server.serve_forever, name="smp-fleet-http", daemon=True)
        self._server_thread.start()
        logger.info("fleet scrape endpoint on port %s", self.bound_port)

    def stop(self):
        """Final-flush + teardown; idempotent. Runs BEFORE the exit
        relay closes the bus (core.shutdown ordering), so the last
        snapshot/window still travels."""
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            if not self._stopped:
                self._stopped = True
                try:
                    now = self._clock()
                    if self.is_aggregator:
                        self._ingest(self.rank, self._local_snapshot(), now)
                        self._aggregate_locked(now)
                    elif self.bus is not None:
                        self.bus.send_raw(
                            self._aggregator
                            if self._aggregator is not None
                            else self._elect(),
                            json.dumps(self._local_snapshot()).encode(),
                            FLEET_TX)
                except Exception:
                    logger.warning("fleet final flush failed", exc_info=True)
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._server_thread = None
            self.bound_port = None


class FleetController:
    """Process-wide singleton (``smp.fleet``): owns the plane's
    lifecycle so core init/shutdown and the serving engine never have
    to know whether the plane is enabled."""

    def __init__(self):
        self.plane = None

    def start(self, bus=None):
        """(Re-)construct from env. Called by state.initialize after the
        supervisor is up; recovery re-init lands here again, so an
        existing plane is stopped first."""
        self.stop()
        if bus is None:
            bus = self._bus()
        self.plane = FleetMetricsPlane.from_env(bus=bus)
        if self.plane is not None:
            self.plane.start()
        return self.plane

    @staticmethod
    def _bus():
        from smdistributed_modelparallel_tpu.backend.state import state

        comm = getattr(state, "_comm", None)
        return getattr(comm, "_bus", None) if comm is not None else None

    def tick(self):
        if self.plane is not None:
            self.plane.tick()

    def last_window(self):
        return self.plane.last_window() if self.plane is not None else None

    def stop(self):
        if self.plane is not None:
            plane, self.plane = self.plane, None
            plane.stop()

    def reset(self):
        self.stop()


fleet = FleetController()
