"""Typed exception hierarchy.

Parity target: reference ``backend/exceptions.py:1-76`` (SMPValidationError /
SMPRuntimeError hierarchy) and the ~70 typed errors of ``torch/exceptions.py``.
Only the errors meaningful under an SPMD/XLA runtime are kept; the
request/response-runtime errors of the reference (dummy-tensor misuse, link
exhaustion, ...) have no TPU-native counterpart.
"""


class SMPError(Exception):
    """Base class for all framework errors."""


class SMPValidationError(SMPError):
    """User-facing configuration / usage validation error."""


class SMPRuntimeError(SMPError):
    """Internal invariant violation."""


class SMPUnsupportedError(SMPError):
    """Feature exists in the reference API but is not supported in this build."""


class NotInitializedError(SMPValidationError):
    def __init__(self, what="smp"):
        super().__init__(
            f"{what} has not been initialized. Call smp.init(config) before using the framework."
        )


class ConfigError(SMPValidationError):
    """Invalid configuration value or combination (schema validation)."""


class DeviceCountError(SMPValidationError):
    def __init__(self, required, available):
        super().__init__(
            f"Model-parallel degree product ({required} = pipeline * tensor * context "
            f"* expert) must divide the device count ({available} available)."
        )


class MicrobatchError(SMPValidationError):
    """Batch not divisible into the configured number of microbatches."""


class PartitionError(SMPValidationError):
    """Invalid manual partition assignment or partitioner failure."""


class TensorParallelismError(SMPValidationError):
    """Invalid tensor-parallelism registration or module distribution failure."""


class StepUsageError(SMPValidationError):
    """Misuse of @smp.step (e.g. model.backward never called, nested steps)."""


class CheckpointError(SMPValidationError):
    """Checkpoint save/load failure or incompatible smp config on resume."""


class SMPWatchdogTimeout(SMPRuntimeError):
    """A watchdog-guarded wait (collective, device probe) stalled past
    SMP_WATCHDOG_TIMEOUT; diagnostics were dumped (utils/telemetry.py)."""


class SMPPeerLost(SMPRuntimeError):
    """A native-bus peer is unreachable: the send path exhausted its
    bounded retry/backoff budget (``SMP_BUS_SEND_RETRIES``), or a receive
    /barrier wait found the peer's link already marked dead
    (``backend/native.py``). Carries ``peer`` (process index) so recovery
    logic can exclude the dead rank instead of parsing the message."""

    def __init__(self, peer, message=None):
        self.peer = int(peer)
        super().__init__(
            message or f"native-bus peer (process {peer}) is unreachable."
        )


class SMPCollectiveTimeout(SMPRuntimeError):
    """A host collective exceeded ``SMP_COLLECTIVE_TIMEOUT``. Unlike the
    global watchdog (which dumps and raises for ANY stall), this is a
    per-operation deadline with enough structure for the failure-recovery
    supervisor to distinguish "slow" from "gone": it carries the group
    name, the phase (barrier / recv / ...), and the group's last
    flight-recorder collective sequence number — the coordinate at which
    this rank's collective stream stopped."""

    def __init__(self, group, phase, last_seq=-1, message=None):
        self.group = str(group)
        self.phase = str(phase)
        self.last_seq = int(last_seq)
        super().__init__(
            message
            or f"host collective over {group} timed out in phase "
            f"'{phase}' (last collective seq {last_seq}; bound set by "
            "SMP_COLLECTIVE_TIMEOUT)."
        )


class SMPRecoveryError(SMPRuntimeError):
    """In-job failure recovery could not complete (rendezvous failed, no
    common committed checkpoint, world re-initialization failed). The
    supervisor dumps its detector state + the flight-recorder ring before
    raising this (``resilience/supervisor.py``)."""


class SMPEvicted(SMPRuntimeError):
    """Surviving peers reformed the world WITHOUT this rank (it was
    classified dead/wedged — e.g. it was wedged long enough to exhaust
    ``SMP_WEDGE_TIMEOUT`` and came back after the shrink). The rank must
    exit instead of training on as a split-brain singleton."""


class DelayedParamError(SMPRuntimeError):
    """Materialization of delayed-initialized parameters failed."""


class OffloadError(SMPRuntimeError):
    """Activation offloading failure."""
