"""Step memory metrics and compile/communication reporting.

Parity target: reference ``StepMemoryMetricsCollector``
(``torch/step.py:69-115``, env ``SMP_WRITE_STEP_MEMORY_METRICS`` — per-step
file dump of allocator peaks + D2D pool stats, native struct
``backend/core.py:538-562``) and the one-time metrics upload of comm
volume / hop counts / per-device params (``torch/step.py:295-312``,
``backend/utils.py:134-149``).

TPU-native: allocator peaks come from ``device.memory_stats()`` (HBM pool),
and the comm/FLOP profile of the compiled step comes from XLA's
``cost_analysis`` — the reference's hand-counted comm volume is the
compiler's own accounting here.
"""

import json
import os

import jax

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import telemetry

logger = get_logger()

MEMORY_METRICS_ENV = "SMP_WRITE_STEP_MEMORY_METRICS"


def record_device_memory_telemetry():
    """Per-device allocator gauges for the telemetry report (peak HBM is
    what the step report CLI surfaces). Backends without allocator stats
    (XLA:CPU) simply record nothing. Runs unconditionally on the per-step
    dispatch path: ``smp.telemetry.report()`` / ``render_prometheus()`` are
    live surfaces that must contain memory gauges without any env var, and
    the cost is one local memory_stats() round-trip per device per step."""
    for d in jax.local_devices():
        try:
            ms = d.memory_stats() or {}
        except Exception:
            continue
        for key, metric in (
            ("peak_bytes_in_use", "smp_device_peak_hbm_bytes"),
            ("bytes_in_use", "smp_device_hbm_bytes_in_use"),
            ("bytes_limit", "smp_device_hbm_bytes_limit"),
        ):
            if ms.get(key) is not None:
                telemetry.gauge(
                    metric, "device allocator stats (memory_stats)"
                ).labels(device=str(d)).set(int(ms[key]))


class StepMemoryMetricsCollector:
    """Writes per-step device memory metrics when enabled by env."""

    def __init__(self, path=None):
        self.enabled = os.environ.get(MEMORY_METRICS_ENV, "") not in ("", "0")
        self.path = path or os.environ.get(
            "SMP_STEP_MEMORY_METRICS_PATH", "smp_step_memory_metrics.jsonl"
        )

    def record_step(self, step):
        if not self.enabled:
            return
        stats = {}
        for d in jax.local_devices():
            try:
                ms = d.memory_stats() or {}
            except Exception:
                ms = {}
            stats[str(d)] = {
                k: ms.get(k)
                for k in (
                    "bytes_in_use",
                    "peak_bytes_in_use",
                    "largest_alloc_size",
                    "bytes_limit",
                )
                if k in ms
            }
        with open(self.path, "a") as f:
            f.write(json.dumps({"step": step, "devices": stats}) + "\n")


def one_time_compile_report(step_name, lowered_or_compiled):
    """Log FLOPs / bytes-accessed of a compiled step once.

    Parity: the reference's one-time Studio metrics upload (comm volume,
    hops, per-device params — ``torch/step.py:295-312``).
    """
    report = {"name": step_name}
    try:
        cost = lowered_or_compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        report["flops"] = cost.get("flops")
        report["bytes_accessed"] = cost.get("bytes accessed")
    except Exception as e:  # pragma: no cover
        logger.debug("cost_analysis unavailable: %s", e)
    try:
        ma = lowered_or_compiled.memory_analysis()
        if ma is not None:
            for k in (
                "temp_size_in_bytes",
                "argument_size_in_bytes",
                "output_size_in_bytes",
                "generated_code_size_in_bytes",
            ):
                report[k] = getattr(ma, k, None)
    except Exception as e:  # pragma: no cover
        logger.debug("memory_analysis unavailable: %s", e)
    logger.info(
        "[metrics] %s: flops=%s bytes_accessed=%s temp_bytes=%s",
        step_name, report.get("flops"), report.get("bytes_accessed"),
        report.get("temp_size_in_bytes"),
    )
    # XLA's own accounting of the compiled step — the compiler-counted
    # analogue of the reference's hand-counted comm volume upload.
    for key, metric in (
        ("flops", "smp_compiled_step_flops"),
        ("bytes_accessed", "smp_compiled_step_bytes_accessed"),
        ("temp_size_in_bytes", "smp_compiled_step_temp_bytes"),
        ("argument_size_in_bytes", "smp_compiled_step_argument_bytes"),
        ("output_size_in_bytes", "smp_compiled_step_output_bytes"),
    ):
        if report.get(key) is not None:
            telemetry.gauge(
                metric, "XLA cost/memory analysis of the compiled step"
            ).labels(step=step_name).set(float(report[key]))
    return report
