"""Step memory metrics and compile/communication reporting.

Parity target: reference ``StepMemoryMetricsCollector``
(``torch/step.py:69-115``, env ``SMP_WRITE_STEP_MEMORY_METRICS`` — per-step
file dump of allocator peaks + D2D pool stats, native struct
``backend/core.py:538-562``) and the one-time metrics upload of comm
volume / hop counts / per-device params (``torch/step.py:295-312``,
``backend/utils.py:134-149``).

TPU-native: allocator peaks come from ``device.memory_stats()`` (HBM pool),
and the comm/FLOP profile of the compiled step comes from XLA's
``cost_analysis`` — the reference's hand-counted comm volume is the
compiler's own accounting here.
"""

import json
import os

import jax

from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

MEMORY_METRICS_ENV = "SMP_WRITE_STEP_MEMORY_METRICS"


class StepMemoryMetricsCollector:
    """Writes per-step device memory metrics when enabled by env."""

    def __init__(self, path=None):
        self.enabled = os.environ.get(MEMORY_METRICS_ENV, "") not in ("", "0")
        self.path = path or os.environ.get(
            "SMP_STEP_MEMORY_METRICS_PATH", "smp_step_memory_metrics.jsonl"
        )

    def record_step(self, step):
        if not self.enabled:
            return
        stats = {}
        for d in jax.local_devices():
            try:
                ms = d.memory_stats() or {}
            except Exception:
                ms = {}
            stats[str(d)] = {
                k: ms.get(k)
                for k in (
                    "bytes_in_use",
                    "peak_bytes_in_use",
                    "largest_alloc_size",
                    "bytes_limit",
                )
                if k in ms
            }
        with open(self.path, "a") as f:
            f.write(json.dumps({"step": step, "devices": stats}) + "\n")


def one_time_compile_report(step_name, lowered_or_compiled):
    """Log FLOPs / bytes-accessed of a compiled step once.

    Parity: the reference's one-time Studio metrics upload (comm volume,
    hops, per-device params — ``torch/step.py:295-312``).
    """
    report = {"name": step_name}
    try:
        cost = lowered_or_compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        report["flops"] = cost.get("flops")
        report["bytes_accessed"] = cost.get("bytes accessed")
    except Exception as e:  # pragma: no cover
        logger.debug("cost_analysis unavailable: %s", e)
    try:
        ma = lowered_or_compiled.memory_analysis()
        if ma is not None:
            for k in (
                "temp_size_in_bytes",
                "argument_size_in_bytes",
                "output_size_in_bytes",
                "generated_code_size_in_bytes",
            ):
                report[k] = getattr(ma, k, None)
    except Exception as e:  # pragma: no cover
        logger.debug("memory_analysis unavailable: %s", e)
    logger.info(
        "[metrics] %s: flops=%s bytes_accessed=%s temp_bytes=%s",
        step_name, report.get("flops"), report.get("bytes_accessed"),
        report.get("temp_size_in_bytes"),
    )
    return report
