"""Process-wide telemetry registry + hang watchdog.

The reference uploads a one-time comm-volume / hop-count profile per run
(``torch/step.py:295-312``, ``backend/utils.py:134-149``) and counts the
bytes of every NCCL collective by hand. This module is the TPU build's
generalization: a thread-safe metrics registry (counters, gauges,
histograms, all with optional labels) that every layer of the stack feeds —

- ``backend/collectives.py``: per-collective op counts / payload bytes /
  group sizes (the hand-counted comm volume, now live);
- ``parallel/pipeline.py`` / ``pipeline_1f1b.py``: schedule slot occupancy
  -> measured pipeline bubble fraction vs the theoretical
  ``(pp-1)/(mb+pp-1)``;
- ``step.py`` / ``utils/metrics.py``: compile-cache hits/misses, compile
  wall time, XLA ``cost_analysis`` FLOPs/bytes, per-step peak HBM.

Exports: ``smp.telemetry.report()`` (plain dict), ``render_prometheus()``
(text exposition format), and a JSON dump — written on demand, at
``smp.shutdown``, and from an ``atexit`` hook — to ``SMP_TELEMETRY_PATH``.
``scripts/telemetry_report.py`` pretty-prints the dump.

The **watchdog** (``SMP_WATCHDOG_TIMEOUT`` seconds; unset/0 = off) turns
silent wedges (a stalled collective, a hung device probe — see BENCH_r05's
eight silent 150 s probe hangs) into actionable dumps: when a guarded
operation overruns the timeout, the full registry state, the per-rank
last-known phase, and every thread's stack are written to stderr and to
``SMP_WATCHDOG_PATH`` (default ``smp_watchdog_dump.json``). Pollable waits
(the native bus) additionally *raise* ``SMPWatchdogTimeout`` instead of
blocking forever; non-interruptible waits (XLA global syncs) dump from a
timer thread and keep waiting — the dump is the diagnostic.

Import-hygiene contract: this module must import nothing that initializes
an accelerator backend (stdlib + the package logger/exceptions only).
"""

import atexit
import copy
import json
import os
import sys
import threading
import time
import traceback

from smdistributed_modelparallel_tpu.utils.exceptions import SMPWatchdogTimeout
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

TELEMETRY_PATH_ENV = "SMP_TELEMETRY_PATH"
WATCHDOG_TIMEOUT_ENV = "SMP_WATCHDOG_TIMEOUT"
WATCHDOG_PATH_ENV = "SMP_WATCHDOG_PATH"

# Powers-of-4 seconds-scale buckets: host control-plane operations span
# ~1ms (local bus delivery) to minutes (XLA pipeline compiles).
DEFAULT_BUCKETS = (
    0.001, 0.004, 0.016, 0.064, 0.25, 1.0, 4.0, 16.0, 64.0, 256.0,
)


def _geometric_buckets(lo, hi, growth):
    """Geometric bucket boundaries ``lo * growth**i`` up to the first
    boundary >= ``hi``. Deterministic (the same tuple in every process),
    which is what makes per-rank histogram dumps mergeable by
    element-wise count addition."""
    out = []
    b = float(lo)
    while b < hi:
        out.append(round(b, 9))
        b *= growth
    out.append(round(b, 9))
    return tuple(out)


# Log-spaced buckets behind the streaming percentile histograms:
# 0.5 ms .. ~4 min at 1.3x growth (~50 buckets — fixed memory however
# many samples stream through). Serving latencies (queue wait, TTFT,
# ITL, prefill, decode step) and training step times all live in this
# range; the relative quantile error is bounded by the growth factor.
LATENCY_BUCKETS = _geometric_buckets(5e-4, 240.0, 1.3)

#: Serving latency distributions the engine feeds (the ``kind`` label of
#: ``smp_serve_latency_seconds`` and the stem of the per-kind gauges).
SERVE_LATENCY_KINDS = ("ttft", "itl", "queue_wait", "prefill",
                       "decode_step")


def quantile_from_counts(buckets, counts, q):
    """Estimate the q-quantile (0..1) of a bucketed distribution.

    Log-interpolates inside geometric buckets (linearly inside the first
    bucket, which starts at 0); the overflow bucket clamps to the last
    boundary. Returns None for an empty histogram. Operates on the
    (buckets, counts) lists a histogram snapshot/dump carries, so report
    scripts can compute percentiles of cross-rank MERGED counts with the
    same arithmetic (``scripts/telemetry_report.py`` keeps a stdlib
    copy)."""
    total = sum(counts)
    if total <= 0:
        return None
    target = min(max(float(q), 0.0), 1.0) * total
    acc = 0.0
    for i, c in enumerate(counts):
        if c > 0 and acc + c >= target:
            if i >= len(buckets):
                return float(buckets[-1])
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            f = (target - acc) / c
            if lo > 0.0:
                return float(lo * (hi / lo) ** f)
            return float(lo + (hi - lo) * f)
        acc += c
    return float(buckets[-1])


def merge_metric_reports(reports):
    """Merge per-rank telemetry reports into ONE fleet-level report:
    counters and histogram series summed element-wise across ranks
    (bucket-count addition — every rank shares the same deterministic
    bucket tuples, so merged percentiles via ``quantile_from_counts``
    are exact), gauges maxed (peak HBM keeps the worst device). Series
    are matched by (metric, label-set).

    This is the single cross-rank merge: the live fleet aggregator
    (``utils/fleet.py``), ``scripts/telemetry_report.py --dir`` and
    ``scripts/slo_report.py --fleet`` all call it, so an offline merge
    of per-rank dumps is bit-equal to the on-fleet live view.

    ``reports`` is either ``{rank: report}`` or an iterable of reports
    (ranks then come from each report's own meta, falling back to load
    order). Inputs are not mutated.
    """
    if isinstance(reports, dict):
        items = [(r, reports[r]) for r in sorted(reports)]
    else:
        items = [
            (rep.get("meta", {}).get("rank", i) if isinstance(rep, dict)
             else i, rep)
            for i, rep in enumerate(reports)
        ]
    out = {"meta": {"ranks": [r for r, _ in items]}, "metrics": {}}
    for _, report in items:
        for name, fam in report.get("metrics", {}).items():
            ofam = out["metrics"].setdefault(
                name, {"kind": fam["kind"], "help": fam.get("help", ""),
                       "series": []},
            )
            for series in fam.get("series", []):
                key = _label_key(series.get("labels", {}))
                dst = None
                for s in ofam["series"]:
                    if _label_key(s.get("labels", {})) == key:
                        dst = s
                        break
                if dst is None:
                    ofam["series"].append(copy.deepcopy(series))
                    continue
                if fam["kind"] == "histogram":
                    dst["sum"] = dst.get("sum", 0.0) + series.get("sum", 0.0)
                    dst["count"] = dst.get("count", 0) + series.get("count", 0)
                    if dst.get("buckets") == series.get("buckets"):
                        dst["counts"] = [
                            a + b for a, b in zip(dst["counts"],
                                                  series["counts"])
                        ]
                    else:
                        # Mixed-build dumps: sum/count merge fine, the
                        # per-bucket distribution cannot — say so rather
                        # than render a distribution that doesn't add up.
                        logger.warning(
                            "histogram %s has differing buckets across "
                            "ranks; merged bucket counts reflect only "
                            "the first rank", name,
                        )
                elif fam["kind"] == "counter":
                    dst["value"] = dst.get("value", 0) + series.get("value", 0)
                else:  # gauge: keep the worst rank
                    dst["value"] = max(dst.get("value", 0),
                                       series.get("value", 0))
    return out


def render_prometheus_report(report):
    """Prometheus text exposition of a report dict — the live registry's
    ``report()`` or a ``merge_metric_reports`` fleet view (the fleet
    scrape endpoint renders merged metrics through this same path)."""
    out = []
    for name, fam in sorted(report.get("metrics", {}).items()):
        if fam.get("help"):
            out.append(f"# HELP {name} {fam['help']}")
        out.append(f"# TYPE {name} {fam['kind']}")
        for series in fam["series"]:
            lab = ",".join(
                f'{k}="{v}"' for k, v in sorted(series["labels"].items())
            )
            if fam["kind"] == "histogram":
                acc = 0
                for b, c in zip(
                    list(series["buckets"]) + ["+Inf"], series["counts"]
                ):
                    acc += c
                    ble = (lab + "," if lab else "") + f'le="{b}"'
                    out.append(f"{name}_bucket{{{ble}}} {acc}")
                sfx = f"{{{lab}}}" if lab else ""
                out.append(f"{name}_sum{sfx} {series['sum']}")
                out.append(f"{name}_count{sfx} {series['count']}")
            else:
                sfx = f"{{{lab}}}" if lab else ""
                out.append(f"{name}{sfx} {series['value']}")
    return "\n".join(out) + "\n"


def _label_key(labels):
    return tuple(sorted(labels.items()))


def _atomic_json_dump(payload, path, what):
    """Temp-file + rename so a reader (or a concurrent writer) never sees a
    torn JSON. Returns the path written, or None on failure."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path
    except OSError as e:
        logger.warning("%s to %s failed: %s", what, path, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


class _Child:
    """One (metric, label-set) time series. Thread-safe."""

    def __init__(self, kind, labels, buckets=None):
        self._kind = kind
        self._labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0
        if kind == "histogram":
            self._buckets = tuple(buckets or DEFAULT_BUCKETS)
            self._counts = [0] * (len(self._buckets) + 1)
            self._sum = 0.0
            self._count = 0

    # -- counter / gauge --

    def inc(self, value=1):
        if self._kind == "counter" and value < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += value

    def dec(self, value=1):
        if self._kind != "gauge":
            raise ValueError("dec() is gauge-only")
        with self._lock:
            self._value -= value

    def set(self, value):
        if self._kind != "gauge":
            raise ValueError("set() is gauge-only")
        with self._lock:
            self._value = float(value)

    @property
    def value(self):
        with self._lock:
            return self._value

    # -- histogram --

    def observe(self, value):
        if self._kind != "histogram":
            raise ValueError("observe() is histogram-only")
        v = float(value)
        with self._lock:
            i = 0
            while i < len(self._buckets) and v > self._buckets[i]:
                i += 1
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def _snapshot(self):
        with self._lock:
            if self._kind == "histogram":
                return {
                    "labels": self._labels,
                    "buckets": list(self._buckets),
                    "counts": list(self._counts),
                    "sum": self._sum,
                    "count": self._count,
                }
            return {"labels": self._labels, "value": self._value}


class _Family:
    """A named metric; ``labels(**kw)`` returns the per-label-set child.

    Label-less metrics proxy inc/dec/set/observe/value straight to their
    single default child, so ``registry.counter("x").inc()`` works.
    """

    def __init__(self, name, kind, help="", buckets=None):
        self.name = name
        self.kind = kind
        self.help = help
        self._buckets = buckets
        self._lock = threading.Lock()
        self._children = {}

    def labels(self, **kw):
        key = _label_key(kw)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _Child(self.kind, kw, self._buckets)
                self._children[key] = child
            return child

    def _default(self):
        return self.labels()

    def inc(self, value=1):
        self._default().inc(value)

    def dec(self, value=1):
        self._default().dec(value)

    def set(self, value):
        self._default().set(value)

    def observe(self, value):
        self._default().observe(value)

    @property
    def value(self):
        return self._default().value

    def _snapshot(self):
        with self._lock:
            children = list(self._children.values())
        return {
            "kind": self.kind,
            "help": self.help,
            "series": [c._snapshot() for c in children],
        }


class TelemetryRegistry:
    """Process-wide metric registry. All methods are thread-safe;
    registration is idempotent (same name -> same family) but re-registering
    a name under a different kind is a bug and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}
        self._phase = "startup"
        self._phase_ts = time.time()
        self._phase_history = []
        self._created = time.time()
        # Set by backend/core.py at smp.init (asking jax at dump time could
        # itself initialize — or hang on — a wedged backend at exit).
        self.process_index = None
        self.process_count = 1
        # Installed by utils/flight_recorder.py at import: phase
        # transitions flow into the flight-recorder ring without this
        # module importing it (telemetry must stay the leaf of the
        # observability import graph).
        self._phase_listener = None

    # -- registration ---------------------------------------------------

    def _family(self, name, kind, help, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help, buckets)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}"
                )
            return fam

    def counter(self, name, help=""):
        return self._family(name, "counter", help)

    def gauge(self, name, help=""):
        return self._family(name, "gauge", help)

    def histogram(self, name, help="", buckets=None):
        return self._family(name, "histogram", help, buckets)

    # -- phase tracking (consumed by the watchdog dump) -----------------

    def set_phase(self, phase):
        """Record the process's last-known phase (e.g. "step_3/compile").
        Bounded history so a wedged run's dump shows how it got there."""
        with self._lock:
            self._phase = phase
            self._phase_ts = time.time()
            self._phase_history.append((phase, self._phase_ts))
            if len(self._phase_history) > 64:
                del self._phase_history[:-64]
        listener = self._phase_listener
        if listener is not None:
            listener(phase)

    @property
    def phase(self):
        with self._lock:
            return self._phase

    # -- export ---------------------------------------------------------

    def report(self):
        """Plain-dict snapshot of every metric plus phase metadata."""
        with self._lock:
            families = dict(self._families)
            meta = {
                "pid": os.getpid(),
                "rank": self.process_index,
                "world": self.process_count,
                "created": self._created,
                "exported": time.time(),
                "phase": self._phase,
                "phase_age_seconds": time.time() - self._phase_ts,
                "phase_history": [
                    {"phase": p, "time": t} for p, t in self._phase_history
                ],
            }
        return {
            "meta": meta,
            "metrics": {n: f._snapshot() for n, f in families.items()},
        }

    def render_prometheus(self):
        """Prometheus text exposition format (for scraping or eyeballing)."""
        return render_prometheus_report(self.report())

    def _rank_path(self, path):
        """Multi-process runs write per-rank files: N processes dumping the
        one SMP_TELEMETRY_PATH (shared filesystem) would clobber each other."""
        if self.process_count > 1 and self.process_index is not None:
            return f"{path}.rank{self.process_index}"
        return path

    def dump(self, path=None):
        """Write the JSON report (atomically; rank-suffixed under
        multi-process). Explicit ``path`` wins; otherwise
        ``SMP_TELEMETRY_PATH`` (no-op when neither is set). Returns the
        path written, or None."""
        path = path or os.environ.get(TELEMETRY_PATH_ENV)
        if not path:
            return None
        path = self._rank_path(path)
        return _atomic_json_dump(self.report(), path, "telemetry dump")

    def reset(self):
        """Testing hook: drop every metric and the phase history."""
        with self._lock:
            self._families.clear()
            self._phase = "startup"
            self._phase_ts = time.time()
            self._phase_history.clear()


class Watchdog:
    """Stall detector for blocking control-plane operations.

    The timeout is read from ``SMP_WATCHDOG_TIMEOUT`` at *call* time (not
    import time), so tests and long-running jobs can arm/disarm it without
    reimporting. Two usage shapes:

    - ``with watchdog.guard("barrier/step"):`` — a timer thread dumps the
      diagnostics if the block outlives the timeout (the block itself keeps
      waiting: XLA syncs are not interruptible from Python);
    - ``watchdog.wait(poll_fn, "recv/peer3")`` — polls until ``poll_fn()``
      is truthy; on timeout dumps AND raises ``SMPWatchdogTimeout``.

    The native bus integrates directly (``backend/native.py``): unbounded C
    waits are sliced against the watchdog deadline so they stay bounded.
    """

    def __init__(self, registry):
        self._registry = registry
        self._dump_lock = threading.Lock()

    # -- configuration --------------------------------------------------

    def timeout(self):
        """Configured timeout in seconds, or None when disabled."""
        raw = os.environ.get(WATCHDOG_TIMEOUT_ENV, "")
        if not raw:
            return None
        try:
            t = float(raw)
        except ValueError:
            logger.warning(
                "invalid %s=%r (want seconds); watchdog disabled.",
                WATCHDOG_TIMEOUT_ENV, raw,
            )
            return None
        return t if t > 0 else None

    @property
    def enabled(self):
        return self.timeout() is not None

    # -- diagnostics ----------------------------------------------------

    def dump(self, reason, phase=None):
        """Snapshot registry + phase + all thread stacks to stderr and the
        SMP_WATCHDOG_PATH JSON file. Never raises (a broken dump must not
        mask the stall it is reporting). Returns the dump dict."""
        with self._dump_lock:
            try:
                # Mark the stall in the ring first: the snapshot below then
                # carries it, and later dumps show this one as history.
                try:
                    _flight().record_watchdog(reason)
                except Exception:
                    pass
                stacks = {}
                frames = sys._current_frames()
                names = {t.ident: t.name for t in threading.enumerate()}
                for tid, frame in frames.items():
                    stacks[f"{names.get(tid, '?')}:{tid}"] = (
                        traceback.format_stack(frame)
                    )
                payload = {
                    "reason": reason,
                    "phase": phase or self._registry.phase,
                    "time": time.time(),
                    "pid": os.getpid(),
                    "threads": stacks,
                    "telemetry": self._registry.report(),
                    # The last ~N structured events (collectives with seq
                    # numbers, schedule slots, phases): what this rank was
                    # DOING, not just where its threads are parked.
                    "flight_recorder": _flight_snapshot(),
                    # Wall-clock attribution at the stall: the current
                    # goodput state, per-state seconds, and the last N
                    # state TRANSITIONS — strictly more than the phase
                    # string (the 64-entry _phase_history only shows
                    # phases, not where the seconds went). None when the
                    # ledger is disarmed. Marking the stall first means
                    # the wedged seconds start accruing from the dump.
                    "goodput": _goodput_snapshot(reason),
                }
                path = self._registry._rank_path(
                    os.environ.get(WATCHDOG_PATH_ENV, "smp_watchdog_dump.json")
                )
                path = _atomic_json_dump(payload, path, "watchdog dump")
                sys.stderr.write(
                    "\n=== SMP WATCHDOG: %s (phase=%s) ===\n"
                    "full dump: %s\n" % (reason, payload["phase"], path)
                )
                for tname, stack in stacks.items():
                    sys.stderr.write(f"--- thread {tname} ---\n")
                    sys.stderr.write("".join(stack[-6:]))
                sys.stderr.flush()
                return payload
            except Exception:  # pragma: no cover - diagnostics must not throw
                return None

    # -- guards ---------------------------------------------------------

    class _Guard:
        def __init__(self, watchdog, phase, timeout):
            self._watchdog = watchdog
            self._phase = phase
            self._timeout = timeout
            self._timer = None
            self.fired = False

        def __enter__(self):
            if self._timeout is not None:
                self._timer = threading.Timer(self._timeout, self._on_stall)
                self._timer.daemon = True
                self._timer.start()
            return self

        def _on_stall(self):
            self.fired = True
            self._watchdog.dump(
                f"operation exceeded {self._timeout}s", phase=self._phase
            )

        def __exit__(self, *exc):
            if self._timer is not None:
                self._timer.cancel()
            return False

    def guard(self, phase):
        """Context manager: dump diagnostics if the body outlives the
        configured timeout. No-op (no timer thread) when disabled."""
        return self._Guard(self, phase, self.timeout())

    def wait(self, poll, phase, interval=0.05, timeout=None):
        """Poll ``poll()`` until truthy. On watchdog timeout: dump + raise
        ``SMPWatchdogTimeout``. With the watchdog disabled (and no explicit
        ``timeout``), polls forever — matching the unguarded behavior."""
        limit = timeout if timeout is not None else self.timeout()
        deadline = None if limit is None else time.monotonic() + limit
        while True:
            result = poll()
            if result:
                return result
            if deadline is not None and time.monotonic() >= deadline:
                self.dump(f"wait exceeded {limit}s", phase=phase)
                raise SMPWatchdogTimeout(
                    f"watchdog: {phase} stalled for more than {limit}s "
                    "(diagnostics dumped; see stderr / "
                    f"{os.environ.get(WATCHDOG_PATH_ENV, 'smp_watchdog_dump.json')})."
                )
            time.sleep(interval)


# ----------------------------------------------------------------------
# Singletons + convenience recorders
# ----------------------------------------------------------------------

telemetry = TelemetryRegistry()
watchdog = Watchdog(telemetry)

# Lazy seam to utils/flight_recorder.py (it imports THIS module for
# _rank_path, so the reverse edge must not exist at import time). The
# recorder-disabled case stays near-free: one module-attr lookup + the
# recorder's own `enabled` test.
_flight_mod = None


def _flight():
    global _flight_mod
    if _flight_mod is None:
        from smdistributed_modelparallel_tpu.utils import flight_recorder

        _flight_mod = flight_recorder
    return _flight_mod.flight_recorder


def _flight_snapshot():
    try:
        fr = _flight()
        return {"meta": fr._meta(), "events": fr.snapshot()}
    except Exception:  # pragma: no cover - diagnostics must not throw
        return None


def _goodput_snapshot(reason):
    """The goodput-ledger block for a watchdog stall dump, or None when
    the ledger is disarmed. Lazy import: telemetry stays the leaf of the
    observability import graph."""
    try:
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        if goodput.ledger is None:
            return None
        # From the dump on, the stalled seconds accrue to `wedged`.
        goodput.mark_stalled(reason)
        return goodput.snapshot()
    except Exception:  # pragma: no cover - diagnostics must not throw
        return None


def record_sync_mark(name, group, seq):
    """One barrier-exit sync mark: feeds the flight recorder (cross-rank
    clock alignment for trace_fuse) and the skew gauges. All ranks of the
    group leave the barrier within network jitter of each other, so
    comparing ``smp_sync_last_unix_seconds`` for the same
    ``smp_sync_seq`` across per-rank telemetry dumps measures per-rank
    wall-clock skew (+ exit jitter) without any extra collective."""
    fr = _flight()
    fr.record_sync(name, group, seq)
    telemetry.counter(
        "smp_sync_marks_total", "barrier sync marks recorded"
    ).labels(group=group).inc()
    telemetry.gauge(
        "smp_sync_seq", "per-group barrier ordinal of the last sync mark"
    ).labels(group=group).set(seq)
    telemetry.gauge(
        "smp_sync_last_unix_seconds",
        "wall-clock time of the last barrier exit (cross-rank skew probe)",
    ).labels(group=group).set(time.time())


def record_comm(op, group, nbytes, group_size):
    """One host-collective record: op count, payload bytes, group size.

    The TPU analogue of the reference's hand-counted comm volume
    (``backend/utils.py:134-149``): device-side collective traffic is
    compiled into the step program (accounted via XLA cost_analysis in
    ``utils/metrics.py``); what remains observable per-op at runtime is the
    host control plane, counted here.
    """
    g = getattr(group, "name", None) or str(group)
    # Every host collective also lands in the flight-recorder ring. Only
    # SYMMETRIC ops — ones every group member executes in the same order —
    # consume the per-group sequence number (that is what makes cross-rank
    # ring diffs meaningful); p2p send/recv/poll streams are rank-local
    # and are recorded unsequenced.
    _flight().record_collective(
        op, g, nbytes, group_size,
        sequenced=op in ("broadcast", "allgather", "barrier"),
    )
    telemetry.counter(
        "smp_comm_ops_total", "host collective operations"
    ).labels(op=op, group=g).inc()
    if nbytes:
        telemetry.counter(
            "smp_comm_bytes_total", "host collective payload bytes"
        ).labels(op=op, group=g).inc(int(nbytes))
    telemetry.gauge(
        "smp_comm_group_size", "process count of the last collective per op/group"
    ).labels(op=op, group=g).set(int(group_size))


def record_pipeline_occupancy(schedule, num_stages, num_microbatches,
                              busy_slots, total_slots, virtual=1,
                              passes=2, pass_ticks=None):
    """Record measured schedule occupancy -> bubble fraction gauges.

    ``busy_slots``/``total_slots`` count (tick, stage[, sub-step]) slots of
    the static schedule actually baked into the compiled program; the
    theoretical bound is ``(pp-1)/(mb+pp-1)`` for the plain schedules and
    the interleaved ``(pp-1)/(v*mb+pp-1)`` when ``virtual > 1`` (each rank
    owns ``v`` model chunks, so a schedule slot is a chunk sub-step and
    the fill/drain ramps shrink by ``v``). Zero-bubble schedules pass
    ``passes=3`` (forward / input-grad / weight-grad sub-steps): a slot
    is then a (chunk, microbatch, pass) unit and the bound drops to
    ``2*(pp-1)/(3*v*mb + 2*(pp-1))`` — the deferred weight-grad pass
    packs gapless, leaving only the F and B ramps as bubble. Gauges (not
    counters): executors trace more than once per compile and gauge sets
    are idempotent.

    ``pass_ticks`` (optional): {pass name: executed tick-span length}.
    Emitted as ``smp_pipeline_phase_ticks{phase="executed", pass=...}``
    — the per-pass denominators behind ``measured``, so the
    measured-vs-theoretical gate can audit a 3-pass schedule's occupancy
    accounting the same way the interleaved phase split is audited.
    """
    measured = 1.0 - (busy_slots / total_slots) if total_slots else 0.0
    if passes >= 3:
        denom = 3 * virtual * num_microbatches + 2 * (num_stages - 1)
        theoretical = 2 * (num_stages - 1) / denom if denom > 0 else 0.0
    else:
        denom = virtual * num_microbatches + num_stages - 1
        theoretical = (num_stages - 1) / denom if denom > 0 else 0.0
    lab = dict(schedule=schedule)
    if pass_ticks:
        phase_gauge = telemetry.gauge(
            "smp_pipeline_phase_ticks",
            "ticks per schedule phase (warmup/steady/cooldown) or per "
            "executed pass span (pass label)",
        )
        for pass_name, ticks in pass_ticks.items():
            phase_gauge.labels(
                phase="executed", schedule=schedule, **{"pass": pass_name}
            ).set(ticks)
    telemetry.gauge(
        "smp_pipeline_bubble_fraction",
        "measured idle fraction of pipeline schedule slots",
    ).labels(**lab).set(measured)
    telemetry.gauge(
        "smp_pipeline_bubble_fraction_theoretical",
        "schedule bound (pp-1)/(v*mb+pp-1); v=1 is the fill-drain bound",
    ).labels(**lab).set(theoretical)
    telemetry.gauge(
        "smp_pipeline_virtual_stages",
        "virtual pipeline chunks per stage (1 = no interleaving)",
    ).labels(**lab).set(virtual)
    telemetry.gauge(
        "smp_pipeline_schedule_slots", "slots in the static schedule"
    ).labels(state="busy", **lab).set(busy_slots)
    telemetry.gauge(
        "smp_pipeline_schedule_slots", "slots in the static schedule"
    ).labels(state="total", **lab).set(total_slots)
    telemetry.gauge(
        "smp_pipeline_stages", "pipeline stage count"
    ).labels(**lab).set(num_stages)
    telemetry.gauge(
        "smp_pipeline_microbatches", "microbatch count"
    ).labels(**lab).set(num_microbatches)
    return measured


def record_loss_scale(event, scale):
    """One fp16 loss-scale event ("overflow" | "growth" | "static_overflow"):
    counter + current-scale gauge + a flight-recorder health event — the
    scaler's backoff history becomes part of every post-mortem."""
    telemetry.counter(
        "smp_loss_scale_events_total", "fp16 loss-scale events by kind"
    ).labels(event=event).inc()
    telemetry.gauge(
        "smp_loss_scale", "current fp16 loss scale"
    ).set(float(scale))
    _flight().record_health("loss_scale", event, value=float(scale))


def record_update_stats(grad_norm, param_norm, update_norm):
    """Optimizer-step norm gauges (health modes only; see utils/health.py).
    ``update_ratio`` is ||new - old|| / ||new|| — the classic silent-LR
    pathology signal (~1e-3 healthy; ~1 = divergence, ~0 = frozen)."""
    if grad_norm is not None:
        telemetry.gauge(
            "smp_grad_norm", "global L2 norm of the last consumed gradients"
        ).set(grad_norm)
    telemetry.gauge(
        "smp_param_norm", "global L2 norm of the parameters after the update"
    ).set(param_norm)
    if update_norm is not None:
        telemetry.gauge(
            "smp_update_norm", "global L2 norm of the last parameter update"
        ).set(update_norm)
        telemetry.gauge(
            "smp_update_ratio",
            "update-to-parameter norm ratio of the last optimizer step",
        ).set(update_norm / (param_norm + 1e-12))


def record_health_check(step, tags):
    """One decoded health word: per-tag gauges + the checks counter."""
    telemetry.counter(
        "smp_health_checks_total", "health words decoded"
    ).inc()
    telemetry.gauge(
        "smp_health_last_checked_step", "most recent step whose word was read"
    ).set(step)
    for name, d in tags.items():
        telemetry.gauge(
            "smp_health_bad_count", "non-finite elements per sentinel tag"
        ).labels(tag=name).set(d["bad"])
        telemetry.gauge(
            "smp_health_absmax", "largest finite magnitude per sentinel tag"
        ).labels(tag=name).set(d["absmax"])
        telemetry.gauge(
            "smp_health_first_microbatch",
            "first microbatch with a non-finite value (-1 = none)",
        ).labels(tag=name).set(d["microbatch"])


def record_health_trip(tag, step, bad, absmax, microbatch):
    telemetry.counter(
        "smp_health_trips_total", "tripped sentinel tags"
    ).labels(tag=tag).inc()
    telemetry.gauge(
        "smp_health_last_trip_step", "step of the most recent sentinel trip"
    ).set(step)
    _flight().record_health(
        "trip", tag, step=step, value=bad, microbatch=microbatch
    )


def record_health_fault(layer, microbatch, tag, step):
    """Bisection attribution: the first non-finite value's layer."""
    telemetry.counter(
        "smp_health_fault_total",
        "bisection fault attributions (layer of the first non-finite value)",
    ).labels(layer=str(layer), microbatch=str(microbatch), tag=tag).inc()
    _flight().record_health(
        "fault", str(layer), step=step, microbatch=microbatch
    )


def record_oom(name):
    telemetry.counter(
        "smp_oom_total", "RESOURCE_EXHAUSTED failures with a post-mortem dump"
    ).labels(step=str(name)).inc()
    _flight().record_health("oom", str(name))


def record_preemption(event, step=-1, detail=""):
    """Preemption lifecycle (resilience/preemption.py): ``requested`` when
    the signal/sentinel fires, ``rendezvous``/``saved``/``failed`` along
    the emergency-checkpoint path."""
    telemetry.counter(
        "smp_preemption_total", "preemption lifecycle events"
    ).labels(event=event).inc()
    _flight().record_preempt(event, step=step, detail=detail)


def record_chaos(fault, detail=""):
    """One injected fault (resilience/chaos.py) — counted and ring-recorded
    so a post-mortem always shows which failures were synthetic."""
    telemetry.counter(
        "smp_chaos_injected_total", "chaos faults injected"
    ).labels(fault=fault).inc()
    _flight().record_chaos(fault, detail)


def record_failure_detected(kind, peer, detail=""):
    """One failure-detector classification (resilience/supervisor.py):
    ``kind`` is dead / wedged / preempted (or flap_cleared when a peer
    marked dead resumed beating before recovery began)."""
    telemetry.counter(
        "smp_failures_detected_total",
        "peer failures classified by the heartbeat detector",
    ).labels(kind=kind).inc()
    _flight().record_supervisor(f"detect_{kind}", peer=peer, detail=detail)


def record_recovery(mttr_s, phases=None, survivors=-1):
    """One completed in-job recovery (resilience/supervisor.py):
    ``mttr_s`` spans detection to the first trained step in the shrunken
    world; ``phases`` optionally breaks it down (detect / rendezvous /
    reshard_load / first_step seconds)."""
    telemetry.counter(
        "smp_recoveries_total", "completed in-job shrink-to-survivors recoveries"
    ).inc()
    telemetry.gauge(
        "smp_recovery_seconds",
        "MTTR of the last recovery (detection -> first step trained)",
    ).set(float(mttr_s))
    if survivors >= 0:
        telemetry.gauge(
            "smp_recovery_survivors", "world size after the last recovery"
        ).set(int(survivors))
    for phase, secs in (phases or {}).items():
        telemetry.gauge(
            "smp_recovery_phase_seconds",
            "per-phase breakdown of the last recovery",
        ).labels(phase=phase).set(float(secs))
    _flight().record_supervisor(
        "recovery_done",
        detail=f"mttr={mttr_s:.3f}s " + " ".join(
            f"{k}={v:.3f}" for k, v in (phases or {}).items()
        ),
    )


def record_exec_cache(result, seconds=None):
    """One persistent executable-cache lookup outcome
    (utils/exec_cache.py): ``result`` is hit / miss / reject_fingerprint
    / reject_version / corrupt. Hits also record the deserialize+verify
    wall time (the "warm compile" the availability story buys)."""
    telemetry.counter(
        "smp_exec_cache_total",
        "persistent executable-cache lookups by outcome",
    ).labels(result=result).inc()
    if result == "hit" and seconds is not None:
        telemetry.gauge(
            "smp_exec_cache_hit_seconds",
            "deserialize+verify wall time of the last executable-cache hit",
        ).set(float(seconds))


def record_elastic_resume(n_layout, n_soft, detail=""):
    """One elastic (topology-mismatched) checkpoint resume
    (resilience/elastic.py): counts of layout-relevant and soft config
    mismatches that were downgraded from fatal to a reshard."""
    telemetry.counter(
        "smp_elastic_resume_total", "elastic reshard-on-resume events"
    ).inc()
    telemetry.gauge(
        "smp_elastic_resume_mismatches",
        "config mismatches downgraded by the last elastic resume",
    ).labels(kind="layout").set(n_layout)
    telemetry.gauge(
        "smp_elastic_resume_mismatches",
        "config mismatches downgraded by the last elastic resume",
    ).labels(kind="soft").set(n_soft)
    _flight().record_preempt("elastic_resume", detail=detail)


def record_zero3_xray(name, zero_block):
    """Publish the X-ray's ZeRO-3 traffic report (utils/hlo_audit.py
    ``zero_report``) as ``smp_zero3_*`` gauges: per-device rdp-axis
    parameter-gather / gradient-scatter volume of the compiled program,
    the fraction issued inside loop bodies (overlappable with compute),
    and the double-buffered transfer-register evidence. Complements the
    build-time gauges the grad engine sets (``smp_zero3_buckets`` /
    ``smp_zero3_bucket_bytes`` / ``smp_zero3_sharded_params``)."""
    lab = dict(step=name)
    for key, help_text in (
        ("gather_ops", "rdp-axis parameter all-gather instructions in the "
         "compiled zero3 program"),
        ("gather_bytes", "per-device rdp all-gather result bytes in the "
         "compiled zero3 program"),
        ("scatter_ops", "rdp-axis gradient reduce-scatter instructions in "
         "the compiled zero3 program"),
        ("scatter_bytes", "per-device rdp reduce-scatter result bytes in "
         "the compiled zero3 program"),
        ("overlap_fraction", "fraction of zero3 gather/scatter bytes "
         "issued inside loop bodies (overlappable with the loop's "
         "compute)"),
        ("prefetch_registers", "double-buffered transfer-register gathers "
         "(next layer's gather parked in the scan carry) detected in the "
         "compiled zero3 program"),
    ):
        val = zero_block.get(key)
        if val is not None:
            telemetry.gauge(f"smp_zero3_{key}", help_text).labels(
                **lab
            ).set(float(val))


def record_tp_overlap_xray(name, block):
    """Publish the X-ray's overlapped-tensor-parallelism report
    (utils/hlo_audit.py ``tp_overlap_report``) as ``smp_tp_overlap_*``
    gauges: the decomposed ring-hop census attributed to the tp axis,
    the parked-hop double-buffering evidence, and the residual
    synchronous tp collectives the ring should have eliminated."""
    lab = dict(step=name)
    for key, help_text in (
        ("ring_permute_ops", "tp-axis collective-permute (ring hop) "
         "instructions in the compiled tp_overlap program"),
        ("ring_permute_bytes", "per-device tp-axis collective-permute "
         "result bytes (overlapped ring-hop traffic) in the compiled "
         "tp_overlap program"),
        ("parked_hops", "ring hops parked in a loop carry (consumed only "
         "by the next iteration's partial matmul) — the double-buffering "
         "evidence"),
        ("tp_allgather_ops", "residual synchronous tp-axis all-gather "
         "instructions (0 on a clean overlapped path)"),
        ("tp_reduce_scatter_ops", "residual synchronous tp-axis "
         "reduce-scatter instructions"),
        ("tp_allreduce_ops", "residual synchronous tp-axis all-reduce "
         "instructions"),
    ):
        val = block.get(key)
        if val is not None:
            telemetry.gauge(f"smp_tp_overlap_{key}", help_text).labels(
                **lab
            ).set(float(val))
    ev = block.get("overlap_evidence")
    if ev is not None:
        telemetry.gauge(
            "smp_tp_overlap_evidence",
            "1 when the structural overlap proof holds (parked ring hops "
            "present, zero residual tp all-gathers)",
        ).labels(**lab).set(1.0 if ev else 0.0)


def record_fused_kernel_dispatch(kernel, path):
    """One fused-kernel dispatch decision at trace time (``qkv`` /
    ``bias_gelu``; path ``pallas`` or ``fallback``) — the hit counters
    the tp-overlap report section renders. Trace-time counts: one per
    compiled call site, not per executed step."""
    telemetry.counter(
        "smp_fused_kernel_dispatch_total",
        "fused-kernel dispatch decisions at trace time by kernel and "
        "chosen path",
    ).labels(kernel=kernel, path=path).inc()


def record_serve_request(event, n=1):
    """One serving-request lifecycle event (serving/engine.py):
    ``admitted`` / ``finished`` / ``readmitted`` (failover re-admission of
    a dead replica's in-flight request) / ``deadline_miss``."""
    telemetry.counter(
        "smp_serve_requests_total", "serving requests by lifecycle event"
    ).labels(event=event).inc(n)


def record_serve_tokens(kind, n):
    """Serving token throughput counter: ``kind`` is prompt (prefilled)
    or generated (sampled)."""
    if n:
        telemetry.counter(
            "smp_serve_tokens_total", "serving tokens by kind"
        ).labels(kind=kind).inc(int(n))


_SERVE_LATENCY_HELP = {
    "ttft": "time to first token (arrival -> first sampled token)",
    "itl": "inter-token latency of decode streams",
    "queue_wait": "queue wait (arrival -> decode-slot admission)",
    "prefill": "prompt prefill wall (admission -> first token sampled)",
    "decode_step": "batched decode-step dispatch wall",
}


def record_serve_latency(kind, seconds):
    """One serving latency sample, ``kind`` in SERVE_LATENCY_KINDS.

    Feeds the streaming log-bucketed histogram
    ``smp_serve_latency_seconds{kind=...}`` (fixed memory, mergeable
    across ranks — ``scripts/telemetry_report.py`` sums bucket counts
    element-wise because every rank uses the same LATENCY_BUCKETS tuple)
    and derives the per-kind gauge family
    ``smp_serve_<kind>_seconds{stat=last|mean|p50|p90|p99}``. The
    ``last``/``mean`` stats keep the pre-histogram names and meanings
    (mean is the histogram's lifetime sum/count), so existing dashboards
    and the PR-14 serving tests keep reading the same series."""
    v = float(seconds)
    child = telemetry.histogram(
        "smp_serve_latency_seconds",
        "serving latency distributions by kind (the log-bucketed "
        "streaming histogram behind the percentile gauges)",
        buckets=LATENCY_BUCKETS,
    ).labels(kind=kind)
    child.observe(v)
    snap = child._snapshot()
    g = telemetry.gauge(
        f"smp_serve_{kind}_seconds",
        _SERVE_LATENCY_HELP.get(kind, "serving latency"),
    )
    g.labels(stat="last").set(v)
    g.labels(stat="mean").set(snap["sum"] / max(snap["count"], 1))
    for stat, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
        est = quantile_from_counts(snap["buckets"], snap["counts"], q)
        if est is not None:
            g.labels(stat=stat).set(est)


def serve_latency_summary(kind, qs=(0.5, 0.9, 0.99)):
    """``{"count", "mean_s", "quantiles_s": {q: seconds}}`` of one
    serving latency distribution, or None before its first sample
    (bench.py stamps the serve probe's percentile columns from this)."""
    with telemetry._lock:
        fam = telemetry._families.get("smp_serve_latency_seconds")
    if fam is None:
        return None
    snap = fam.labels(kind=kind)._snapshot()
    if not snap["count"]:
        return None
    return {
        "count": snap["count"],
        "mean_s": snap["sum"] / snap["count"],
        "quantiles_s": {
            q: quantile_from_counts(snap["buckets"], snap["counts"], q)
            for q in qs
        },
    }


def record_step_time(seconds):
    """One training-step wall-time sample into the log-bucketed step-time
    histogram ``smp_step_time_seconds`` plus p50/p90/p99 gauges — the
    training-path counterpart of the serving latency distributions (a
    p99 step blowup is invisible in the dispatch-seconds mean)."""
    v = float(seconds)
    child = telemetry.histogram(
        "smp_step_time_seconds",
        "per-step dispatch wall-time distribution (log-bucketed)",
        buckets=LATENCY_BUCKETS,
    ).labels()
    child.observe(v)
    snap = child._snapshot()
    g = telemetry.gauge(
        "smp_step_time_quantile_seconds",
        "step wall-time percentiles from the streaming histogram",
    )
    for stat, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
        est = quantile_from_counts(snap["buckets"], snap["counts"], q)
        if est is not None:
            g.labels(stat=stat).set(est)


def record_serve_trace(event, rid, trace=None, slot=-1, pos=-1, detail=""):
    """One per-request serving span edge (``queued`` / ``admitted`` /
    ``readmitted`` / ``prefill_chunk`` / ``first_token`` / ``finished``)
    into the flight-recorder ring. Host-side timestamps only — recording
    costs one perf_counter read and a deque append, and a disabled ring
    (``SMP_FLIGHT_RECORDER_SIZE=0``) short-circuits to an attribute
    test. ``scripts/trace_fuse.py`` pairs the edges into one Perfetto
    span lane per decode slot; the trace id rides the failover mirror
    log, so a re-admitted request continues its original trace on the
    surviving replica."""
    _flight().record_serve(
        event, rid, trace=trace, slot=slot, pos=pos, detail=detail
    )


def record_serve_occupancy(queue_depth, active_slots, total_slots,
                           kv_used, kv_free, kv_reserved, kv_total,
                           block_bytes=None):
    """Continuous-batching occupancy gauges: request queue depth, decode
    slots in use, and KV-pool block accounting (used / free / promised-
    but-unallocated reservations / total). ``block_bytes`` (bytes per
    pool block AT THE POOL DTYPE, scale sidecars included) additionally
    publishes the block counts as ``smp_serve_kv_bytes`` — the gauge
    that makes the int8-KV halving claim checkable against the bf16
    pool rather than inferred from dtype names."""
    telemetry.gauge(
        "smp_serve_queue_depth", "requests waiting for a decode slot"
    ).set(int(queue_depth))
    g_slots = telemetry.gauge(
        "smp_serve_slots", "decode slots by state"
    )
    g_slots.labels(state="active").set(int(active_slots))
    g_slots.labels(state="total").set(int(total_slots))
    g_kv = telemetry.gauge(
        "smp_serve_kv_blocks", "paged KV-pool blocks by state"
    )
    g_kv.labels(state="used").set(int(kv_used))
    g_kv.labels(state="free").set(int(kv_free))
    g_kv.labels(state="reserved").set(int(kv_reserved))
    g_kv.labels(state="total").set(int(kv_total))
    if block_bytes is not None:
        g_b = telemetry.gauge(
            "smp_serve_kv_bytes",
            "paged KV-pool bytes by state (blocks x bytes per block at "
            "the pool dtype, including quantization-scale sidecars)",
        )
        g_b.labels(state="used").set(int(kv_used) * int(block_bytes))
        g_b.labels(state="free").set(int(kv_free) * int(block_bytes))
        g_b.labels(state="reserved").set(
            int(kv_reserved) * int(block_bytes)
        )
        g_b.labels(state="total").set(int(kv_total) * int(block_bytes))


def record_quant_state(slots, amax, scale):
    """Latest delayed-scaling statistics per quantization slot
    (``quant.QuantState.absorb`` after each fp8 step): the newest amax
    observation and the dequantization scale now in force."""
    g_a = telemetry.gauge(
        "smp_quant_amax",
        "latest per-slot amax observation of the fp8 delayed-scaling "
        "recipe",
    )
    g_s = telemetry.gauge(
        "smp_quant_scale",
        "per-slot fp8 dequantization scale currently in force",
    )
    for slot, a, s in zip(slots, amax, scale):
        g_a.labels(site=slot).set(float(a))
        g_s.labels(site=slot).set(float(s))


def record_quant_dispatch(site, path):
    """One low-precision dispatch decision at trace/setup time: a seam
    routed through fp8 (``path=fp8``), a knob canonicalized back to
    bf16 (``path=bf16_fallback``), the KV pool went int8
    (``site=kv_cache``), or decode weights were repacked
    (``site=decode_weights``). Counts are per-trace, not per-step —
    the signal is WHICH paths engaged, mirroring the fused-kernel
    dispatch counter."""
    telemetry.counter(
        "smp_quant_dispatch_total",
        "low-precision dispatch decisions by seam and path",
    ).labels(site=site, path=path).inc()


def record_serve_programs(n):
    telemetry.gauge(
        "smp_serve_programs",
        "compiled serving programs (the engine holds exactly two: "
        "prefill-chunk and decode-step)",
    ).set(int(n))


def record_scale_event(direction, seconds, phases=None, replicas=None):
    """One completed autoscale event (serving/controller.py): ``up``
    grew the replica set (rendezvous + exec-cache warm start),
    ``down`` shrank it through the drain protocol. ``phases`` breaks
    the wall down like a recovery MTTR (trigger / rendezvous /
    warm_start / first_token for up; drain / reroute for down)."""
    telemetry.counter(
        "smp_autoscale_events_total",
        "completed autoscale events by direction",
    ).labels(direction=direction).inc()
    telemetry.gauge(
        "smp_autoscale_last_scale_seconds",
        "wall seconds of the last autoscale event (trigger -> serving)",
    ).set(float(seconds))
    for phase, secs in (phases or {}).items():
        telemetry.gauge(
            "smp_autoscale_phase_seconds",
            "per-phase breakdown of the last autoscale event",
        ).labels(phase=phase).set(float(secs))
    if replicas is not None:
        telemetry.gauge(
            "smp_controller_replicas",
            "live serving replicas the controller routes to",
        ).set(int(replicas))
    _flight().record_controller(
        f"scale_{direction}",
        detail=f"seconds={seconds:.3f} " + " ".join(
            f"{k}={v:.3f}" for k, v in (phases or {}).items()
        ),
    )


def record_controller_replicas(n):
    """Live replica-count gauge outside a scale event (controller
    construction, replica death absorbed by failover, shutdown)."""
    telemetry.gauge(
        "smp_controller_replicas",
        "live serving replicas the controller routes to",
    ).set(int(n))


def record_route(version, n=1):
    """One request dispatched by the front-door router
    (serving/router.py), labelled with the weights version of the
    replica it landed on (the blue/green traffic-split evidence)."""
    telemetry.counter(
        "smp_controller_routed_total",
        "requests dispatched by the router, by weights version",
    ).labels(version=str(version)).inc(n)


def record_drain_stragglers(n):
    """Queued-but-never-admitted requests handed back by a draining
    replica and re-routed elsewhere (zero dropped tokens: every
    straggler is re-admitted from its restartable record)."""
    if n:
        telemetry.counter(
            "smp_controller_drain_stragglers_total",
            "requests re-routed off draining replicas",
        ).inc(int(n))


def record_weight_update(seconds, version, fresh=0):
    """One live weight adoption (serving/engine.py ``adopt_params``):
    ``seconds`` is the full swap wall, ``fresh`` the number of fresh
    program compiles it caused — the zero-recompile contract holds
    when it stays 0 (exec-cache keys are weight-free)."""
    telemetry.gauge(
        "smp_weight_update_seconds",
        "wall seconds of the last live weight adoption (zero-recompile "
        "contract: no compile_fresh events inside this window)",
    ).set(float(seconds))
    telemetry.counter(
        "smp_weight_updates_total", "live weight adoptions by outcome"
    ).labels(outcome="adopted" if not fresh else "recompiled").inc()
    telemetry.gauge(
        "smp_controller_weights_version",
        "weights version this engine currently serves",
    ).set(int(version))
    _flight().record_controller(
        "weight_update",
        detail=f"version={version} seconds={seconds:.3f} fresh={fresh}",
    )


def record_canary(verdict, version, detail=""):
    """A blue/green canary verdict (serving/controller.py):
    ``promoted`` (token parity held and the SLO-window comparison
    passed — every replica adopts), ``rolled_back`` (parity mismatch or
    SLO regression — traffic snaps back, the counter latches), or
    ``started``."""
    if verdict == "promoted":
        telemetry.counter(
            "smp_canary_promotions_total",
            "canary versions promoted to the full replica set",
        ).inc()
    elif verdict == "rolled_back":
        telemetry.counter(
            "smp_canary_rollback_total",
            "canary versions rolled back (token-parity mismatch or "
            "SLO regression)",
        ).inc()
    telemetry.gauge(
        "smp_canary_active",
        "1 while a canary version is taking split traffic",
    ).set(1 if verdict == "started" else 0)
    _flight().record_controller(
        f"canary_{verdict}", detail=f"version={version} {detail}".strip()
    )


def _atexit_dump():  # pragma: no cover - exercised via subprocess test
    try:
        # An empty registry must not clobber the dump smp.shutdown already
        # wrote (shutdown resets the registry after dumping).
        if telemetry._families:
            telemetry.dump()
    except Exception:
        pass


atexit.register(_atexit_dump)
