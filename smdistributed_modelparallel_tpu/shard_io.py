"""Sharded checkpoint IO: save/load addressable shards, never the tree.

Parity target: reference per-rank partial checkpoints
(``torch/checkpoint.py:124-165``): each rank writes only the parameters it
owns. Under SPMD "ownership" is the set of addressable shards; this module
writes one ``.npz`` per process containing the replica-0 shards it
addresses (each global element stored exactly once across all files), and
reassembles arrays on load with ``jax.make_array_from_callback`` — the
loading process materializes only the shards it needs, never the full
array.
"""

import glob
import json
import os

import numpy as np

import jax

from smdistributed_modelparallel_tpu.module_manager import path_key
from smdistributed_modelparallel_tpu.utils.exceptions import SMPRuntimeError
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

_SEP = "|"


def _index_to_json(index, shape):
    """Tuple of slices -> [[start, stop], ...] (concrete bounds)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        out.append([int(start), int(stop)])
    return json.dumps(out)


def shard_payload(tree, dedupe_global=True):
    """This process's addressable shards of ``tree`` as a flat
    ``{"path|bounds": np.ndarray}`` dict (the ``local_state_dict``
    representation; also the npz file layout).

    ``dedupe_global=True`` (checkpoint files): only replica-0 shards, so
    each global element is stored exactly once ACROSS processes.
    ``dedupe_global=False`` (``local_state_dict``): the lowest-replica
    addressable shard per index, so every process's payload is complete
    for its addressable data even when replica 0 lives elsewhere.
    """
    payload = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = path_key(path)
        if not isinstance(leaf, jax.Array):
            payload[f"{key}{_SEP}full"] = np.asarray(leaf)
            continue
        if dedupe_global:
            chosen = [s for s in leaf.addressable_shards if s.replica_id == 0]
        else:
            by_index = {}
            for s in leaf.addressable_shards:
                k = _index_to_json(s.index, leaf.shape)
                if k not in by_index or s.replica_id < by_index[k].replica_id:
                    by_index[k] = s
            chosen = list(by_index.values())
        for shard in chosen:
            idx = _index_to_json(shard.index, leaf.shape)
            payload[f"{key}{_SEP}{idx}"] = np.asarray(shard.data)
    return payload


def is_shard_payload(flat_dict):
    """True when a flat state dict uses the shard-payload key format."""
    return bool(flat_dict) and all(_SEP in k for k in flat_dict)


def save_sharded(tree, directory, name):
    """Write this process's replica-0 addressable shards of ``tree`` to
    ``{directory}/{name}_shards_p{process_index}.npz``. Returns the path."""
    os.makedirs(directory, exist_ok=True)
    out = os.path.join(
        directory, f"{name}_shards_p{jax.process_index()}.npz"
    )
    np.savez(out, **shard_payload(tree))
    return out


class _CatalogBase:
    """Shared reassembly logic over a ``key -> [(src, npz_key, bounds)]``
    entry map; subclasses provide ``_read``."""

    def keys(self):
        return set(self.entries)

    def _index_entries(self, keyed_sources):
        # keyed_sources: iterable of (src_handle_index, iterable of npz_keys)
        self.entries = {}
        for fi, npz_keys in keyed_sources:
            for npz_key in npz_keys:
                key, _, idx = npz_key.rpartition(_SEP)
                bounds = None if idx == "full" else json.loads(idx)
                self.entries.setdefault(key, []).append((fi, npz_key, bounds))

    def assemble(self, key, index, shape, dtype):
        """Materialize the slice ``index`` of global array ``key`` from the
        stored pieces (only the overlapping pieces are read)."""
        if key not in self.entries:
            raise SMPRuntimeError(f"Checkpoint is missing parameter '{key}'.")
        want = []
        for sl, dim in zip(index, shape):
            start = 0 if sl.start is None else sl.start
            stop = dim if sl.stop is None else sl.stop
            want.append((int(start), int(stop)))
        if not want:  # scalar
            fi, npz_key, _ = self.entries[key][0]
            return np.asarray(self._read(fi, npz_key), dtype=dtype)
        out = np.empty([b - a for a, b in want], dtype=dtype)
        filled = 0
        for fi, npz_key, bounds in self.entries[key]:
            if bounds is None:
                bounds = [[0, d] for d in shape]
            # overlap of saved piece with wanted region
            inter = []
            for (wa, wb), (sa, sb) in zip(want, bounds):
                a, b = max(wa, sa), min(wb, sb)
                if a >= b:
                    inter = None
                    break
                inter.append((a, b))
            if inter is None:
                continue
            piece = self._read(fi, npz_key)
            src = tuple(
                slice(a - sa, b - sa)
                for (a, b), (sa, _) in zip(inter, bounds)
            )
            dst = tuple(
                slice(a - wa, b - wa)
                for (a, b), (wa, _) in zip(inter, want)
            )
            out[dst] = piece[src]
            filled += int(np.prod([b - a for a, b in inter]))
        total = int(np.prod([b - a for a, b in want]))
        if filled < total:
            raise SMPRuntimeError(
                f"Sharded checkpoint pieces for '{key}' do not cover the "
                f"requested region {want} ({filled}/{total} elements)."
            )
        return out

    def coverage(self):
        """Metadata-only coverage report: ``{key: (covered, total)}``
        element counts per logical array, computed from the stored bounds
        without decompressing any piece. Checkpoint payloads store each
        global element exactly once (replica-0 dedupe), so
        ``covered < total`` means an interior hole (a rank's file never
        landed / is absent from this filesystem) and ``covered > total``
        means overlapping pieces (mixed checkpoints in one directory).
        The global extent is inferred as the max stored stop per dim, so a
        missing TAIL (beyond every stored bound) is undetectable here and
        is instead caught at ``assemble`` time against the target shape.
        Full (unbounded) pieces trivially cover their array."""
        out = {}
        for key, entries in self.entries.items():
            if any(b is None for _, _, b in entries):
                out[key] = (1, 1)
                continue
            ndim = max(len(b) for _, _, b in entries)
            dims = [0] * ndim
            vol = 0
            for _, _, bounds in entries:
                for i, (_, stop) in enumerate(bounds):
                    dims[i] = max(dims[i], stop)
                v = 1
                for a, b in bounds:
                    v *= b - a
                vol += v
            total = 1
            for d in dims:
                total *= d
            out[key] = (vol, total)
        return out

    def verify_complete(self, what="checkpoint", expected_files=None):
        """Raise (before any deferred load is stashed) when coverage is
        wrong — resume-time is the moment to learn a peer's shard file is
        missing, not the first training step.

        Overlaps are as fatal as gaps: checkpoint payloads are disjoint by
        construction (replica-0 dedupe), so overlapping pieces mean mixed
        checkpoints in one directory — and because coverage is a volume
        SUM, an undetected overlap could exactly cancel a gap elsewhere,
        letting assembly fill that region with whichever save's bytes it
        read last.

        ``expected_files`` (the writer-process census saved in
        ``smp_config.pt``) closes the one hole bounds coverage has: a
        missing TAIL shard file shrinks the inferred global extent instead
        of showing a gap, so only the file count can prove it absent."""
        if expected_files is not None:
            nfiles = len(getattr(self, "paths", ()))
            if nfiles < expected_files:
                raise SMPRuntimeError(
                    f"{what}: found {nfiles} shard file(s) but the "
                    f"checkpoint was written by {expected_files} "
                    "process(es) — a peer's file is missing (never landed "
                    "on this filesystem, or lost). Bounds coverage cannot "
                    "see a missing tail shard, so the file census is "
                    "authoritative."
                )
        cov = self.coverage()
        bad = {k: c for k, c in cov.items() if c[0] != c[1]}
        # Duplicate bounds are overlap evidence even when the volume sum
        # balances (a duplicated piece can exactly cancel a gap in the
        # SAME key): two saves under the same sharding produce identical
        # bounds, which is the realistic mixed-checkpoint signature.
        dup = set()
        for key, entries in self.entries.items():
            seen = set()
            for _, _, bounds in entries:
                if bounds is None:
                    # 'full' pieces are replicated by design: shard_payload
                    # writes non-jax.Array leaves whole into EVERY
                    # process's file (no replica-0 dedupe on that branch),
                    # so N identical full entries are a healthy
                    # multiprocess checkpoint, not an overlap.
                    continue
                sig = tuple(map(tuple, bounds))
                if sig in seen:
                    dup.add(key)
                    break
                seen.add(sig)
        for k in dup:
            bad.setdefault(k, (cov[k][0] + 1, cov[k][1]))
        if bad:
            gaps = sorted(k for k, c in bad.items() if c[0] < c[1])
            overlaps = sorted(k for k, c in bad.items() if c[0] > c[1])
            parts = []
            if gaps:
                parts.append(
                    "missing pieces (a rank's shard file is absent or was "
                    "never written) for: " + ", ".join(
                        f"'{k}' ({cov[k][0]}/{cov[k][1]} elements)"
                        for k in gaps
                    )
                )
            if overlaps:
                parts.append(
                    "overlapping pieces (mixed checkpoints in one "
                    "directory?) for: " + ", ".join(
                        f"'{k}' ({cov[k][0]}/{cov[k][1]} elements)"
                        for k in overlaps
                    )
                )
            raise SMPRuntimeError(f"{what}: " + "; ".join(parts))

    def load_tree(self, target_tree, shardings):
        """Build jax.Arrays matching ``target_tree``'s structure/shapes,
        sharded per ``shardings``; each process reads only the pieces its
        addressable shards need. ``shardings`` must structurally match
        ``target_tree`` (None entries keep the stored value as-is)."""
        t_leaves, treedef = jax.tree_util.tree_flatten_with_path(target_tree)
        # flatten_up_to keeps None sharding entries aligned per leaf.
        s_leaves = treedef.flatten_up_to(shardings)
        out = []
        for (path, leaf), sharding in zip(t_leaves, s_leaves):
            key = path_key(path)
            shape = tuple(getattr(leaf, "shape", ()))
            dtype = getattr(leaf, "dtype", None)
            if sharding is None:
                full = tuple(slice(0, d) for d in shape)
                out.append(self.assemble(key, full, shape, dtype))
                continue

            def cb(index, _key=key, _shape=shape, _dtype=dtype):
                return self.assemble(_key, index, _shape, _dtype)

            out.append(
                jax.make_array_from_callback(shape, sharding, cb)
            )
        return jax.tree_util.tree_unflatten(treedef, out)

    def close(self):
        pass


class ShardCatalog(_CatalogBase):
    """Lazy view over all shard files of a checkpoint component.

    Files stay open as ``NpzFile`` handles; arrays are decompressed only
    when a loader asks for a piece overlapping its shard. ``close()``
    releases the file handles (loaders call it when done).
    """

    def __init__(self, directory, name):
        pattern = os.path.join(directory, f"{name}_shards_p*.npz")
        self.paths = sorted(glob.glob(pattern))
        if not self.paths:
            raise SMPRuntimeError(
                f"No sharded checkpoint files match {pattern}"
            )
        self._files = [np.load(p, allow_pickle=False) for p in self.paths]
        self._index_entries(
            (fi, f.files) for fi, f in enumerate(self._files)
        )

    def _read(self, fi, npz_key):
        return self._files[fi][npz_key]

    def close(self):
        for f in self._files:
            f.close()


class InMemoryCatalog(_CatalogBase):
    """Catalog over an in-memory shard payload (``shard_payload`` output /
    ``local_state_dict`` round-trips)."""

    def __init__(self, payload):
        self._payload = dict(payload)
        self._index_entries([(0, list(self._payload))])

    def _read(self, fi, npz_key):
        return self._payload[npz_key]
