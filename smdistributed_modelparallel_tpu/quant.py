"""Low-precision matmul + KV-cache quantization — ``smp.quant``.

TPU extension (no reference counterpart): the reference
(``smdistributed.modelparallel``) stops at an fp16 dynamic loss scaler
(``fp16/loss_scaler.py``); it has no low-precision matmul or KV path at
all. This module is one knob family with two halves:

**Training** — ``matmul_precision: fp8`` (env ``SMP_MATMUL_PRECISION``)
dispatches the framework's matmul seams (the tp ring's chunk matmuls,
the fused QKV Pallas kernel, the DistributedLinear/Transformer einsum
paths, the bias+GELU epilogue input, the attention score inputs)
through fp8: e4m3 forward operands, e5m2 gradients, with DELAYED
scaling — each quantization site carries an amax history whose running
max sets the next step's dequantization scale, exactly the recipe of
the Transformer-Engine/TE fp8 ladder. The per-site state
(``QuantState``) threads through the step like the fp16 loss scaler:
it enters the compiled program as an input pytree, per-microbatch amax
observations ride out of the microbatch scan as stacked outputs, and
the program returns the rolled history + refreshed scales, which the
runner absorbs back into ``state.quant_state`` (checkpointed beside
the loss scaler as ``quant_states.pt``; see ``checkpoint.py``).

**Serving** — ``SMP_KV_QUANT=int8`` stores the paged KV pool
(``nn/utils.PagedKVCache``) as int8 with per-block-per-head scales
(pool bytes ~ halved -> ~2x servable concurrency per chip),
dequantizing at the decode-attention gather; ``SMP_DECODE_WEIGHTS=int8``
adds weight-only int8 (per-output-channel scales, quantized ONCE at
``ServingEngine.adopt_params``/load) for the memory-bound decode
matmuls, with ``smp.generate`` running the numerics-identical
fake-quant path so the two decode stacks stay token-parity-checkable
against each other.

Canonicalization contract (the PR-12/15 discipline): every knob here
resolves through a canonical mode function (``matmul_precision_mode``,
``kv_quant_mode``, ``decode_weights_mode``); defaults contribute
NOTHING to step keys, exec-cache knob facts, serving program keys, or
X-ray fingerprints — default-knob programs stay byte-identical to
pre-knob builds. fp8 does not compose with pipeline parallelism or the
ZeRO-3 manual-gradient path yet; the mode canonicalizes to "bf16"
there with a one-time warning, so the key/fact story stays coherent.

CPU/interpret note: XLA:CPU upcasts f8 dot operands to f32 inside the
compiled program (the dots remain *fp8-origin*: their operands are
converts from f8 — the X-ray ``quant`` census counts both forms), so
CPU smoke runs prove plumbing + numerics parity only; the fp8 speed
claim is a TPU criterion (BENCH_NOTES Round 20).
"""

import functools
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

# ----------------------------------------------------------------------
# Knob resolution (canonical modes)
# ----------------------------------------------------------------------

_WARNED = set()


def _warn_once(key, msg, *args):
    if key in _WARNED:
        return
    _WARNED.add(key)
    logger.warning(msg, *args)


def matmul_precision_mode(cfg=None):
    """The effective training matmul precision: the config knob
    (``matmul_precision``, env ``SMP_MATMUL_PRECISION``), canonicalized
    to "bf16" whenever it cannot engage: pipeline parallelism (the
    pipelined executors own their own grad plumbing — the amax scan
    outputs have no seat there yet) and ZeRO-3 (the manual-grad vmap
    would trap the amax observations inside its trace). Keyed into the
    step cache / exec-cache knob facts in this canonical form so an
    idle knob never moves a key."""
    cfg = cfg if cfg is not None else state.cfg
    if cfg is None:
        return "bf16"
    mode = getattr(cfg, "matmul_precision", "bf16") or "bf16"
    if mode == "bf16":
        return "bf16"
    if getattr(cfg, "pipeline_parallel_degree", 1) > 1:
        _warn_once(
            ("pp", mode),
            "matmul_precision=%s requested with pipeline_parallel_degree "
            "> 1; fp8 does not compose with the pipelined executors yet "
            "— keeping bf16 matmuls.", mode,
        )
        return "bf16"
    if getattr(cfg, "sharded_params", "none") == "zero3":
        _warn_once(
            ("zero3", mode),
            "matmul_precision=%s requested with sharded_params=zero3; "
            "fp8 does not compose with the ZeRO-3 manual-gradient path "
            "yet — keeping bf16 matmuls.", mode,
        )
        return "bf16"
    return mode


def kv_quant_mode():
    """Serving paged-KV pool precision: ``SMP_KV_QUANT`` (default
    "none"; "int8" stores the pool int8 with per-block-per-head
    scales)."""
    v = os.environ.get("SMP_KV_QUANT", "none").strip().lower() or "none"
    if v in ("", "0", "none", "off", "bf16"):
        return "none"
    if v != "int8":
        raise ValueError(
            f"SMP_KV_QUANT={v!r}: expected 'int8' or unset/none."
        )
    return "int8"


def decode_weights_mode():
    """Serving/decode weight precision: ``SMP_DECODE_WEIGHTS`` (default
    "none"; "int8" = weight-only int8 with per-output-channel scales,
    quantized once at ``adopt_params``/load)."""
    v = os.environ.get("SMP_DECODE_WEIGHTS", "none").strip().lower() or "none"
    if v in ("", "0", "none", "off", "bf16"):
        return "none"
    if v != "int8":
        raise ValueError(
            f"SMP_DECODE_WEIGHTS={v!r}: expected 'int8' or unset/none."
        )
    return "int8"


def serving_key_suffix():
    """Serving-program cache-key components for the quant knobs.
    Defaults contribute NOTHING (byte-identical key tuples to pre-knob
    builds); a knob flip appends facts, so the flipped program is a
    verified miss, never a warm hit of the other pool layout."""
    suffix = ()
    if kv_quant_mode() != "none":
        suffix += (("kv_quant", kv_quant_mode()),)
    if decode_weights_mode() != "none":
        suffix += (("decode_weights", decode_weights_mode()),)
    return suffix


# ----------------------------------------------------------------------
# fp8 formats + the static site registry
# ----------------------------------------------------------------------

E4M3_MAX = 448.0
E5M2_MAX = 57344.0
AMAX_HISTORY = 16

# Static quantization slots: "<site>.<role>" with role x (fwd input)
# and w (fwd weight) — the delayed-scaling (stateful) seams. Backward
# cotangents carry NO slot: ``jax.custom_vjp`` traces its bwd rule into
# a jaxpr of its own, so a bwd-side amax observation could never escape
# into the step's state — the e5m2 cotangent instead uses just-in-time
# CURRENT scaling (``amax(g) / E5M2_MAX`` computed where g exists),
# which is stateless and at least as tight as a delayed estimate. The
# registry is a FIXED tuple so the QuantState pytree structure is known
# before the first trace (it is a program input); instances of one seam
# family share a slot — the ``nn.scan`` layer stack shares one trace
# anyway, and the shared running max is a conservative
# (never-overflowing) scale for every member.
SITE_SLOTS = (
    "qkv.x", "qkv.w",
    "attn_proj.x", "attn_proj.w",
    "mlp_fc.x", "mlp_fc.w",
    "mlp_proj.x", "mlp_proj.w",
    "linear_col.x", "linear_col.w",
    "linear_row.x", "linear_row.w",
    "ring_ag.x", "ring_ag.w",
    "ring_rs.x", "ring_rs.w",
    "gelu_in.x",
    "attn_q.x", "attn_k.x",
)
_SLOT_INDEX = {s: i for i, s in enumerate(SITE_SLOTS)}


def _slot_fmax(slot):
    return E5M2_MAX if slot.endswith(".g") else E4M3_MAX


def _slot_dtype(slot):
    import jax.numpy as jnp

    return jnp.float8_e5m2 if slot.endswith(".g") else jnp.float8_e4m3fn


# ----------------------------------------------------------------------
# QuantState — the host-side delayed-scaling state (the loss-scaler
# pattern: lives on smp.state, updated from each step's outputs,
# checkpointed as a plain state dict).
# ----------------------------------------------------------------------


class QuantState:
    """Per-slot amax history + dequantization scales.

    ``scale[i]`` is the DIVISOR applied before the f8 cast (and the
    multiplier at dequant): ``x8 = cast(clip(x / scale))``. Delayed
    scaling: scale derives from the running max of the previous
    ``AMAX_HISTORY`` steps' amax, ``max_amax / fmax`` — the current
    step quantizes with last step's statistics, so the whole update is
    one program with no mid-step host sync. Scales start at 1.0 (the
    TE convention) until a history entry lands."""

    def __init__(self):
        n = len(SITE_SLOTS)
        self.amax_history = np.zeros((n, AMAX_HISTORY), np.float32)
        self.scale = np.ones((n,), np.float32)

    def arrays(self):
        import jax.numpy as jnp

        return {
            "amax_history": jnp.asarray(self.amax_history),
            "scale": jnp.asarray(self.scale),
        }

    def absorb(self, out):
        """Install a step program's rolled state and publish the
        telemetry gauges (``smp_quant_amax`` / ``smp_quant_scale``,
        latest per site)."""
        from smdistributed_modelparallel_tpu.utils.telemetry import (
            record_quant_state,
        )

        self.amax_history = np.asarray(out["amax_history"], np.float32)
        self.scale = np.asarray(out["scale"], np.float32)
        record_quant_state(
            SITE_SLOTS, self.amax_history[:, 0], self.scale
        )

    def state_dict(self):
        return {
            "amax_history": np.asarray(self.amax_history, np.float32),
            "scale": np.asarray(self.scale, np.float32),
            "slots": list(SITE_SLOTS),
        }

    def load_state_dict(self, sd):
        """Slot-name keyed restore: resuming under a build with a
        different slot registry keeps the intersection (new slots keep
        their fresh-start 1.0 scale)."""
        slots = list(sd.get("slots", ()))
        hist = np.asarray(sd["amax_history"], np.float32)
        scale = np.asarray(sd["scale"], np.float32)
        for j, name in enumerate(slots):
            i = _SLOT_INDEX.get(name)
            if i is None:
                continue
            h = min(hist.shape[1], AMAX_HISTORY)
            self.amax_history[i, :h] = hist[j, :h]
            self.scale[i] = scale[j]


def ensure_state():
    """``state.quant_state``, created on first use (fp8 mode only)."""
    qs = getattr(state, "quant_state", None)
    if qs is None:
        qs = QuantState()
        state.quant_state = qs
    return qs


# ----------------------------------------------------------------------
# Trace-time context: installed by the step runner around the traced
# program (the health-collector pattern). Seams read their slot's
# scale from the context and record amax observations; the microbatch
# scan body drains the observations into stacked scan outputs, and the
# runner folds them into the rolled state the program returns.
# ----------------------------------------------------------------------

_TRACE = threading.local()


class _QuantTrace:
    def __init__(self, arrays):
        self.arrays = arrays
        self.pending = {}       # slot -> amax tracer (current trace level)
        self.last_drain = ()    # slot order of the most recent scan_drain

    def scale_for(self, slot):
        return self.arrays["scale"][_SLOT_INDEX[slot]]

    def record(self, slot, amax):
        import jax.numpy as jnp

        tgt = self.pending
        if slot in tgt:
            try:
                tgt[slot] = jnp.maximum(tgt[slot], amax)
            except Exception:
                # The stored value is a dead tracer from an abandoned or
                # completed sub-trace (lax.scan traces bodies more than
                # once; a differentiated nn.scan re-traces its body for
                # the backward pass). The live re-trace re-records, so
                # replacing is exact.
                tgt[slot] = amax
        else:
            tgt[slot] = amax


class step_trace:
    """Context manager installing the quant trace for one program
    trace. ``arrays=None`` (bf16 mode) installs nothing — the traced
    program is byte-identical to a build without this module."""

    def __init__(self, arrays):
        self.arrays = arrays
        self.ctx = None

    def __enter__(self):
        if self.arrays is not None:
            self.ctx = _QuantTrace(self.arrays)
            _TRACE.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _TRACE.ctx = None
        return False


def _ctx():
    return getattr(_TRACE, "ctx", None)


def fp8_trace_active():
    """Whether the CURRENT trace should dispatch fp8 matmuls: a quant
    trace context is installed (only the step runner installs one, and
    only under ``matmul_precision: fp8``). Serving / generate / eager
    forwards see False and keep the bf16 paths."""
    return _ctx() is not None


def _drain_live(ctx):
    """Pop the pending entries whose tracers are still usable at the
    current trace level, sorted by slot name. Entries recorded inside a
    completed sub-trace (e.g. the backward rules the layer scan's
    transpose re-traces in its own body) are dead here and silently
    dropped — their slots simply see no observation this step, which
    delayed scaling tolerates by design (the scale is a running max
    over AMAX_HISTORY steps)."""
    import jax.numpy as jnp

    live = []
    for slot in sorted(ctx.pending):
        val = ctx.pending[slot]
        try:
            # Any op on a leaked tracer raises UnexpectedTracerError;
            # on a live one it's a no-op the compiler folds away.
            val = jnp.maximum(val, val)
        except Exception:
            continue
        live.append((slot, val))
    ctx.pending.clear()
    return live


def scan_drain():
    """Drain the amax observations recorded during the current scan
    body's trace, as a tuple ordered by sorted slot name — the scan
    body returns it as extra stacked outputs (ys). () when inactive or
    nothing recorded. Each drain fixes its own slot order
    (``last_drain``): the layer scan inside the microbatch scan drains
    a different slot set than the microbatch body itself."""
    ctx = _ctx()
    if ctx is None or not ctx.pending:
        if ctx is not None:
            ctx.last_drain = ()
        return ()
    live = _drain_live(ctx)
    ctx.last_drain = tuple(s for s, _ in live)
    return tuple(v for _, v in live)


def scan_was_drained():
    """Whether the most recent ``scan_drain`` (the just-completed
    scan's body trace) shipped any observations — the unpack flag for
    that scan's wrapped ys. Consume with ``absorb_stacked`` before any
    further drain runs."""
    ctx = _ctx()
    return ctx is not None and bool(ctx.last_drain)


def absorb_stacked(stacked):
    """Fold a completed scan's stacked amax outputs ([length] leading
    axis each, ordered like the body's ``scan_drain``) back into the
    CURRENT trace level's pending observations (max over the scanned
    axis). Inside a nested scan this re-arms the enclosing body's own
    drain; at the top level the records wait for ``finalize``. Clears
    the drain marker — each drain is consumed exactly once."""
    import jax.numpy as jnp

    ctx = _ctx()
    if ctx is None or not stacked:
        return
    slots, ctx.last_drain = ctx.last_drain, ()
    for slot, arr in zip(slots, stacked):
        ctx.record(slot, jnp.max(arr))


def finalize(arrays):
    """The program-output state: roll each observed slot's history by
    one (newest at column 0) and refresh every scale from its
    history's running max — ``max_amax / fmax`` once any history entry
    landed, 1.0 before (the fresh-start convention). Unobserved slots
    roll nothing (an eval-only program leaves the grad slots' history
    untouched). Consumes whatever reached the top-level pending set —
    scan-absorbed maxima plus any seam traced outside the scans."""
    import jax.numpy as jnp

    ctx = _ctx()
    hist = arrays["amax_history"]
    observed = dict(_drain_live(ctx)) if ctx is not None else {}
    if observed:
        rows = []
        for i, slot in enumerate(SITE_SLOTS):
            if slot in observed:
                rows.append(
                    jnp.concatenate(
                        [observed[slot][None].astype(jnp.float32),
                         hist[i, :-1]]
                    )
                )
            else:
                rows.append(hist[i])
        hist = jnp.stack(rows)
    fmax = jnp.asarray(
        [_slot_fmax(s) for s in SITE_SLOTS], jnp.float32
    )
    running = jnp.max(hist, axis=1)
    scale = jnp.where(running > 0.0, running / fmax, 1.0)
    return {"amax_history": hist, "scale": scale}


# ----------------------------------------------------------------------
# The fp8 ops (delayed-scaling quantize + f8-operand dots)
# ----------------------------------------------------------------------


def _record_amax(x, slot):
    """Record this step's amax observation for ``slot`` — MUST run in
    the caller's trace, never inside a ``custom_vjp`` fwd/bwd rule
    (those trace into jaxprs of their own, and a tracer recorded there
    is dead the moment the rule's trace closes)."""
    import jax.numpy as jnp

    _ctx().record(slot, jnp.max(jnp.abs(x)).astype(jnp.float32))


def _cast_f8(x, slot):
    """(x8, scale): clip/scale ``x`` into the slot's f8 format with the
    delayed scale. Pure — safe inside custom_vjp rules; the caller-side
    wrapper records the amax separately."""
    import jax.numpy as jnp

    d = _ctx().scale_for(slot)
    fmax = _slot_fmax(slot)
    x8 = jnp.clip(
        x.astype(jnp.float32) / d, -fmax, fmax
    ).astype(_slot_dtype(slot))
    return x8, d


def _cast_e5m2_current(g):
    """(g8, scale): e5m2 cotangent with just-in-time CURRENT scaling —
    ``amax(g) / E5M2_MAX`` computed from the tensor itself (stateless;
    see the SITE_SLOTS note on why bwd cannot feed delayed state)."""
    import jax.numpy as jnp

    ag = jnp.max(jnp.abs(g)).astype(jnp.float32)
    d = jnp.where(ag > 0.0, ag / E5M2_MAX, 1.0)
    g8 = jnp.clip(
        g.astype(jnp.float32) / d, -E5M2_MAX, E5M2_MAX
    ).astype(jnp.float8_e5m2)
    return g8, d


def _f8_dot(a8, b8, scale):
    """f32 <- f8 x f8 dot (contract a's last dim with b's first),
    dequantized by ``scale``. The dot's operands are genuine f8 arrays:
    TPU MXUs with native f8 consume them directly; XLA:CPU upcasts
    them (the X-ray census counts those as fp8-ORIGIN dots)."""
    import jax
    import jax.numpy as jnp

    y = jax.lax.dot_general(
        a8, b8, (((a8.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y * scale


def _pallas_f8_mm(x8, w8, interpret):
    """The fp8 rung of the Pallas matmul ladder: the fused-QKV kernel's
    tiling with f8 operand refs (``ops/pallas_qkv.matmul_bias_fp8``);
    dequant + bias stay in the XLA epilogue."""
    from smdistributed_modelparallel_tpu.ops.pallas_qkv import (
        matmul_bias_fp8,
    )

    return matmul_bias_fp8(x8, w8, interpret=interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fp8_mm2d(x2, w2, b, site, use_pallas, interpret):
    y, _ = _fp8_mm2d_fwd(x2, w2, b, site, use_pallas, interpret)
    return y


def _fp8_mm2d_fwd(x2, w2, b, site, use_pallas, interpret):
    x8, dx = _cast_f8(x2, site + ".x")
    w8, dw = _cast_f8(w2, site + ".w")
    if use_pallas:
        y = _pallas_f8_mm(x8, w8, interpret) * (dx * dw)
    else:
        y = _f8_dot(x8, w8, dx * dw)
    if b is not None:
        y = y + b.astype(y.dtype)
    y = y.astype(x2.dtype)
    # Zero-size dtype carriers: custom_vjp residuals must be JAX types,
    # and the saved operands are f8 — the originals' dtypes ride along
    # as empty arrays so the cotangents cast back correctly.
    res = (x8, dx, w8, dw,
           jnp.zeros((0,), x2.dtype), jnp.zeros((0,), w2.dtype),
           None if b is None else jnp.zeros((0,), b.dtype))
    return y, res


def _fp8_mm2d_bwd(site, use_pallas, interpret, res, g):
    x8, dx, w8, dw, x_dt, w_dt, b_dt = res
    g8, dg = _cast_e5m2_current(g)
    # e5m2 cotangent against the SAVED f8 operands (the fp8 residency
    # win: no bf16 copies of x/w survive the forward).
    dx2 = _f8_dot(g8, w8.T, dg * dw).astype(x_dt.dtype)
    dw2 = _f8_dot(x8.T, g8, dx * dg).astype(w_dt.dtype)
    db = None if b_dt is None else jnp.sum(g, axis=0).astype(b_dt.dtype)
    return dx2, dw2, db


_fp8_mm2d.defvjp(_fp8_mm2d_fwd, _fp8_mm2d_bwd)


def fp8_matmul(x, w, site, *, bias=None, n_contract=1, use_pallas=False,
               interpret=False):
    """``x @ w (+ bias)`` through the fp8 delayed-scaling path,
    contracting x's last ``n_contract`` dims with w's first
    ``n_contract`` dims (the einsum shapes of the transformer seams).
    Forward operands e4m3, backward cotangent e5m2; scales come from
    the step's ``QuantState`` and this call records the amax that
    feeds the next step's scales."""
    import numpy as _np

    lead = x.shape[:x.ndim - n_contract]
    k = int(_np.prod(x.shape[x.ndim - n_contract:], dtype=_np.int64))
    out_shape = w.shape[n_contract:]
    n = int(_np.prod(out_shape, dtype=_np.int64)) if out_shape else 1
    x2 = x.reshape(-1, k)
    w2 = w.reshape(k, n)
    b1 = None if bias is None else bias.reshape(n)
    # Amax observations happen HERE, in the caller's trace — the
    # custom_vjp rules below trace into their own jaxprs and anything
    # recorded there could never reach the step's quant outputs.
    _record_amax(x2, site + ".x")
    _record_amax(w2, site + ".w")
    y = _fp8_mm2d(x2, w2, b1, site, use_pallas, interpret)
    return y.reshape(lead + out_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _fake_quant(x, slot):
    y, _ = _fake_quant_fwd(x, slot)
    return y


def _fake_quant_fwd(x, slot):
    x8, d = _cast_f8(x, slot)
    return (x8.astype(jnp.float32) * d).astype(x.dtype), None


def _fake_quant_bwd(slot, _, g):
    return (g,)


_fake_quant.defvjp(_fake_quant_fwd, _fake_quant_bwd)


def fake_quant(x, slot):
    """fp8 round-trip (quantize -> dequantize) with the slot's delayed
    scale and a straight-through gradient — the handoff precision for
    non-dot consumers (the bias+GELU epilogue input, the attention
    score operands inside the flash kernel's bf16 compute, the ring's
    chunk-matmul operands at the shard_map boundary). Records the amax
    in THIS trace, then round-trips through the pure custom_vjp."""
    _record_amax(x, slot)
    return _fake_quant(x, slot)


# ----------------------------------------------------------------------
# Serving: weight-only int8 (per-output-channel scales)
# ----------------------------------------------------------------------


def _weight_leaf(leaf):
    """Weight-only int8 eligibility: float leaves with a contraction
    structure (ndim >= 2) — Dense/attention kernels and embeddings;
    biases, layernorm vectors and scalars stay put."""
    dt = getattr(leaf, "dtype", None)
    return (
        dt is not None
        and jnp.issubdtype(dt, jnp.floating)
        and getattr(leaf, "ndim", 0) >= 2
    )


def quantize_decode_params(params):
    """One-shot weight-only int8: eligible leaves become int8 with a
    per-OUTPUT-channel (last-axis) f32 scale; the rest ride unchanged.
    Returns ``{"q": tree, "s": tree}`` — a plain pytree, so the
    serving programs take it as a call argument and ``adopt_params``
    stays a zero-recompile pointer swap. Selection is structural
    (dtype + ndim), so ``dequantize_decode_params`` inverts it without
    side metadata."""
    def q_leaf(leaf):
        if not _weight_leaf(leaf):
            return leaf
        amax = jnp.max(
            jnp.abs(leaf.astype(jnp.float32)),
            axis=tuple(range(leaf.ndim - 1)),
        )
        scale = jnp.maximum(amax / 127.0, 1e-12).astype(jnp.float32)
        q = jnp.round(leaf.astype(jnp.float32) / scale)
        return jnp.clip(q, -127, 127).astype(jnp.int8)

    def s_leaf(leaf):
        if not _weight_leaf(leaf):
            return jnp.zeros((), jnp.float32)
        amax = jnp.max(
            jnp.abs(leaf.astype(jnp.float32)),
            axis=tuple(range(leaf.ndim - 1)),
        )
        return jnp.maximum(amax / 127.0, 1e-12).astype(jnp.float32)

    return {
        "q": jax.tree_util.tree_map(q_leaf, params),
        "s": jax.tree_util.tree_map(s_leaf, params),
    }


def dequantize_decode_params(qparams, dtype=None):
    """Invert ``quantize_decode_params`` inside the serving program:
    int8 leaves dequantize per channel to ``dtype`` (default f32);
    pass-through leaves return untouched. The int8 copies are what
    lives in HBM — the dequant materializes at use, which is the
    weight-only decode contract (memory-bound matmuls read half the
    bytes)."""
    tgt = dtype or jnp.float32

    def d_leaf(q, s):
        if getattr(q, "dtype", None) == jnp.int8:
            return (q.astype(jnp.float32) * s).astype(tgt)
        return q

    return jax.tree_util.tree_map(d_leaf, qparams["q"], qparams["s"])


def fake_quant_decode_params(params):
    """The ``smp.generate`` twin of the serving int8 path: the same
    per-channel int8 round-trip applied in-program (values identical
    to store-int8 + dequant), so generate/serving outputs stay
    comparable token-for-token under the same knob."""
    q = quantize_decode_params(params)
    return jax.tree_util.tree_map(
        lambda p, qq, ss: (
            (qq.astype(jnp.float32) * ss).astype(p.dtype)
            if getattr(qq, "dtype", None) == jnp.int8 else p
        ),
        params, q["q"], q["s"],
    )


# ----------------------------------------------------------------------
# Serving: int8 paged-KV helpers (per-block-per-head scales)
# ----------------------------------------------------------------------


def kv_pool_dtype(requested):
    import jax.numpy as _jnp

    return _jnp.int8 if kv_quant_mode() == "int8" else requested


def kv_quantize_append(pool_i8, scale, k, blk_flat):
    """One paged append under int8: fold the incoming tokens' per-head
    amax into the touched blocks' scales (scales only GROW), requantize
    the pool under the grown scales (``q_new = round(q_old *
    old/new)`` — exact where the scale didn't move), and quantize the
    new tokens with the post-growth scales.

    Args:
      pool_i8: [nb, bt, H, hd] int8 pool (flattened writes happen by
        the caller).
      scale: [nb, H] f32 per-block-per-head scales.
      k: [N, H, hd] incoming tokens (flattened rows).
      blk_flat: [N] int32 destination block per token.

    Returns (requantized pool_i8, new scale, q_tokens int8 [N, H, hd]).
    """
    tok_amax = jnp.max(jnp.abs(k.astype(jnp.float32)), axis=2)  # [N, H]
    grown = scale.at[blk_flat].max(tok_amax / 127.0)
    new_scale = jnp.maximum(grown, 1e-12)
    ratio = scale / new_scale                                   # <= 1
    requant = jnp.round(
        pool_i8.astype(jnp.float32) * ratio[:, None, :, None]
    ).astype(jnp.int8)
    d = jnp.take(new_scale, blk_flat, axis=0)                   # [N, H]
    q_tok = jnp.clip(
        jnp.round(k.astype(jnp.float32) / d[:, :, None]), -127, 127
    ).astype(jnp.int8)
    return requant, new_scale, q_tok


def kv_dequantize_gather(vals_i8, scale, slot_blocks, dtype):
    """Dequantize gathered KV columns: ``vals_i8`` [B, S, H, hd] int8
    gathered by flat slot, ``slot_blocks`` [B, S] the pool block each
    gathered column came from."""
    d = jnp.take(scale, slot_blocks, axis=0)                    # [B,S,H]
    return (vals_i8.astype(jnp.float32) * d[..., None]).astype(dtype)
